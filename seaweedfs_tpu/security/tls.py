"""Cluster TLS/mTLS for the control+data plane.

The reference wraps every gRPC server and client dial in mTLS loaded from
security.toml's [grpc] sections (weed/security/tls.go:26-60). Here the wire
is HTTPS: one process-wide TLS state is configured from the [tls] table of
security.toml (per-role cert overrides like [tls.volume] mirror the
reference's [grpc.volume]), servers hand their ssl context to TCPSite, and
clients get theirs two ways:

  - urllib users: `configure()` installs a global opener whose HTTPSHandler
    carries the client context, so every existing `urllib.request.urlopen`
    call in the tree is covered without per-call-site plumbing (the Python
    analogue of the reference's single pb.GrpcDial chokepoint);
  - aiohttp users call `client_ssl()` for their TCPConnector.

URL scheme selection rides `scheme()` — when TLS is on, every intra-cluster
URL becomes https. `verify_client = true` turns on mutual auth: the server
requires a peer certificate signed by the same CA.

`generate_certs()` creates a CA + a node cert (SAN: localhost and given
hosts; it serves as both server and client identity) for tests and the
`certs` CLI subcommand.

[tls]
ca = "ca.crt"
cert = "server.crt"
key = "server.key"
verify_client = true     # optional mTLS
"""

from __future__ import annotations

import os
import ssl
import urllib.request


class _TlsState:
    def __init__(self) -> None:
        self.enabled = False
        self.ca: str | None = None
        self.cert: str | None = None
        self.key: str | None = None
        self.verify_client = False
        self.role_overrides: dict[str, dict] = {}
        self._server_ctx: dict[str, ssl.SSLContext] = {}
        self._client_ctx: ssl.SSLContext | None = None


_state = _TlsState()


_installed_opener = False


def configure(data: dict | None) -> None:
    """Install process-wide TLS from a security.toml [tls] table (or clear
    it when absent/empty). Safe to call multiple times; last call wins.

    Raises ValueError for a cert/key table with verify_client but no ca —
    mTLS without a CA to verify against would silently accept anyone."""
    global _state, _installed_opener
    st = _TlsState()
    data = data or {}
    st.cert = data.get("cert") or None
    st.key = data.get("key") or None
    st.ca = data.get("ca") or None
    st.verify_client = bool(data.get("verify_client", False))
    st.role_overrides = {k: v for k, v in data.items() if isinstance(v, dict)}
    st.enabled = bool(st.cert and st.key)
    if st.enabled and st.verify_client and not st.ca:
        raise ValueError(
            "[tls] verify_client = true requires `ca` — without it the "
            "server cannot verify any client certificate")
    _state = st
    if st.enabled:
        ctx = client_ssl()
        opener = urllib.request.build_opener(
            urllib.request.HTTPSHandler(context=ctx))
        urllib.request.install_opener(opener)
        _installed_opener = True
    elif _installed_opener:
        # only undo an opener WE installed — never clobber an embedding
        # application's own opener on a plain-config load
        urllib.request.install_opener(urllib.request.build_opener())
        _installed_opener = False


def enabled() -> bool:
    return _state.enabled


def scheme() -> str:
    """URL scheme for intra-cluster calls."""
    return "https" if _state.enabled else "http"


def _role_paths(role: str | None) -> tuple[str | None, str | None]:
    ov = _state.role_overrides.get(role or "", {})
    return ov.get("cert", _state.cert), ov.get("key", _state.key)


def server_ssl(role: str | None = None) -> ssl.SSLContext | None:
    """Server-side context for aiohttp TCPSite; None when TLS is off."""
    if not _state.enabled:
        return None
    key = role or ""
    ctx = _state._server_ctx.get(key)
    if ctx is None:
        cert, pkey = _role_paths(role)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, pkey)
        if _state.ca:
            ctx.load_verify_locations(_state.ca)
            if _state.verify_client:
                ctx.verify_mode = ssl.CERT_REQUIRED
        _state._server_ctx[key] = ctx
    return ctx


def client_ssl() -> ssl.SSLContext | None:
    """Client-side context (verifies the cluster CA, presents the client
    cert for mTLS); None when TLS is off."""
    if not _state.enabled:
        return None
    if _state._client_ctx is None:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        # hostname verification stays ON: node certs must carry their
        # host/IP in SAN (the `certs` subcommand's -hosts flag does this),
        # and since this context also serves process-global urllib traffic
        # (see configure()), system-CA endpoints keep full verification
        ctx.check_hostname = True
        ctx.load_default_certs()
        if _state.ca:
            ctx.load_verify_locations(_state.ca)
        if _state.cert and _state.key:
            ctx.load_cert_chain(_state.cert, _state.key)
        _state._client_ctx = ctx
    return _state._client_ctx


def generate_certs(out_dir: str, hosts: list[str] | None = None) -> dict:
    """Create ca + server cert/key PEMs under out_dir (the server cert
    doubles as the client identity for mTLS — every cluster node is both).
    Returns the [tls] table dict ready to feed `configure()` or write to
    security.toml."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    hosts = hosts or ["localhost", "127.0.0.1"]
    os.makedirs(out_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def _key():
        return ec.generate_private_key(ec.SECP256R1())

    def _write(name: str, key, cert) -> tuple[str, str]:
        kp = os.path.join(out_dir, f"{name}.key")
        cp = os.path.join(out_dir, f"{name}.crt")
        with open(kp, "wb") as f:
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()))
        with open(cp, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))
        return cp, kp

    ca_key = _key()
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "seaweedfs-tpu-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now)
               .not_valid_after(now + datetime.timedelta(days=3650))
               .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))
    ca_crt, _ = _write("ca", ca_key, ca_cert)

    import ipaddress

    def _alt(h: str):
        try:
            return x509.IPAddress(ipaddress.ip_address(h))
        except ValueError:
            return x509.DNSName(h)

    san = x509.SubjectAlternativeName([_alt(h) for h in hosts])

    def _leaf(cn: str):
        key = _key()
        cert = (x509.CertificateBuilder()
                .subject_name(x509.Name(
                    [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
                .issuer_name(ca_name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now)
                .not_valid_after(now + datetime.timedelta(days=3650))
                .add_extension(san, critical=False)
                .sign(ca_key, hashes.SHA256()))
        return key, cert

    leaf_key, leaf_cert = _leaf("seaweedfs-tpu-node")
    srv_crt, srv_key = _write("server", leaf_key, leaf_cert)
    return {
        "ca": ca_crt,
        "cert": srv_crt,
        "key": srv_key,
        "verify_client": True,
    }
