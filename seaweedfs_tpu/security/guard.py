"""Access guard: IP whitelist + per-role signing keys from security config.

Reference: weed/security/guard.go (white-list check) and the `[jwt.signing]`
/ `[access]` sections of security.toml (command/scaffold/security.toml).
Config is TOML loaded via stdlib tomllib; env vars WEED_JWT_SIGNING_KEY /
WEED_JWT_SIGNING_READ_KEY override, mirroring the reference's viper
WEED_-prefix env override (util/config.go).
"""

from __future__ import annotations

import ipaddress
import os

from seaweedfs_tpu.security.jwt import SigningKey


class Guard:
    def __init__(self, whitelist: list[str] | None = None):
        self.networks: list[ipaddress._BaseNetwork] = []
        self.exact: set[str] = set()
        for item in whitelist or []:
            item = item.strip()
            if not item:
                continue
            try:
                self.networks.append(ipaddress.ip_network(item, strict=False))
            except ValueError:
                self.exact.add(item)

    def __bool__(self) -> bool:
        return bool(self.networks or self.exact)

    def is_allowed(self, remote_ip: str) -> bool:
        if not self:
            return True
        if remote_ip in self.exact:
            return True
        try:
            addr = ipaddress.ip_address(remote_ip)
        except ValueError:
            return False
        return any(addr in net for net in self.networks)


class SecurityConfig:
    """Parsed security.toml: write/read JWT keys for volume + filer, and the
    master/shell IP whitelist."""

    def __init__(self, data: dict | None = None):
        data = data or {}

        def key(section: str) -> SigningKey:
            # TOML [jwt.signing.read] parses to nested dicts — walk the
            # dotted path under the "jwt" table
            sec: dict = data.get("jwt", {})
            for part in section.split("."):
                sec = sec.get(part, {}) if isinstance(sec, dict) else {}
            if not isinstance(sec, dict):
                sec = {}
            return SigningKey(sec.get("key", ""),
                              int(sec.get("expires_after_seconds", 10)))

        self.volume_write = key("signing")
        self.volume_read = key("signing.read")
        self.filer_write = key("filer.signing")
        self.filer_read = key("filer.signing.read")
        self.guard = Guard(data.get("access", {}).get("ui", {}).get(
            "white_list", data.get("access", {}).get("white_list")))
        # [tls] table: installs process-wide HTTPS/mTLS for every server
        # and client in this process (reference: weed/security/tls.go:26-60
        # wraps all gRPC ends the same way from [grpc] sections)
        from seaweedfs_tpu.security import tls
        self.tls = data.get("tls") or {}
        tls.configure(self.tls)

    @classmethod
    def load(cls, path: str | None = None) -> "SecurityConfig":
        data: dict = {}
        candidates = [path] if path else [
            "./security.toml",
            os.path.expanduser("~/.seaweedfs/security.toml"),
            "/etc/seaweedfs/security.toml",
        ]
        for cand in candidates:
            if cand and os.path.exists(cand):
                try:
                    import tomllib
                except ImportError:  # Python < 3.11
                    import tomli as tomllib
                with open(cand, "rb") as f:
                    data = tomllib.load(f)
                break
        cfg = cls(data)
        env_key = os.environ.get("WEED_JWT_SIGNING_KEY")
        if env_key:
            cfg.volume_write = SigningKey(
                env_key, cfg.volume_write.expires_after_seconds)
        env_rkey = os.environ.get("WEED_JWT_SIGNING_READ_KEY")
        if env_rkey:
            cfg.volume_read = SigningKey(
                env_rkey, cfg.volume_read.expires_after_seconds)
        return cfg
