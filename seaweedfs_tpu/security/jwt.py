"""JWT signing for volume writes and filer access.

Reference: weed/security/jwt.go — `GenJwtForVolumeServer` (jwt.go:30) signs a
short-lived HS256 token over the file id; the volume server checks it on
writes (volume_server_handlers_write.go:33) and the filer issues/forwards
tokens per chunk. Implemented on the stdlib (hmac/hashlib/base64) — no
external jwt dependency.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
import time


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    pad = -len(s) % 4
    return base64.urlsafe_b64decode(s + "=" * pad)


class SigningKey:
    """One HS256 key with a token lifetime (0 lifetime = tokens never expire,
    matching the reference's expires_after_seconds=0 behavior)."""

    def __init__(self, key: str | bytes = "", expires_after_seconds: int = 10):
        if isinstance(key, str):
            key = key.encode()
        self.key = key
        self.expires_after_seconds = expires_after_seconds

    def __bool__(self) -> bool:
        return bool(self.key)


def gen_jwt(key: SigningKey, fid: str) -> str:
    """Sign a token authorizing one operation on `fid` (empty fid = filer
    token covering any path)."""
    if not key:
        return ""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"},
                             separators=(",", ":")).encode())
    claims: dict = {"fid": fid}
    if key.expires_after_seconds != 0:
        claims["exp"] = int(time.time()) + key.expires_after_seconds
    payload = _b64(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = _b64(hmac.new(key.key, signing_input, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


class JwtError(Exception):
    pass


def decode_jwt(key: SigningKey, token: str, expected_fid: str | None = None) -> dict:
    """Verify signature + expiry (+ fid claim when expected); returns claims."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        sig = _unb64(sig_b64)
    except (ValueError, binascii.Error):
        raise JwtError("malformed token")
    signing_input = f"{header_b64}.{payload_b64}".encode()
    want = hmac.new(key.key, signing_input, hashlib.sha256).digest()
    if not hmac.compare_digest(want, sig):
        raise JwtError("bad signature")
    try:
        header = json.loads(_unb64(header_b64))
        claims = json.loads(_unb64(payload_b64))
    except (ValueError, UnicodeDecodeError):
        raise JwtError("malformed claims")
    if header.get("alg") != "HS256":
        raise JwtError(f"unsupported alg {header.get('alg')!r}")
    exp = claims.get("exp")
    if exp is not None and time.time() > exp:
        raise JwtError("token expired")
    if expected_fid is not None and claims.get("fid") not in ("", expected_fid):
        raise JwtError("token fid mismatch")
    return claims


def token_from_request(headers, query) -> str:
    """Authorization: Bearer <t> header, else ?jwt= query param (the
    reference accepts both: security/guard.go GetJwt)."""
    auth = headers.get("Authorization", "")
    if auth[:7].lower() == "bearer ":
        return auth[7:]
    return query.get("jwt", "")
