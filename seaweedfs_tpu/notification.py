"""Notification bus: publish filer meta events to an external queue.

Reference: weed/notification/configuration.go + the kafka / aws_sqs /
gcp_pub_sub / gocdk_pub_sub / log backends.  External brokers aren't
available in this environment, so the concrete backends are a JSONL log
queue and an in-memory queue (the reference's `log` backend analogue),
behind the same registry seam so kafka-style backends can slot in.
"""

from __future__ import annotations

import json
import threading
from collections import deque


class MessageQueue:
    name = "abstract"

    def send(self, key: str, message: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LogQueue(MessageQueue):
    """Append events to a JSONL file (reference: notification `log`
    backend)."""

    name = "log"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")

    def send(self, key: str, message: dict) -> None:
        line = json.dumps({"key": key, **message}, separators=(",", ":"))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.close()


class MemoryQueue(MessageQueue):
    name = "memory"

    def __init__(self, maxlen: int = 65536):
        self.messages: deque = deque(maxlen=maxlen)

    def send(self, key: str, message: dict) -> None:
        self.messages.append((key, message))


QUEUES = {"log": LogQueue, "memory": MemoryQueue}


def make_queue(kind: str, **options) -> MessageQueue:
    try:
        return QUEUES[kind](**options)
    except KeyError:
        raise ValueError(f"unknown notification queue {kind!r} "
                         f"(have {sorted(QUEUES)}; kafka/sqs/pubsub need "
                         f"their client libraries)")
