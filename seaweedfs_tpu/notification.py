"""Notification bus: publish filer meta events to an external queue.

Reference: weed/notification/configuration.go + the kafka / aws_sqs /
gcp_pub_sub / gocdk_pub_sub / log backends.  External brokers aren't
available in this environment, so the concrete backends are a JSONL log
queue and an in-memory queue (the reference's `log` backend analogue),
behind the same registry seam so kafka-style backends can slot in.
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque

log = logging.getLogger("notification")


class MessageQueue:
    name = "abstract"

    def send(self, key: str, message: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LogQueue(MessageQueue):
    """Append events to a JSONL file (reference: notification `log`
    backend)."""

    name = "log"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")

    def send(self, key: str, message: dict) -> None:
        line = json.dumps({"key": key, **message}, separators=(",", ":"))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.close()


class MemoryQueue(MessageQueue):
    name = "memory"

    def __init__(self, maxlen: int = 65536):
        self.messages: deque = deque(maxlen=maxlen)
        self.sent = 0  # total ever sent: lets consumers detect eviction
        # keeps (messages, sent) consistent for consumers that snapshot
        # both (replicate_daemon.MemorySource): append + increment is not
        # atomic, and a consumer catching the gap mis-offsets every event
        # after an eviction
        self.lock = threading.Lock()

    def send(self, key: str, message: dict) -> None:
        with self.lock:
            self.messages.append((key, message))
            self.sent += 1


class WebhookQueue(MessageQueue):
    """POST each event to an HTTP endpoint (the gocdk/webhook-style
    backend) — SDK-free, works against any collector, retried with
    backoff like the replication sinks.

    Delivery runs on an internal worker thread behind a bounded queue:
    `send()` is called synchronously from the filer's event loop, so a
    slow/down collector must never block file operations. Overflow drops
    the oldest events (logged) — same at-most-once posture as the
    reference's fire-and-forget notification publishers."""

    name = "webhook"

    def __init__(self, url: str, timeout: float = 10.0,
                 max_pending: int = 10000):
        import logging
        import queue as _queue
        self.url = url
        self.timeout = timeout
        self._log = logging.getLogger("notification.webhook")
        self._q: _queue.Queue = _queue.Queue(maxsize=max_pending)
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name="webhook-notify")
        self._worker.start()

    def send(self, key: str, message: dict) -> None:
        item = json.dumps({"key": key, **message},
                          separators=(",", ":")).encode()
        try:
            self._q.put_nowait(item)
        except Exception:
            try:  # full: drop the oldest so fresh events keep flowing
                self._q.get_nowait()
                self._q.put_nowait(item)
                self._log.warning("webhook queue full; dropped oldest event")
            except Exception:
                pass

    def _drain(self) -> None:
        import urllib.request

        from seaweedfs_tpu.replication.sink import retry
        while not self._stop.is_set():
            try:
                body = self._q.get(timeout=0.5)
            except Exception:
                continue
            req = urllib.request.Request(
                self.url, data=body, method="POST",
                headers={"Content-Type": "application/json"})

            def post():
                with urllib.request.urlopen(req, timeout=self.timeout):
                    pass
            try:
                retry(post, attempts=3)
            except Exception as e:
                self._log.warning("webhook delivery failed, event lost: %s",
                                  e)

    def close(self) -> None:
        deadline = 5.0
        import time as _time
        end = _time.monotonic() + deadline
        while not self._q.empty() and _time.monotonic() < end:
            _time.sleep(0.05)
        self._stop.set()


class KafkaQueue(MessageQueue):
    """Kafka producer backend (reference: weed/notification/kafka);
    registers only when a kafka client package imports."""

    name = "kafka"

    def __init__(self, hosts: str = "127.0.0.1:9092", topic: str = "seaweedfs"):
        from kafka import KafkaProducer
        self.topic = topic
        self._producer = KafkaProducer(
            bootstrap_servers=[h.strip() for h in hosts.split(",")],
            value_serializer=lambda m: json.dumps(
                m, separators=(",", ":")).encode())

    def send(self, key: str, message: dict) -> None:
        self._producer.send(self.topic, key=key.encode(),
                            value={"key": key, **message})

    def close(self) -> None:
        self._producer.flush()
        self._producer.close()


class SqsQueue(MessageQueue):
    """AWS SQS backend (reference: weed/notification/aws_sqs); registers
    only when boto3 imports."""

    name = "aws_sqs"

    def __init__(self, queue_url: str, region: str = "us-east-1"):
        import boto3
        self.queue_url = queue_url
        self._sqs = boto3.client("sqs", region_name=region)

    def send(self, key: str, message: dict) -> None:
        self._sqs.send_message(
            QueueUrl=self.queue_url,
            MessageBody=json.dumps({"key": key, **message},
                                   separators=(",", ":")))


class GooglePubSubQueue(MessageQueue):
    """GCP Pub/Sub backend (reference: weed/notification/google_pub_sub);
    registers only when google-cloud-pubsub imports."""

    name = "google_pub_sub"

    def __init__(self, project_id: str, topic: str = "seaweedfs"):
        from google.cloud import pubsub_v1
        self._publisher = pubsub_v1.PublisherClient()
        self._topic = self._publisher.topic_path(project_id, topic)

    def send(self, key: str, message: dict) -> None:
        future = self._publisher.publish(
            self._topic,
            json.dumps({"key": key, **message},
                       separators=(",", ":")).encode(),
            key=key)
        # publish() batches and resolves later: surface failures instead
        # of dropping events silently
        future.add_done_callback(
            lambda f: f.exception() and log.warning(
                "pubsub event lost for %s: %s", key, f.exception()))

    def close(self) -> None:
        # flush the batched tail before shutdown (KafkaQueue parity)
        self._publisher.stop()


QUEUES = {"log": LogQueue, "memory": MemoryQueue, "webhook": WebhookQueue}

# SDK-gated backends, mirroring the reference's build-tag registration
try:
    import kafka  # noqa: F401
    QUEUES["kafka"] = KafkaQueue
except ImportError:
    pass
try:
    import boto3  # noqa: F401
    QUEUES["aws_sqs"] = SqsQueue
except ImportError:
    pass
try:
    from google.cloud import pubsub_v1  # noqa: F401
    QUEUES["google_pub_sub"] = GooglePubSubQueue
except ImportError:
    pass


def make_queue(kind: str, **options) -> MessageQueue:
    try:
        return QUEUES[kind](**options)
    except KeyError:
        raise ValueError(f"unknown notification queue {kind!r} "
                         f"(have {sorted(QUEUES)}; kafka/sqs/pubsub need "
                         f"their client libraries)")
