"""Device-mesh parallel erasure coding.

SeaweedFS scales EC by spreading the 14 shard *files* of each volume across
volume servers (weed/shell/command_ec_encode.go:164-270 spreadEcShards +
balancedEcDistribution). The TPU-native analogue has three axes:

- **column parallelism** ("sequence parallel" of this system): the byte
  columns of one stripe matrix [k, n] shard over devices; parity is
  column-local so encode needs NO collectives — each chip crunches its slice.
- **unit parallelism** (the fleet-encode shape): a batch of independent
  [k, B] column units — interleaved from many volumes by the conversion
  pipeline (ops/fleet_convert.py) — shards over devices on the unit axis.
  Parity is unit-local, so this too needs NO collectives, and unlike column
  sharding there is no per-chip tile-width loss: every chip runs the fused
  kernel at its preferred tile on whole units.  `FleetUnitEncoder` keeps
  in/out shardings matched call-to-call so device-resident outputs never
  reshard between unit batches, and donates the input buffer on real chips
  so XLA reuses it instead of copying.
- **volume/shard placement** ("data parallel" + all-to-all): a batch of
  volumes [V, k, n] shards over devices on V; after local encode, one
  `all_to_all` over ICI re-distributes so device d holds shard-group d of
  *every* volume — the shard-spread step of ec.encode, but riding ICI
  instead of 14 gRPC copies.

Per-device compute dispatches through ONE body seam (`_ApplyKernel`):
the fused Pallas kernel on real TPU chips (ops/pallas_gf — the 336 GB/s
r04 path), the XLA bit-sliced matmul everywhere else (CPU test meshes,
interpreters).  Before round 6 the mesh paths always used the XLA body,
which is why `ec_encode_rs10_4_mesh` trailed the single-chip Pallas
number even before any sharding overhead.

Everything is `shard_map` over a `jax.sharding.Mesh`, so it runs identically
on a real multi-chip slice and on the virtual CPU mesh used in tests.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax: not yet re-exported at top level
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seaweedfs_tpu.ops import gf, gfmat_jax


def _book_h2d(nbytes: float, secs: float,
              kernel: str = "encode_parity") -> None:
    """Book a mesh place() H2D into the kernel profile.  The pre-placed
    paths bypass ops/dispatch's single-dispatch seam (which deliberately
    skips re-booking a placed batch), so without this the device-link
    totals — and the h2d roofline row — understate fleet traffic."""
    from seaweedfs_tpu.stats.profile import KERNELS
    KERNELS.record(kernel, "device", calls=0,
                   h2d_s=secs, h2d_bytes=nbytes)


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, ...] = ("data",),
              shape: tuple[int, ...] | None = None) -> Mesh:
    """Build a Mesh over the first n_devices (default: all devices, or
    prod(shape) when an explicit shape is given)."""
    devs = jax.devices()
    if n_devices is None and shape is not None:
        n_devices = int(np.prod(shape))
    if n_devices is not None:
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, axis_names)


def resolve_kernel(kernel: str = "auto") -> str:
    """Per-device compute body: the fused Pallas kernel only on real TPU
    chips (under the CPU interpreter it would benchmark the emulator);
    the XLA bit-sliced path — byte-identical by construction — elsewhere."""
    if kernel == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return kernel


class _ApplyKernel:
    """The per-device GF(2^8) matrix-apply seam of the mesh encoders.

    `lift(C)` pre-lifts a GF matrix to the bit-matrix layout its body
    expects (bit-major for XLA, plane-major + sublane-padded for Pallas);
    `body(bm, x2)` / `batch_body(bm, x3)` apply it to a local [k, n] /
    [U, k, n] block inside shard_map.  Both bodies are un-jitted — they
    inline into the enclosing jit(shard_map) — and both tolerate
    non-tile-aligned column counts (the Pallas body pads internally)."""

    def __init__(self, kernel: str = "auto", tile: int | None = None):
        self.kind = resolve_kernel(kernel)
        if self.kind == "pallas":
            from seaweedfs_tpu.ops import pallas_gf
            self._pg = pallas_gf
            self.tile = pallas_gf.resolved_tile(tile)
        else:
            self._pg = None
            self.tile = 0

    def lift(self, C: np.ndarray) -> jax.Array:
        if self._pg is not None:
            kpad = self._kpad(C.shape[1])
            return jnp.asarray(
                self._pg.gf_matrix_to_bitmatrix_planemajor(C, kpad),
                dtype=jnp.int8)
        return jnp.asarray(gf.gf_matrix_to_bitmatrix(C), dtype=jnp.int8)

    def _kpad(self, k: int) -> int:
        pp = self._pg.PLANE_PAD
        return max(pp, -(-k // pp) * pp)

    def body(self, bm: jax.Array, x: jax.Array) -> jax.Array:
        if self._pg is None:
            return gfmat_jax.bitsliced_apply_body(bm, x)
        k, n = x.shape
        m = bm.shape[0] // 8
        pad = (-n) % self.tile
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
        out = self._pg._gf_apply(bm, x, k, m, self._kpad(k), self.tile,
                                 False)
        return out[:, :n] if pad else out

    def batch_body(self, bm: jax.Array, x: jax.Array) -> jax.Array:
        if self._pg is None:
            return gfmat_jax.bitsliced_apply_batch_body(bm, x)
        U, k, n = x.shape
        m = bm.shape[0] // 8
        pad = (-n) % self.tile
        if pad:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
        out = self._pg._gf_apply_batch(bm, x, k, m, self._kpad(k),
                                       self.tile, False)
        return out[:, :, :n] if pad else out


def _donate_argnums() -> tuple[int, ...]:
    """Donate the data operand on real chips (XLA aliases the buffer, the
    copy disappears); CPU backends don't implement donation and would
    just log a warning per call."""
    return (1,) if jax.default_backend() == "tpu" else ()


class ShardedRSEncoder:
    """RS(k, m) encode/rebuild over a device mesh.

    `col_axis` shards byte columns; optional `vol_axis` shards a leading
    volume-batch dimension for `encode_batch_place`. The jitted shard_map
    callables are built once here — per-call construction would make jax
    retrace and XLA recompile on every stripe.
    """

    def __init__(self, code, mesh: Mesh, col_axis: str = "data",
                 vol_axis: str | None = None, kernel: str = "auto",
                 tile: int | None = None):
        self.code = code
        self.k, self.m, self.n_shards = code.k, code.m, code.n
        self.mesh = mesh
        self.col_axis = col_axis
        self.vol_axis = vol_axis
        self.kernel = _ApplyKernel(kernel, tile)
        self.parity_bits = self.kernel.lift(code.parity_matrix)

        apply_body = self.kernel.body

        self._encode = jax.jit(shard_map(
            lambda bm, x: jnp.concatenate([x, apply_body(bm, x)], axis=0),
            mesh=mesh, in_specs=(P(), P(None, col_axis)),
            out_specs=P(None, col_axis)))

        # decode shares one compiled fn across survivor patterns: the
        # pattern only changes `bm`, which is a plain array argument.
        self._apply_cols = jax.jit(shard_map(
            apply_body,
            mesh=mesh, in_specs=(P(), P(None, col_axis)),
            out_specs=P(None, col_axis)))

        self._placement_groups: int | None = None
        if vol_axis is not None:
            D = mesh.shape[vol_axis]
            S = -(-self.n_shards // D) * D
            self._placement_groups = S
            pad_rows = S - self.n_shards
            batch_body = self.kernel.batch_body

            def _enc_place(bm, vols):  # vols: [Vl, k, nl]
                # ONE batched kernel launch for all local volumes (the
                # fused Pallas grid on TPU) — half the r05 batch4
                # regression was a vmap of the slower XLA body here
                par = batch_body(bm, vols)
                shards = jnp.concatenate([vols, par], axis=1)  # [Vl, k+m, nl]
                if D == 1:
                    # degenerate placement (1-way vol axis): every shard
                    # group already lives here, and the row pad +
                    # all_to_all below would be pure whole-batch HBM
                    # copies — the other half of the r05 regression
                    return shards
                if pad_rows:
                    shards = jnp.pad(shards, ((0, 0), (0, pad_rows), (0, 0)))
                # all_to_all over the volume axis: split shard rows into D
                # groups, gather all volumes -> each device holds one
                # shard-group of every volume
                return jax.lax.all_to_all(
                    shards, vol_axis, split_axis=1, concat_axis=0, tiled=True)

            # donated volume batch: the concat+all_to_all reuses the input
            # buffer instead of holding both alive (fleet batches are
            # ~160MB per depth step on the production config)
            self._encode_place = jax.jit(shard_map(
                _enc_place,
                mesh=mesh, in_specs=(P(), P(vol_axis, None, col_axis)),
                out_specs=P(None, vol_axis, col_axis)),
                donate_argnums=_donate_argnums())

    # -- column-parallel single volume ---------------------------------

    def encode(self, data: jax.Array) -> jax.Array:
        """[k, n] -> [k+m, n]; columns sharded over `col_axis`, no collectives."""
        return self._encode(self.parity_bits, data)

    def encode_parity(self, data: jax.Array) -> jax.Array:
        """[k, n] -> [m, n] parity, column-sharded; pads n up to a
        device-count multiple internally (shard_map needs even splits)."""
        k, n = data.shape
        D = self.mesh.shape[self.col_axis]
        pad = (-n) % D
        if pad:
            data = jnp.pad(data, ((0, 0), (0, pad)))
        out = self._apply_cols(self.parity_bits, data)
        return out[:, :n] if pad else out

    def place_columns(self, arr) -> jax.Array:
        """H2D an array with columns already sharded over `col_axis`, so
        the first encode doesn't pay a gather+reshard: each device pulls
        only its slice from the host buffer.  This is the in_sharding
        `encode`/`encode_parity` expect — committed here, never reshard."""
        t0 = time.perf_counter()
        out = jax.device_put(
            arr, NamedSharding(self.mesh, P(None, self.col_axis)))
        _book_h2d(getattr(arr, "nbytes", 0), time.perf_counter() - t0)
        return out

    def reconstruct(self, shards: dict[int, jax.Array],
                    wanted: list[int] | None = None) -> dict[int, jax.Array]:
        """Column-parallel rebuild of missing shards from >= k survivors.
        Pads columns to a device-count multiple like encode_parity
        (shard_map needs even splits)."""
        present = sorted(shards)
        if wanted is None:
            wanted = [i for i in range(self.n_shards) if i not in shards]
        if not wanted:
            return {}
        D = self.code.decode_matrix(present, wanted)
        dbits = self.kernel.lift(D)
        stack = jnp.stack([shards[i] for i in present[: self.k]], axis=0)
        n = stack.shape[1]
        ndev = self.mesh.shape[self.col_axis]
        pad = (-n) % ndev
        if pad:
            stack = jnp.pad(stack, ((0, 0), (0, pad)))
        out = self._apply_cols(dbits, stack)
        if pad:
            out = out[:, :n]
        return {w: out[i] for i, w in enumerate(wanted)}

    # -- batched volumes + shard placement over ICI --------------------

    def placement_groups(self) -> int:
        """Shard rows are padded so every device gets an equal group."""
        assert self._placement_groups is not None, "construct with vol_axis="
        return self._placement_groups

    def encode_batch_place(self, volumes: jax.Array) -> jax.Array:
        """[V, k, n] -> [V, S_pad, n] where the shard dimension is sharded
        over `vol_axis`: device d ends up holding shard rows
        [d*S_pad/D, (d+1)*S_pad/D) of EVERY volume (ec.encode's spreadEcShards
        as one ICI all_to_all instead of 14 gRPC file copies)."""
        assert self.vol_axis is not None, "construct with vol_axis= for batching"
        return self._encode_place(self.parity_bits, volumes)


class FleetUnitEncoder:
    """Unit-parallel fleet encode: the mesh shape of the multi-volume
    conversion pipeline (ops/fleet_convert.py).

    A batch of U independent [k, B] column units — interleaved from N
    volumes — shards over the mesh on the unit axis.  Each chip encodes
    its U/D units wholly (parity is unit-local): NO collectives, no
    cross-chip bytes, so 8 chips process 8x the units of 1 at equal unit
    size.  The jitted shard_map is built once; its in/out shardings are
    both P(unit_axis), so a device-resident output (or a staging buffer
    placed by `place`) feeds the next call without any reshard, and on
    real chips the input batch is DONATED — XLA writes parity into
    recycled memory instead of growing the footprint per in-flight batch.

    D2H is per-device: `unit_shards(parity)` yields each device's local
    [U/D, m, B] block the moment it is fetched, so the conversion drain
    streams shards to their writers as they come off the device rather
    than after a full gather.
    """

    def __init__(self, code, mesh: Mesh | None = None,
                 unit_axis: str = "unit", kernel: str = "auto",
                 tile: int | None = None):
        if mesh is None:
            mesh = make_mesh(axis_names=(unit_axis,))
        self.code = code
        self.k, self.m = code.k, code.m
        self.mesh = mesh
        self.unit_axis = unit_axis
        self.n_devices = mesh.shape[unit_axis]
        self.kernel = _ApplyKernel(kernel, tile)
        self.parity_bits = self.kernel.lift(code.parity_matrix)
        self.in_sharding = NamedSharding(mesh, P(unit_axis))
        batch_body = self.kernel.batch_body
        self._encode = jax.jit(shard_map(
            batch_body,
            mesh=mesh, in_specs=(P(), P(unit_axis)),
            out_specs=P(unit_axis)),
            donate_argnums=_donate_argnums())

    def unit_slots(self, min_units: int) -> int:
        """Round a desired in-flight unit count up to an even per-device
        split (shard_map needs one)."""
        D = self.n_devices
        return max(D, -(-min_units // D) * D)

    def place(self, host_units: np.ndarray) -> jax.Array:
        """H2D a [U, k, B] host batch with units sharded over the mesh:
        each device pulls exactly its U/D units from the host buffer, so
        no later reshard (this IS the encode's in_sharding)."""
        assert host_units.shape[0] % self.n_devices == 0, \
            (host_units.shape, self.n_devices)
        t0 = time.perf_counter()
        out = jax.device_put(host_units, self.in_sharding)
        _book_h2d(host_units.nbytes, time.perf_counter() - t0,
                  kernel="fleet_encode")
        return out

    def encode_parity_batch(self, units: jax.Array) -> jax.Array:
        """[U, k, B] (device-resident, unit-sharded) -> [U, m, B] parity,
        unit-sharded with the SAME spec — device-resident outputs chain
        into whatever consumes them without moving."""
        return self._encode(self.parity_bits, units)

    def unit_shards(self, parity: jax.Array):
        """Yield (u_start, u_stop, np.ndarray) per addressable device
        shard, in unit order: the streaming D2H of the conversion drain.
        Plain single-device arrays yield one chunk."""
        shards = getattr(parity, "addressable_shards", None)
        if not shards:
            yield 0, int(parity.shape[0]), np.asarray(parity)
            return
        for sh in sorted(shards, key=lambda s: s.index[0].start or 0):
            idx = sh.index[0]
            start = idx.start or 0
            data = np.asarray(sh.data)
            yield int(start), int(start) + data.shape[0], data


def shard_columns(mesh: Mesh, arr: jax.Array, axis: str = "data") -> jax.Array:
    """Place [k, n] with columns sharded over `axis`."""
    return jax.device_put(arr, NamedSharding(mesh, P(None, axis)))
