"""Device-mesh parallel erasure coding.

SeaweedFS scales EC by spreading the 14 shard *files* of each volume across
volume servers (weed/shell/command_ec_encode.go:164-270 spreadEcShards +
balancedEcDistribution). The TPU-native analogue has two axes:

- **column parallelism** ("sequence parallel" of this system): the byte
  columns of one stripe matrix [k, n] shard over devices; parity is
  column-local so encode needs NO collectives — each chip crunches its slice.
- **volume/shard placement** ("data parallel" + all-to-all): a batch of
  volumes [V, k, n] shards over devices on V; after local encode, one
  `all_to_all` over ICI re-distributes so device d holds shard-group d of
  *every* volume — the shard-spread step of ec.encode, but riding ICI
  instead of 14 gRPC copies.

Everything is `shard_map` over a `jax.sharding.Mesh`, so it runs identically
on a real multi-chip slice and on the virtual CPU mesh used in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax: not yet re-exported at top level
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seaweedfs_tpu.ops import gf, gfmat_jax


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, ...] = ("data",),
              shape: tuple[int, ...] | None = None) -> Mesh:
    """Build a Mesh over the first n_devices (default: all devices, or
    prod(shape) when an explicit shape is given)."""
    devs = jax.devices()
    if n_devices is None and shape is not None:
        n_devices = int(np.prod(shape))
    if n_devices is not None:
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, axis_names)


class ShardedRSEncoder:
    """RS(k, m) encode/rebuild over a device mesh.

    `col_axis` shards byte columns; optional `vol_axis` shards a leading
    volume-batch dimension for `encode_batch_place`. The jitted shard_map
    callables are built once here — per-call construction would make jax
    retrace and XLA recompile on every stripe.
    """

    def __init__(self, code, mesh: Mesh, col_axis: str = "data",
                 vol_axis: str | None = None):
        self.code = code
        self.k, self.m, self.n_shards = code.k, code.m, code.n
        self.mesh = mesh
        self.col_axis = col_axis
        self.vol_axis = vol_axis
        self.parity_bits = jnp.asarray(
            gf.gf_matrix_to_bitmatrix(code.parity_matrix), dtype=jnp.int8)

        apply_body = gfmat_jax.bitsliced_apply_body

        self._encode = jax.jit(shard_map(
            lambda bm, x: jnp.concatenate([x, apply_body(bm, x)], axis=0),
            mesh=mesh, in_specs=(P(), P(None, col_axis)),
            out_specs=P(None, col_axis)))

        # decode shares one compiled fn across survivor patterns: the
        # pattern only changes `bm`, which is a plain array argument.
        self._apply_cols = jax.jit(shard_map(
            apply_body,
            mesh=mesh, in_specs=(P(), P(None, col_axis)),
            out_specs=P(None, col_axis)))

        self._placement_groups: int | None = None
        if vol_axis is not None:
            D = mesh.shape[vol_axis]
            S = -(-self.n_shards // D) * D
            self._placement_groups = S
            pad_rows = S - self.n_shards

            def _enc_place(bm, vols):  # vols: [Vl, k, nl]
                par = jax.vmap(lambda v: apply_body(bm, v))(vols)
                shards = jnp.concatenate([vols, par], axis=1)  # [Vl, k+m, nl]
                if pad_rows:
                    shards = jnp.pad(shards, ((0, 0), (0, pad_rows), (0, 0)))
                # all_to_all over the volume axis: split shard rows into D
                # groups, gather all volumes -> each device holds one
                # shard-group of every volume
                return jax.lax.all_to_all(
                    shards, vol_axis, split_axis=1, concat_axis=0, tiled=True)

            self._encode_place = jax.jit(shard_map(
                _enc_place,
                mesh=mesh, in_specs=(P(), P(vol_axis, None, col_axis)),
                out_specs=P(None, vol_axis, col_axis)))

    # -- column-parallel single volume ---------------------------------

    def encode(self, data: jax.Array) -> jax.Array:
        """[k, n] -> [k+m, n]; columns sharded over `col_axis`, no collectives."""
        return self._encode(self.parity_bits, data)

    def encode_parity(self, data: jax.Array) -> jax.Array:
        """[k, n] -> [m, n] parity, column-sharded; pads n up to a
        device-count multiple internally (shard_map needs even splits)."""
        k, n = data.shape
        D = self.mesh.shape[self.col_axis]
        pad = (-n) % D
        if pad:
            data = jnp.pad(data, ((0, 0), (0, pad)))
        out = self._apply_cols(self.parity_bits, data)
        return out[:, :n] if pad else out

    def reconstruct(self, shards: dict[int, jax.Array],
                    wanted: list[int] | None = None) -> dict[int, jax.Array]:
        """Column-parallel rebuild of missing shards from >= k survivors.
        Pads columns to a device-count multiple like encode_parity
        (shard_map needs even splits)."""
        present = sorted(shards)
        if wanted is None:
            wanted = [i for i in range(self.n_shards) if i not in shards]
        if not wanted:
            return {}
        D = self.code.decode_matrix(present, wanted)
        dbits = jnp.asarray(gf.gf_matrix_to_bitmatrix(D), dtype=jnp.int8)
        stack = jnp.stack([shards[i] for i in present[: self.k]], axis=0)
        n = stack.shape[1]
        ndev = self.mesh.shape[self.col_axis]
        pad = (-n) % ndev
        if pad:
            stack = jnp.pad(stack, ((0, 0), (0, pad)))
        out = self._apply_cols(dbits, stack)
        if pad:
            out = out[:, :n]
        return {w: out[i] for i, w in enumerate(wanted)}

    # -- batched volumes + shard placement over ICI --------------------

    def placement_groups(self) -> int:
        """Shard rows are padded so every device gets an equal group."""
        assert self._placement_groups is not None, "construct with vol_axis="
        return self._placement_groups

    def encode_batch_place(self, volumes: jax.Array) -> jax.Array:
        """[V, k, n] -> [V, S_pad, n] where the shard dimension is sharded
        over `vol_axis`: device d ends up holding shard rows
        [d*S_pad/D, (d+1)*S_pad/D) of EVERY volume (ec.encode's spreadEcShards
        as one ICI all_to_all instead of 14 gRPC file copies)."""
        assert self.vol_axis is not None, "construct with vol_axis= for batching"
        return self._encode_place(self.parity_bits, volumes)


def shard_columns(mesh: Mesh, arr: jax.Array, axis: str = "data") -> jax.Array:
    """Place [k, n] with columns sharded over `axis`."""
    return jax.device_put(arr, NamedSharding(mesh, P(None, axis)))
