"""GF(2^8) arithmetic on the host (numpy).

The slow-but-correct reference implementation of the Galois field used by the
Reed-Solomon codec, plus the matrix machinery (inversion, sub-matrix selection)
needed to build decode matrices. The TPU codec (`ops.gfmat_jax`,
`ops.pallas_gf`) is property-tested against this module.

Field: GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11D) and
generator 2 — the same field as the reference's reedsolomon dependency
(reference: weed/storage/erasure_coding/ec_encoder.go:77 uses
klauspost/reedsolomon, which inherits Backblaze's 0x11D tables), so shard
bytes are drop-in compatible.
"""

from __future__ import annotations

import numpy as np

POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
FIELD = 256
ORDER = 255  # multiplicative group order


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)  # doubled to skip the mod in mul
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    for i in range(ORDER, 512):
        exp[i] = exp[i - ORDER]
    log[0] = -1  # sentinel; callers must special-case 0
    return exp, log


GF_EXP, GF_LOG = _build_tables()

def _build_mul_table() -> np.ndarray:
    """Dense 256x256 multiplication table: handy for vectorised host-side
    encode and for building bit-matrices."""
    mul = np.zeros((256, 256), dtype=np.uint8)
    nz = np.arange(1, 256)
    mul[1:, 1:] = GF_EXP[(GF_LOG[nz][:, None] + GF_LOG[nz][None, :]) % ORDER]
    return mul


GF_MUL_TABLE = _build_mul_table()


def gf_mul(a: int, b: int) -> int:
    return int(GF_MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("division by zero in GF(2^8)")
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] - GF_LOG[b]) % ORDER])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("0 has no inverse in GF(2^8)")
    return int(GF_EXP[(ORDER - GF_LOG[a]) % ORDER])


def gf_pow(a: int, n: int) -> int:
    """a**n in GF(2^8). By convention 0**0 == 1 (matches the reference's
    Vandermonde construction)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % ORDER])


def gf_mul_vec(a: int, x: np.ndarray) -> np.ndarray:
    """Multiply every byte of `x` by the constant `a`."""
    return GF_MUL_TABLE[a][x]


def gf_matmul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8). A: [m,k] uint8, B: [k,n] uint8 -> [m,n].

    Slow reference path — used for building matrices and for property tests,
    not the data plane.
    """
    A = np.asarray(A, dtype=np.uint8)
    B = np.asarray(B, dtype=np.uint8)
    m, k = A.shape
    k2, n = B.shape
    assert k == k2, (A.shape, B.shape)
    out = np.zeros((m, n), dtype=np.uint8)
    for j in range(k):
        # out ^= A[:, j] * B[j, :]
        out ^= GF_MUL_TABLE[A[:, j][:, None], B[j][None, :]]
    return out


def gf_mat_inv(A: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(2^8) by Gauss-Jordan elimination.

    Raises ValueError if the matrix is singular.
    """
    A = np.asarray(A, dtype=np.uint8)
    n = A.shape[0]
    assert A.shape == (n, n)
    aug = np.concatenate([A.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        # pivot
        pivot = -1
        for r in range(col, n):
            if aug[r, col] != 0:
                pivot = r
                break
        if pivot < 0:
            raise ValueError("singular matrix over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        # scale pivot row to 1
        inv = gf_inv(int(aug[col, col]))
        aug[col] = GF_MUL_TABLE[inv][aug[col]]
        # eliminate other rows
        for r in range(n):
            if r != col and aug[r, col] != 0:
                aug[r] ^= GF_MUL_TABLE[int(aug[r, col])][aug[col]]
    return aug[:, n:].copy()


def gf_rref(A: np.ndarray) -> tuple[np.ndarray, list[int]]:
    """Reduced row-echelon form over GF(2^8) -> (R, pivot_columns).

    Non-destructive; the pivot column list doubles as the rank."""
    R = np.array(A, dtype=np.uint8, copy=True)
    rows, cols = R.shape
    pivots: list[int] = []
    r = 0
    for c in range(cols):
        if r >= rows:
            break
        pivot = -1
        for i in range(r, rows):
            if R[i, c] != 0:
                pivot = i
                break
        if pivot < 0:
            continue
        if pivot != r:
            R[[r, pivot]] = R[[pivot, r]]
        R[r] = GF_MUL_TABLE[gf_inv(int(R[r, c]))][R[r]]
        for i in range(rows):
            if i != r and R[i, c] != 0:
                R[i] ^= GF_MUL_TABLE[int(R[i, c])][R[r]]
        pivots.append(c)
        r += 1
    return R, pivots


def gf_rank(A: np.ndarray) -> int:
    return len(gf_rref(np.asarray(A, dtype=np.uint8))[1])


def gf_solve(A: np.ndarray, B: np.ndarray) -> np.ndarray | None:
    """Solve A @ X = B over GF(2^8) for X; None when inconsistent.

    A: [r, c], B: [r, w] -> X: [c, w].  Under-determined systems return
    the particular solution with every free variable zero — the codec
    layer uses this to express wanted shard rows as combinations of an
    arbitrary (possibly non-square, possibly redundant) survivor row
    set, which a plain matrix inverse cannot do for non-MDS codes like
    LRC."""
    A = np.asarray(A, dtype=np.uint8)
    B = np.asarray(B, dtype=np.uint8)
    r, c = A.shape
    assert B.shape[0] == r, (A.shape, B.shape)
    aug = np.concatenate([A, B], axis=1)
    R, pivots = gf_rref(aug)
    # a pivot landing in the B block means B has a row outside A's span
    if any(p >= c for p in pivots):
        return None
    X = np.zeros((c, B.shape[1]), dtype=np.uint8)
    for row, p in enumerate(pivots):
        X[p] = R[row, c:]
    return X


def gf_mul_bitmatrix(c: int) -> np.ndarray:
    """The GF(2) 8x8 bit-matrix of 'multiply by constant c'.

    GF(2^8) is an 8-dimensional vector space over GF(2) and multiplication by
    a constant is linear, so y = c*x satisfies bits(y) = M_c @ bits(x) mod 2.
    Column s of M_c is bits(c * 2^s); bit r of a byte b is (b >> r) & 1.

    This is the seed of the whole TPU codec: a [m,k] GF(2^8) coding matrix
    expands to a [8m,8k] 0/1 matrix and encode becomes an integer matmul
    (MXU) followed by parity (&1).
    """
    M = np.zeros((8, 8), dtype=np.uint8)
    for s in range(8):
        prod = gf_mul(c, 1 << s)
        for r in range(8):
            M[r, s] = (prod >> r) & 1
    return M


def gf_matrix_to_bitmatrix(C: np.ndarray) -> np.ndarray:
    """Expand a [m,k] GF(2^8) matrix to its [8m,8k] GF(2) bit-matrix.

    Row 8i+r of the result computes bit r of output shard i; column 8j+s
    corresponds to bit s of input shard j.
    """
    C = np.asarray(C, dtype=np.uint8)
    m, k = C.shape
    out = np.zeros((8 * m, 8 * k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = gf_mul_bitmatrix(int(C[i, j]))
    return out
