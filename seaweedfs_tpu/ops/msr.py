"""PM-MSR(k, d): product-matrix minimum-storage regenerating codes.

RS repairs one lost shard by shipping k full shards over the wire.
Regenerating codes (Dimakis et al.; construction from Rashmi, Shah &
Kumar, arXiv:1005.4178 / PAPERS.md 1412.3022) hit the cut-set bound
instead: each of d > k helpers ships a 1/alpha fraction of its shard,
for a total of d/alpha shard-equivalents.  The default PM-MSR(9,16)
has alpha = k-1 = 8, so a repair moves 16/8 = 2 shard-equivalents
instead of 9 — a repair_network_ratio of d/(k*alpha) = 16/72 = 0.222
against the naive k-shard copy, under the 0.334 reduced-read RS floor.

Construction (product-matrix, MSR point, beta = 1)
--------------------------------------------------
alpha = k - 1, d = 2*alpha = 2k - 2, n <= d + 1 nodes.  Node i has an
encoding row psi_i = [phi_i, lambda_i * phi_i] of length d, where
phi_i = [1, x_i, .., x_i^(alpha-1)] is Vandermonde over distinct
x_i = g^i and lambda_i = x_i^alpha (distinct while alpha*i < 255 for
all i).  The message is M = [[S1],[S2]] with S1, S2 symmetric
alpha x alpha — exactly B = alpha*(alpha+1) = k*alpha free symbols —
and node i stores the alpha symbols psi_i @ M.

Repair of node f: every helper j ships the single symbol
stored_j . phi_f (the same phi_f combination for all helpers); the
collected d-vector equals Psi_H @ [S1 phi_f^T; S2 phi_f^T], so the
rebuilder applies R = [I | lambda_f I] @ inv(Psi_H) and, because S1
and S2 are symmetric, R @ received is node f's content transposed.
`repair_coeff` / `repair_matrix` expose exactly these two matrices to
ops/regen.py's planner.

Byte layout: sub-packetization is BYTE-INTERLEAVED.  Sub-row a of
node i's shard file is the byte set {t*alpha + a}; coupling is purely
local, so reconstructing byte range [o, o+s) of one shard touches only
the survivors' same alpha-aligned range, ragged tails behave exactly
as in RS, and a helper's partial read over sub-range [o, s) is one
contiguous pread of file bytes [o*alpha, (o+s)*alpha).

Two classes:
- PMMSRCode: the inner code over n*alpha "virtual rows", systematised
  so its parity_matrix [k*alpha, k*alpha] drops straight into the
  RSCodecBase / NativeRSCodec / matrix_apply_factory seam (the XLA
  bit-sliced, fused Pallas and AVX2 backends run it unchanged).
- MSRFileCodec: the file-level wrapper (k files in, n files out) that
  owns the interleave reshapes; what the storage layer sees.
"""

from __future__ import annotations

import functools

import numpy as np

from seaweedfs_tpu.ops import gf

DEFAULT_K = 9
DEFAULT_D = 16
GENERATOR = 2


class PMMSRCode:
    """Inner product-matrix MSR code over virtual rows.

    Virtual row i*alpha + a is sub-row a of node i.  Systematic in the
    first k nodes' rows; `parity_matrix` is [m*alpha, k*alpha].  The
    code is node-MDS (any k whole nodes decode), NOT row-MDS — decoding
    goes through `decode_select`, which picks whole nodes."""

    family = "msr"

    def __init__(self, k: int = DEFAULT_K, d: int = DEFAULT_D,
                 n: int | None = None):
        if d != 2 * (k - 1):
            raise ValueError(f"PM-MSR needs d == 2k-2, got k={k} d={d}")
        self.k_nodes = k
        self.d = d
        self.alpha = k - 1
        self.n_nodes = n if n is not None else d + 2  # 2k-2 helpers + lost + 1
        if self.n_nodes < d + 1:
            raise ValueError(f"need n >= d+1 nodes, got {self.n_nodes}")
        if self.alpha * (self.n_nodes - 1) >= gf.ORDER:
            raise ValueError(f"PM-MSR({k},{d}) lambdas collide in GF(2^8)")
        self.m_nodes = self.n_nodes - k
        a = self.alpha
        # per-node encoding rows psi_i = [phi_i, lambda_i * phi_i]
        self.x = np.array([gf.gf_pow(GENERATOR, i)
                           for i in range(self.n_nodes)], dtype=np.uint8)
        self.phi = np.array(
            [[gf.gf_pow(int(xi), t) for t in range(a)] for xi in self.x],
            dtype=np.uint8)
        self.lam = np.array([gf.gf_pow(int(xi), a) for xi in self.x],
                            dtype=np.uint8)
        assert len(set(int(v) for v in self.lam)) == self.n_nodes
        self.psi = np.concatenate(
            [self.phi, gf.GF_MUL_TABLE[self.lam[:, None], self.phi]], axis=1)
        # E maps the B = k*alpha free symbols (upper triangles of S1, S2)
        # to the n*alpha stored symbols; systematise against the first k
        # nodes to get the generator G with parity block G[k*alpha:].
        B = a * (a + 1)
        tri = {}
        for p in range(a):
            for q in range(p, a):
                tri[(p, q)] = len(tri)
        E = np.zeros((self.n_nodes * a, B), dtype=np.uint8)
        half = B // 2
        for i in range(self.n_nodes):
            for col in range(a):  # stored symbol: phi_i @ S1[:,col] + ...
                for u in range(a):
                    s = tri[(min(u, col), max(u, col))]
                    E[i * a + col, s] ^= int(self.phi[i, u])
                    E[i * a + col, half + s] ^= gf.gf_mul(
                        int(self.lam[i]), int(self.phi[i, u]))
        D = E[: k * a]
        G = gf.gf_matmul(E, gf.gf_mat_inv(D))
        assert np.array_equal(G[: k * a], np.eye(k * a, dtype=np.uint8))
        self.G = G
        self.parity_matrix = np.ascontiguousarray(G[k * a:])
        # RSCodecBase surface: virtual-row dimensions
        self.k = k * a
        self.m = self.m_nodes * a
        self.n = self.n_nodes * a
        self.tag = f"msr_{k}_{d}"

    # ---- node geometry ---------------------------------------------------

    def node_rows(self, i: int) -> list[int]:
        return list(range(i * self.alpha, (i + 1) * self.alpha))

    def whole_nodes(self, rows) -> list[int]:
        """Node ids whose full alpha sub-rows appear in `rows`."""
        have = set(rows)
        return [i for i in range(self.n_nodes)
                if all(r in have for r in self.node_rows(i))]

    # ---- decoding (virtual-row protocol for the codec shells) ------------

    def decodable(self, lost_nodes: list[int]) -> bool:
        return len(set(lost_nodes)) <= self.n_nodes - self.k_nodes

    def decode_select(self, available: list[int],
                      wanted: list[int]) -> list[int]:
        """First k whole surviving nodes, as sorted virtual rows.  The
        PM code is node-MDS, so any k whole nodes form a basis."""
        nodes = self.whole_nodes(available)
        if len(nodes) < self.k_nodes:
            raise ValueError(
                f"msr: {len(nodes)} whole nodes available, need "
                f"{self.k_nodes}")
        basis: list[int] = []
        for i in nodes[: self.k_nodes]:
            basis.extend(self.node_rows(i))
        return sorted(basis)

    def decode_matrix(self, available: list[int],
                      wanted: list[int]) -> np.ndarray:
        basis = self.decode_select(list(available), list(wanted))
        inv = gf.gf_mat_inv(self.G[basis])
        return gf.gf_matmul(self.G[list(wanted)], inv)

    # ---- regenerating repair (consumed by ops/regen.py) ------------------

    def repair_coeff(self, lost_node: int) -> np.ndarray:
        """[1, alpha] helper-side combination: every helper ships
        phi_f @ its own sub-rows — one row per alpha stored."""
        return self.phi[lost_node][None, :].copy()

    def repair_matrix(self, lost_node: int,
                      helpers: list[int]) -> np.ndarray:
        """[alpha, d] rebuilder matrix R: node f's sub-rows are
        R @ stacked helper symbols (helpers in the given order)."""
        if len(helpers) != self.d:
            raise ValueError(f"msr repair needs d={self.d} helpers, "
                             f"got {len(helpers)}")
        if lost_node in helpers:
            raise ValueError("lost node cannot help itself")
        psi_h = self.psi[list(helpers)]  # [d, d] — invertible Vandermonde
        inv = gf.gf_mat_inv(psi_h)
        a = self.alpha
        lam_f = int(self.lam[lost_node])
        # [I | lambda_f I] @ inv(Psi_H)
        return gf.gf_matmul(
            np.concatenate([np.eye(a, dtype=np.uint8),
                            lam_f * np.eye(a, dtype=np.uint8)], axis=1),
            inv)

    def repair_ratio(self) -> float:
        """Repair bytes over naive k-shard copy: d / (k * alpha)."""
        return self.d / (self.k_nodes * self.alpha)


def interleave_split(data, k: int, alpha: int):
    """[k, L] file rows -> [k*alpha, L/alpha] virtual sub-rows.
    Works on numpy and jax arrays alike (pure reshape/swap)."""
    kk, L = data.shape
    assert kk == k and L % alpha == 0, (data.shape, k, alpha)
    return data.reshape(k, L // alpha, alpha).swapaxes(1, 2).reshape(
        k * alpha, L // alpha)


def interleave_merge(virt, m: int, alpha: int):
    """[m*alpha, S] virtual sub-rows -> [m, S*alpha] file rows."""
    rows, S = virt.shape
    assert rows == m * alpha, (virt.shape, m, alpha)
    return virt.reshape(m, alpha, S).swapaxes(1, 2).reshape(m, S * alpha)


class MSRFileCodec:
    """File-level MSR codec: k shard files in, n out.

    Wraps an inner RSCodecBase-style shell over PMMSRCode's virtual
    rows and owns the byte-interleave reshapes.  Propagates the inner
    backend's `_factory` / host nature so ops/dispatch routes the
    wrapped kernels exactly as it would the bare shell."""

    family = "msr"

    def __init__(self, inner, code: PMMSRCode | None = None):
        self.inner = inner
        self.code = code if code is not None else inner.code
        assert isinstance(self.code, PMMSRCode)
        self.k = self.code.k_nodes
        self.m = self.code.m_nodes
        self.n = self.code.n_nodes
        self.alpha = self.code.alpha
        factory = getattr(inner, "_factory", None)
        if factory is not None:
            self._factory = factory
        self.host_backend = getattr(inner, "host_backend", False)

    def encode_parity(self, data):
        """[k, L] data files -> [m, L] parity files (L % alpha == 0)."""
        virt = interleave_split(data, self.k, self.alpha)
        return interleave_merge(self.inner.encode_parity(virt),
                                self.m, self.alpha)

    def encode_parity_batch(self, units):
        """[U, k, L] -> [U, m, L] through the inner batch kernel."""
        U, kk, L = units.shape
        a = self.alpha
        assert kk == self.k and L % a == 0, units.shape
        virt = units.reshape(U, self.k, L // a, a).swapaxes(2, 3).reshape(
            U, self.k * a, L // a)
        enc = getattr(self.inner, "encode_parity_batch", None)
        if enc is not None:
            pv = enc(virt)
        else:
            pv = np.stack([self.inner.encode_parity(virt[u])
                           for u in range(U)], axis=0)
        return pv.reshape(U, self.m, a, L // a).swapaxes(2, 3).reshape(
            U, self.m, L)

    def encode(self, data):
        parity = self.encode_parity(data)
        if isinstance(parity, np.ndarray):
            return np.concatenate([np.asarray(data), parity], axis=0)
        import jax.numpy as jnp
        return jnp.concatenate([jnp.asarray(data), parity], axis=0)

    def decode_select(self, available: list[int],
                      wanted: list[int]) -> list[int]:
        """File-level survivor choice: any k files decode (node-MDS)."""
        avail = sorted(set(available))
        if len(avail) < self.k:
            raise ValueError(f"msr: {len(avail)} survivors, need {self.k}")
        return avail[: self.k]

    def reconstruct(self, shards: dict, wanted: list[int] | None = None
                    ) -> dict:
        """File-level reconstruct: de-interleave survivors into virtual
        rows, run the inner shell, re-interleave the wanted files."""
        present = sorted(shards)
        if wanted is None:
            wanted = [i for i in range(self.n) if i not in shards]
        if not wanted:
            return {}
        a = self.alpha
        use = self.decode_select(present, list(wanted))
        virt: dict = {}
        for sid in use:
            row = shards[sid]
            rows = interleave_split(row.reshape(1, -1), 1, a)
            for j in range(a):
                virt[sid * a + j] = rows[j]
        want_rows = [w * a + j for w in wanted for j in range(a)]
        out = self.inner.reconstruct(virt, want_rows)
        result = {}
        for w in wanted:
            stacked = np.stack(
                [np.asarray(out[w * a + j]) for j in range(a)], axis=0)
            result[w] = interleave_merge(stacked, 1, a)[0]
        return result

    # regen-facing passthroughs
    def repair_coeff(self, lost: int) -> np.ndarray:
        return self.code.repair_coeff(lost)

    def repair_matrix(self, lost: int, helpers: list[int]) -> np.ndarray:
        return self.code.repair_matrix(lost, helpers)


@functools.lru_cache(maxsize=8)
def get_code(k: int = DEFAULT_K, d: int = DEFAULT_D) -> PMMSRCode:
    return PMMSRCode(k, d)
