"""Codec registry: tag grammar, per-volume codec identity, backend builds.

A volume's erasure code is no longer a constant — `.vif` metadata, the
heartbeat shard report, repair planning and the autopilot all carry a
codec *tag*, and this module is the one place the tag grammar lives:

    rs_<k>_<m>        Reed-Solomon (MDS), e.g. rs_10_4
    lrc_<k>_<l>_<g>   locally repairable, e.g. lrc_10_2_2
    msr_<k>_<d>       product-matrix regenerating, e.g. msr_9_16

`parse_tag(None)` and any unknown tag resolve to the RS default — old
nodes that never heard of codec tags keep working with no flag-day.

Backend builds go through `make_codec(tag, kind)`, the codec-family
generalisation of ec_files._get_codec: the same WEEDTPU_EC_CODEC knob
(auto|tpu|jax|cpp|numpy|mesh) picks the matrix-apply backend, and every
family rides the RSCodecBase / NativeRSCodec shells unchanged — LRC is
just another fixed matrix; MSR wraps the shell in its interleaving
file codec.  The Pallas and mesh backends are RS-shaped (fixed 10x4
tiling assumptions); non-RS families fall back to the XLA bit-sliced
backend there rather than guessing at tile geometry.

Knobs: WEEDTPU_CODEC_DEFAULT (tag or family for untagged volumes),
WEEDTPU_CODEC_LRC ("k,l,g" params behind the bare "lrc" family name),
WEEDTPU_CODEC_MSR ("k,d" likewise).
"""

from __future__ import annotations

import dataclasses
import functools
import os

import numpy as np

DEFAULT_TAG = "rs_10_4"


@dataclasses.dataclass(frozen=True)
class CodecSpec:
    """Identity + geometry of one registered code: everything the
    control plane needs without building a backend."""
    tag: str
    family: str       # rs | lrc | msr
    k: int            # data shard files
    m: int            # parity shard files
    n: int            # total shard files
    alpha: int        # sub-packetization (1 for rs/lrc)
    params: tuple     # family params, e.g. (10, 4) / (10, 2, 2) / (9, 16)

    @property
    def tolerance(self) -> int:
        """Worst-case guaranteed losses: m for MDS codes, the minimum
        distance - 1 for LRC (g + 1 with one local parity per group)."""
        if self.family == "lrc":
            return self.params[2] + 1
        return self.m

    def describe(self) -> dict:
        return {"tag": self.tag, "family": self.family, "k": self.k,
                "m": self.m, "n": self.n, "alpha": self.alpha,
                "tolerance": self.tolerance,
                "params": list(self.params)}


def _lrc_params() -> tuple[int, int, int]:
    raw = os.environ.get("WEEDTPU_CODEC_LRC", "10,2,2")
    try:
        k, l, g = (int(v) for v in raw.split(","))  # noqa: E741
        return k, l, g
    except ValueError:
        return 10, 2, 2


def _msr_params() -> tuple[int, int]:
    raw = os.environ.get("WEEDTPU_CODEC_MSR", "9,16")
    try:
        k, d = (int(v) for v in raw.split(","))
        return k, d
    except ValueError:
        return 9, 16


def _spec_rs(k: int, m: int) -> CodecSpec:
    return CodecSpec(tag=f"rs_{k}_{m}", family="rs", k=k, m=m, n=k + m,
                     alpha=1, params=(k, m))


def _spec_lrc(k: int, l: int, g: int) -> CodecSpec:  # noqa: E741
    return CodecSpec(tag=f"lrc_{k}_{l}_{g}", family="lrc", k=k, m=l + g,
                     n=k + l + g, alpha=1, params=(k, l, g))


def _spec_msr(k: int, d: int) -> CodecSpec:
    n = d + 2
    return CodecSpec(tag=f"msr_{k}_{d}", family="msr", k=k, m=n - k, n=n,
                     alpha=k - 1, params=(k, d))


def parse_tag(tag: str | None) -> CodecSpec:
    """Tag string -> CodecSpec.  None, "", bare family names and any
    unparseable/unknown tag degrade to a usable spec — an old node
    reporting no codec means RS, not an error."""
    if not tag:
        return parse_tag(DEFAULT_TAG)
    tag = str(tag).strip().lower()
    if tag == "rs":
        return _spec_rs(10, 4)
    if tag == "lrc":
        return _spec_lrc(*_lrc_params())
    if tag == "msr":
        return _spec_msr(*_msr_params())
    parts = tag.split("_")
    try:
        if parts[0] == "rs" and len(parts) == 3:
            return _spec_rs(int(parts[1]), int(parts[2]))
        if parts[0] == "lrc" and len(parts) == 4:
            return _spec_lrc(int(parts[1]), int(parts[2]), int(parts[3]))
        if parts[0] == "msr" and len(parts) == 3:
            return _spec_msr(int(parts[1]), int(parts[2]))
    except ValueError:
        pass
    return parse_tag(DEFAULT_TAG)


def default_tag() -> str:
    """The codec newly-encoded volumes get when nothing chose one:
    WEEDTPU_CODEC_DEFAULT accepts a full tag or a bare family name."""
    return parse_tag(os.environ.get("WEEDTPU_CODEC_DEFAULT", DEFAULT_TAG)).tag


def registered() -> list[CodecSpec]:
    """The codec family as configured right now — what `ec.codecs`
    lists."""
    return [_spec_rs(10, 4), _spec_lrc(*_lrc_params()),
            _spec_msr(*_msr_params())]


# ---------------------------------------------------------------------------
# backend builds


class _NumpyShell:
    """Pure-numpy eager shell for non-RS inner codes when no native lib
    and no device backend is wanted (WEEDTPU_EC_CODEC=numpy).  Slowest
    path, test/reference only."""

    host_backend = True

    def __init__(self, code):
        self.code = code
        self.k, self.m, self.n = code.k, code.m, code.n
        self._decode_cache: dict = {}

    def encode_parity(self, data):
        from seaweedfs_tpu.ops import gf
        return gf.gf_matmul(self.code.parity_matrix, np.asarray(data))

    def encode(self, data):
        data = np.asarray(data)
        return np.concatenate([data, self.encode_parity(data)], axis=0)

    def reconstruct(self, shards, wanted=None):
        from seaweedfs_tpu.ops import codec_base, gf
        present = tuple(sorted(shards))
        if wanted is None:
            wanted = [i for i in range(self.n) if i not in shards]
        if not wanted:
            return {}
        basis = codec_base.select_survivors(self.code, present, list(wanted))
        mat = self.code.decode_matrix(list(present), list(wanted))
        stack = np.stack([np.asarray(shards[i]) for i in basis])
        out = gf.gf_matmul(mat, stack)
        return {w: out[i] for i, w in enumerate(wanted)}


def _code_for(spec: CodecSpec):
    """The bare code object (matrix + decode protocol) behind a spec.
    For MSR this is the inner virtual-row code; the file surface is
    MSRFileCodec's."""
    if spec.family == "lrc":
        from seaweedfs_tpu.ops import lrc
        return lrc.get_code(*spec.params)
    if spec.family == "msr":
        from seaweedfs_tpu.ops import msr
        return msr.get_code(*spec.params)
    from seaweedfs_tpu.models import rs
    return rs.get_code(spec.k, spec.m)


def _shell_for(code, kind: str):
    """An RSCodecBase-compatible shell over `code` for one backend
    kind.  Pallas/mesh are RS-tiled; generic codes use the XLA
    bit-sliced backend there."""
    if kind in ("cpp", "native"):
        from seaweedfs_tpu.ops import native_codec
        return native_codec.NativeRSCodec(code)
    if kind == "numpy":
        return _NumpyShell(code)
    if kind == "auto":
        import jax
        if jax.default_backend() == "tpu":
            from seaweedfs_tpu.ops import gfmat_jax
            return gfmat_jax.JaxRSCodec(code)
        from seaweedfs_tpu import native
        if native.available():
            from seaweedfs_tpu.ops import native_codec
            return native_codec.NativeRSCodec(code)
    from seaweedfs_tpu.ops import gfmat_jax
    return gfmat_jax.JaxRSCodec(code)


@functools.lru_cache(maxsize=16)
def _build(tag: str, kind: str):
    spec = parse_tag(tag)
    if spec.family == "rs":
        # RS keeps its existing per-backend registries (incl. Pallas
        # fused kernels and the mesh codec) — delegate so behaviour and
        # caches stay byte-identical with pre-family builds
        from seaweedfs_tpu.storage.ec import ec_files
        return ec_files._get_codec(kind if kind != "default" else None)
    code = _code_for(spec)
    if spec.family == "msr":
        from seaweedfs_tpu.ops import msr
        return msr.MSRFileCodec(_shell_for(code, kind))
    return _shell_for(code, kind)


def make_codec(tag: str | None, kind: str | None = None):
    """Backend codec for a codec tag.  `kind` defaults to the
    WEEDTPU_EC_CODEC knob, exactly like ec_files._get_codec."""
    spec = parse_tag(tag)
    kind = kind or os.environ.get("WEEDTPU_EC_CODEC", "auto")
    return _build(spec.tag, kind)


def spec_of(codec) -> CodecSpec:
    """Best-effort spec for a live codec object (for metrics labels)."""
    code = getattr(codec, "code", codec)
    tag = getattr(code, "tag", None)
    if tag:
        return parse_tag(tag)
    fam = getattr(code, "family", "rs")
    if fam == "msr":
        return _spec_msr(code.k_nodes, code.d)
    return _spec_rs(getattr(codec, "k", 10), getattr(codec, "m", 4))
