"""LRC(k, l, g): locally repairable codes with per-group XOR parities.

The Facebook warehouse study (arXiv:1309.0186) measures what RS costs a
hot cluster: every degraded read of a single lost shard fans in k full
survivor ranges.  An LRC splits the k data shards into l local groups
of r = k/l and gives each group its own local parity (the XOR of its
members), plus g global parities for multi-loss protection — so the
overwhelmingly common single-shard degraded read touches ONE group:
r surviving shards instead of k, and never crosses group boundaries.

Construction
------------
Generator [n, k] over GF(2^8), n = k + l + g:

- rows 0..k-1: identity (systematic);
- rows k..k+l-1: local parities — row k+i is all-ones over group i's
  columns, zero elsewhere (plain XOR, so local repair needs no table
  multiplies at all);
- rows k+l..n-1: global parities — extended-Cauchy rows 1/(x_i + y_j)
  with distinct x_i, y_j.  Together with the all-ones local rows these
  form a generalized Cauchy family (the ones row is the x -> infinity
  limit), which is what makes every information-theoretically
  decodable loss pattern actually decode; the default LRC(10,2,2) has
  distance 4 (any 3 losses decode, verified exhaustively by the tests).

Unlike RS, the code is NOT MDS: "first k sorted survivors" is not a
valid decode basis (two data losses in one group leave its local
parity useless).  Decoding therefore goes through `decode_select`,
which picks a preferred basis by Gaussian elimination — local group
first, then other data rows, locals, globals — and `decode_matrix`,
whose columns follow that basis.  The codec shells (codec_base /
native_codec) consume exactly this pair, so the XLA bit-sliced, fused
Pallas and native AVX2 backends run LRC unchanged: it is just another
fixed GF(2^8) matrix.
"""

from __future__ import annotations

import functools

import numpy as np

from seaweedfs_tpu.ops import gf

DEFAULT_K = 10
DEFAULT_L = 2  # local groups
DEFAULT_G = 2  # global parities


class LRCCode:
    """A systematic LRC(k, l, g) code over GF(2^8).

    k data shards in l groups of r = k/l, one XOR local parity per
    group, g extended-Cauchy global parities.  Pure metadata + numpy
    reference codec, same contract as models/rs.RSCode plus the local
    -repair hooks (`group_of`, `repair_support`, `decode_select`)."""

    family = "lrc"

    def __init__(self, k: int = DEFAULT_K, l: int = DEFAULT_L,  # noqa: E741
                 g: int = DEFAULT_G):
        if k < 2 or l < 1 or g < 0 or k % l != 0:
            raise ValueError(f"bad LRC({k},{l},{g}): need k % l == 0")
        self.k = k
        self.l = l  # noqa: E741
        self.g = g
        self.r = k // l  # group width (data shards per local group)
        self.m = l + g
        self.n = k + self.m
        if self.n + g > 256:
            raise ValueError(f"LRC({k},{l},{g}) does not fit GF(2^8)")
        mat = np.zeros((self.n, k), dtype=np.uint8)
        mat[:k] = np.eye(k, dtype=np.uint8)
        for gi in range(l):
            mat[k + gi, gi * self.r:(gi + 1) * self.r] = 1
        # extended-Cauchy global rows: x_i = n + i keeps x disjoint from
        # y_j = j for every shard count that fits the field
        for i in range(g):
            for j in range(k):
                mat[k + l + i, j] = gf.gf_inv((self.n + i) ^ j)
        self.matrix = mat
        self.parity_matrix = mat[k:]
        self.tag = f"lrc_{k}_{l}_{g}"

    # ---- group geometry --------------------------------------------------

    def group_of(self, sid: int) -> int | None:
        """Local group of a shard id; None for global parities."""
        if sid < self.k:
            return sid // self.r
        if sid < self.k + self.l:
            return sid - self.k
        return None

    def group_members(self, gi: int) -> tuple[int, ...]:
        """Data shards of group gi plus its local parity shard."""
        return tuple(range(gi * self.r, (gi + 1) * self.r)) + (self.k + gi,)

    def repair_support(self, lost: int,
                       available: list[int]) -> list[int] | None:
        """The single-group survivor set repairing `lost`, or None when
        the loss is not locally repairable (global parity, or a second
        loss inside the group).  This is the no-wide-fan-in path: the
        returned set has exactly r shards, all in one group."""
        gi = self.group_of(lost)
        if gi is None:
            return None
        members = set(self.group_members(gi))
        support = sorted((members - {lost}) & set(available))
        if len(support) != self.r:  # a second group member is missing
            return None
        return support

    # ---- decoding --------------------------------------------------------

    def decodable(self, lost: list[int]) -> bool:
        keep = [i for i in range(self.n) if i not in set(lost)]
        return gf.gf_rank(self.matrix[keep]) == self.k

    def decode_select(self, available: list[int],
                      wanted: list[int]) -> list[int]:
        """Choose the survivor basis feeding `decode_matrix`.

        Preference order: single-group local repair when possible
        (degraded reads touch <= r shards, never both groups), else a
        greedy rank build over data rows first, then local, then global
        parities, pruned to the rows the solve actually uses."""
        avail = sorted(set(available))
        if len(wanted) == 1:
            support = self.repair_support(wanted[0], avail)
            if support is not None:
                return support
        w_rows = self.matrix[list(wanted)]
        # preference: identity rows are free pivots; globals are last
        order = sorted(avail, key=lambda s: (s >= self.k,
                                             s >= self.k + self.l, s))
        chosen: list[int] = []
        rank = 0
        for sid in order:
            cand = chosen + [sid]
            nr = len(gf.gf_rref(self.matrix[cand])[1])
            if nr > rank:
                chosen, rank = cand, nr
            if rank and gf.gf_solve(self.matrix[chosen].T,
                                    w_rows.T) is not None:
                break
        X = gf.gf_solve(self.matrix[chosen].T, w_rows.T)
        if X is None:
            raise ValueError(
                f"lrc: cannot reconstruct {list(wanted)} from "
                f"{avail} (undecodable loss pattern)")
        used = [sid for i, sid in enumerate(chosen) if X[i].any()]
        return sorted(used) if used else chosen[:1]

    def decode_matrix(self, available: list[int],
                      wanted: list[int]) -> np.ndarray:
        """[w, len(basis)] matrix with columns following
        decode_select(available, wanted) in sorted order, so that
        wanted_rows = M @ survivor_rows[basis]."""
        basis = self.decode_select(list(available), list(wanted))
        X = gf.gf_solve(self.matrix[basis].T, self.matrix[list(wanted)].T)
        if X is None:
            raise ValueError(
                f"lrc: basis {basis} cannot express {list(wanted)}")
        return np.ascontiguousarray(X.T, dtype=np.uint8)

    # ---- slow reference codec (numpy, for tests) -------------------------

    def encode_numpy(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        assert data.shape[0] == self.k, data.shape
        parity = gf.gf_matmul(self.parity_matrix, data)
        return np.concatenate([data, parity], axis=0)

    def reconstruct_numpy(self, shards: dict[int, np.ndarray],
                          wanted: list[int] | None = None
                          ) -> dict[int, np.ndarray]:
        present = sorted(shards)
        if wanted is None:
            wanted = [i for i in range(self.n) if i not in shards]
        if not wanted:
            return {}
        basis = self.decode_select(present, list(wanted))
        M = self.decode_matrix(present, list(wanted))
        stack = np.stack([np.asarray(shards[s]) for s in basis], axis=0)
        out = gf.gf_matmul(M, stack)
        return {w: out[i] for i, w in enumerate(wanted)}


@functools.lru_cache(maxsize=16)
def get_code(k: int = DEFAULT_K, l: int = DEFAULT_L,  # noqa: E741
             g: int = DEFAULT_G) -> LRCCode:
    return LRCCode(k, l, g)
