"""Batched multi-volume EC conversion: one device-resident stream.

`write_ec_files` converts ONE volume well: its pipeline overlaps read /
encode / write, but between volumes the device drains and the writers
idle — fleet-wide cold-volume conversion (the consumer the autopilot
demote path feeds) runs as N serial encodes.  This module interleaves N
volumes' column units into ONE stream of unit batches:

    readers     stage units round-robin across volumes into pooled
                [U, k, B] host batches (data shards go straight to each
                volume's writer pool by in-kernel copy_file_range — they
                never touch the device)
    dispatch    H2D through the encoder's matched in_sharding (on a mesh
                each chip pulls exactly its U/D units) and launches ONE
                batched parity kernel per batch (pallas grid over units;
                ops/dispatch.dispatch_parity_batch)
    drain       streams parity off the device PER DEVICE SHARD as each
                block's D2H lands (dispatch.unit_parity_shards) and fans
                rows to the owning volume's writers — no full gather
    writers     per-volume _ShardWriterPool; a volume whose last unit
                drains is finalized (truncate to shard size, .vif,
                tmp -> rename commit) while the stream keeps feeding the
                other volumes

Double buffering falls out of the pooled batches: H2D + kernel for batch
N+1 runs while batch N is still draining D2H + writes.  Failure/cancel
anywhere aborts the WHOLE run cleanly: uncommitted volumes keep their
previous valid shard set (same .tmp recycle + rename-on-success contract
as write_ec_files), committed volumes stay committed.

Knobs: WEEDTPU_CONVERT_UNITS (units per device batch, default 4; rounded
up to an even mesh split), WEEDTPU_CONVERT_DEPTH (in-flight batches,
default 2 = double buffered).  The master-side pacing of fleet runs
lives in maintenance/convert.py; this module is the data plane.
"""

from __future__ import annotations

import functools
import os
import queue
import threading
import time

import numpy as np

from seaweedfs_tpu.ops.dispatch import (dispatch_parity_batch,
                                        unit_parity_shards)
from seaweedfs_tpu.stats import netflow as _netflow
from seaweedfs_tpu.stats import pipeline as _pipeline
from seaweedfs_tpu.storage.ec import layout
from seaweedfs_tpu.storage.ec.ec_files import (
    DEFAULT_BATCH, EncodeCancelled, _book_stage_bytes, _iter_units,
    _map_readonly, _ShardFlusher, _ShardWriterPool, _Timer,
    _unit_coverage, _unit_steps, overlap_fraction, write_vif)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


@functools.lru_cache(maxsize=4)
def _fleet_unit_encoder(k: int, m: int):
    from seaweedfs_tpu.models import rs
    from seaweedfs_tpu.parallel import mesh as pmesh
    return pmesh.FleetUnitEncoder(rs.get_code(k, m))


def fleet_codec(kind: str | None = None):
    """The codec a fleet conversion rides: with more than one attached
    device (a real slice, or the virtual CPU mesh in tests) the
    unit-sharded FleetUnitEncoder; otherwise whatever WEEDTPU_EC_CODEC
    resolves to — every backend now takes `dispatch_parity_batch`."""
    from seaweedfs_tpu.storage.ec.ec_files import _get_codec
    kind = kind or os.environ.get("WEEDTPU_CONVERT_CODEC")
    if kind:
        if kind in ("mesh", "fleet"):
            return _fleet_unit_encoder(layout.DATA_SHARDS,
                                       layout.PARITY_SHARDS)
        return _get_codec(kind)
    try:
        import jax
        if len(jax.devices()) > 1:
            return _fleet_unit_encoder(layout.DATA_SHARDS,
                                       layout.PARITY_SHARDS)
    except Exception:
        pass
    return _get_codec()


class _VolumeJob:
    """One volume mid-conversion: source map, recycled .tmp shard fds,
    its writer pool, and completion accounting."""

    def __init__(self, base: str, dat_path: str | None, large_block: int,
                 small_block: int, batch_size: int, stats: dict | None):
        self.base = base
        self.dat_path = dat_path or base + ".dat"
        self.dat_size = os.path.getsize(self.dat_path)
        self.large_block = large_block
        self.small_block = small_block
        self.shard_size = layout.shard_file_size(
            self.dat_size, large_block, small_block)
        self.tmp_paths = [base + layout.to_ext(i) + ".tmp"
                          for i in range(layout.TOTAL_SHARDS)]
        self.out_fds = [os.open(p, os.O_RDWR | os.O_CREAT, 0o644)
                        for p in self.tmp_paths]
        self.highwater = [0] * layout.TOTAL_SHARDS
        self.dat_f = open(self.dat_path, "rb")
        self.mm = None
        self.view: np.ndarray | None = None
        if self.dat_size:
            self.mm = _map_readonly(self.dat_f.fileno(), self.dat_size)
            self.view = np.frombuffer(self.mm, dtype=np.uint8)
        k = layout.DATA_SHARDS
        self.writers = _ShardWriterPool(
            self.out_fds, self.highwater, stats,
            stage_key=lambda i: "write_data_s" if i < k
            else "write_parity_s")
        # two submission batchers, one per producer thread: the reader
        # ships data-shard copies, the drain ships parity rows — a
        # _ShardFlusher is single-producer (its per-shard job lists and
        # accumulator are unlocked)
        self.data_flusher = _ShardFlusher(self.writers, layout.TOTAL_SHARDS)
        self.parity_flusher = _ShardFlusher(self.writers,
                                            layout.TOTAL_SHARDS)
        self.units = _iter_units(self.dat_size, large_block, small_block,
                                 batch_size)
        self.units_read = 0
        self.units_total: int | None = None  # set when the iterator ends
        self.units_drained = 0   # written by the drain thread only
        self.units_skipped = 0   # written by the reader thread only
        self.done_bytes = 0
        self.committed = False
        self._stats = stats

    def next_unit(self):
        try:
            u = next(self.units)
            self.units_read += 1
            return u
        except StopIteration:
            self.units_total = self.units_read
            return None

    def drained_all(self) -> bool:
        # drained is drain-thread-owned, skipped reader-thread-owned: two
        # counters so the threads never race one += on the same field
        return self.units_total is not None and \
            self.units_drained + self.units_skipped >= self.units_total

    def finalize(self) -> None:
        """All units drained: barrier on the writers, cut shards to size,
        commit by rename.  Runs on the drain thread while the stream
        keeps feeding other volumes."""
        self.data_flusher.flush()
        self.parity_flusher.flush()
        self.writers.close()
        if self.writers.errors:
            raise self.writers.errors[0]
        for fd, hw in zip(self.out_fds, self.highwater):
            os.ftruncate(fd, min(hw, self.shard_size))
            if hw < self.shard_size:
                os.ftruncate(fd, self.shard_size)
        for fd in self.out_fds:
            os.close(fd)
        self.out_fds = []
        write_vif(self.base, self.dat_size)
        for i, p in enumerate(self.tmp_paths):
            os.replace(p, self.base + layout.to_ext(i))
        self.committed = True
        if self._stats is not None:
            # callers that must react per-volume (the volume server's
            # freeze bookkeeping) see commits even when a LATER volume
            # fails the run
            self._stats.setdefault("committed_bases", []).append(self.base)

    def abort(self) -> None:
        """Failure path: drop fds and every .tmp so no partial shard set
        is ever visible; a previous valid shard set stays untouched."""
        try:
            self.writers.close()
        except Exception:
            pass
        for fd in self.out_fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self.out_fds = []
        if not self.committed:
            for p in self.tmp_paths:
                try:
                    os.remove(p)
                except OSError:
                    pass

    def release(self) -> None:
        if self.view is not None:
            self.view = None
        if self.mm is not None:
            try:
                self.mm.close()
            except BufferError:
                pass
            self.mm = None
        self.dat_f.close()


def convert_volumes(bases: list[str], *,
                    large_block: int = layout.LARGE_BLOCK_SIZE,
                    small_block: int = layout.SMALL_BLOCK_SIZE,
                    batch_size: int = DEFAULT_BATCH,
                    codec=None, unit_batch: int | None = None,
                    progress=None, cancel=None,
                    stats: dict | None = None) -> dict:
    """Convert `bases` (.dat volumes) into EC shard sets through one
    interleaved device-resident stream.  Returns per-volume accounting.

    `progress(bytes_done)` sees TOTAL volume bytes consumed across the
    fleet; `cancel()` aborts the whole run (uncommitted volumes roll
    back).  `stats` receives the usual per-stage wall-second attribution
    plus units/volumes counters."""
    if not bases:
        return {"volumes": {}, "bytes": 0}
    codec = codec if codec is not None else fleet_codec()

    # chaos hook: an armed shard_write_error fault fails the conversion
    # like a dying disk — before any tmp shard file exists
    from seaweedfs_tpu.maintenance import faults as _faults
    for base in bases:
        _faults.check_shard_write(base)

    k, m = layout.DATA_SHARDS, layout.PARITY_SHARDS
    depth = max(1, _env_int("WEEDTPU_CONVERT_DEPTH", 2))
    U = max(1, _env_int("WEEDTPU_CONVERT_UNITS", 4))
    slots = getattr(codec, "unit_slots", None)
    if slots is not None:  # round to an even mesh split
        U = slots(U)

    stats = stats if stats is not None else {}
    stats["mode"] = "fleet"
    stats["unit_batch"] = U
    # class=convert on THIS thread and (contextvars are per-thread) re-
    # stamped inside each pipeline thread, so any hop made on the
    # conversion's behalf — wherever it runs — books as convert
    flow_cls = _netflow.current_class() or "convert"
    _flow_token = _netflow.set_class(flow_cls)
    t_wall = time.perf_counter()
    jobs = [_VolumeJob(b, None, large_block, small_block, batch_size,
                       stats) for b in bases]
    stats["bytes"] = sum(j.dat_size for j in jobs)

    # one staging width covers every job (ragged tails zero-fill): pooled
    # [U, k, W] batches, depth+1 so H2D/kernel of batch N+1 overlaps the
    # D2H/writes of batch N
    W = max(_unit_steps(j.dat_size, large_block, small_block,
                        batch_size)[1] for j in jobs)
    pool: queue.Queue = queue.Queue()
    for _ in range(depth + 1):
        pool.put(np.empty((U, k, W), dtype=np.uint8))
    q_read: queue.Queue = queue.Queue(maxsize=depth)
    q_disp: queue.Queue = queue.Queue()
    errors: list[BaseException] = []
    done_total = 0

    def reader() -> None:
        """Round-robin units across volumes into staged unit batches."""
        nonlocal done_total
        active = list(jobs)
        _netflow.set_class(flow_cls)
        try:
            while active and not errors:
                if cancel is not None and cancel():
                    raise EncodeCancelled("fleet conversion cancelled")
                with _Timer(stats, "stall_s"):
                    buf = pool.get()
                metas = []
                with _Timer(stats, "read_s"):
                    while len(metas) < U and active:
                        job = active[len(metas) % len(active)]
                        unit = job.next_unit()
                        if unit is None:
                            active.remove(job)
                            continue
                        row_start, block, col, step, shard_off = unit
                        nz, tail = _unit_coverage(
                            job.dat_size, row_start, block, col, step)
                        if nz == 0:
                            # a trailing column unit wholly beyond the
                            # .dat: nothing to encode or write
                            job.units_skipped += 1
                            continue
                        # data shards: in-kernel copies on the volume's
                        # own writers — they never ride the device
                        for j in range(nz):
                            off = row_start + j * block + col
                            n = step if j < nz - 1 else tail
                            job.data_flusher.copy(j, job.dat_f.fileno(), off,
                                             shard_off, n,
                                             src_view=job.view)
                        slot = buf[len(metas)]
                        for j in range(k):
                            off = row_start + j * block + col
                            n = max(0, min(step, job.dat_size - off))
                            if n > 0:
                                np.copyto(slot[j, :n],
                                          job.view[off:off + n])
                            if n < W:
                                slot[j, max(n, 0):] = 0
                        metas.append((job, shard_off, step))
                        done_total += (nz - 1) * step + tail
                        job.done_bytes += (nz - 1) * step + tail
                        job.data_flusher.account(step)
                    if progress is not None:
                        progress(done_total)
                if metas:
                    q_read.put((buf, metas))
                else:
                    pool.put(buf)
        except BaseException as e:
            errors.append(e)
        finally:
            q_read.put(None)

    def drain() -> None:
        """Materialise parity per device shard and fan rows out; finalize
        each volume the moment its last unit lands."""
        failed = False
        _netflow.set_class(flow_cls)
        while True:
            item = q_disp.get()
            if item is None:
                return
            buf, metas, parity = item
            if failed or errors:
                pool.put(buf)
                continue
            try:
                # stream: each block fans out (and its parity writes
                # submit) the moment its d2h lands, instead of waiting
                # for a full gather — write_parity overlaps the d2h of
                # the blocks still in flight
                blocks = unit_parity_shards(parity)
                released = False
                while True:
                    with _Timer(stats, "d2h_s"):
                        item_blk = next(blocks, None)
                    if item_blk is None:
                        break
                    if not released:
                        # the first yield implies block_until_ready has
                        # returned: the device is done with the staging
                        # memory even though later shards are still
                        # transferring
                        pool.put(buf)
                        released = True
                    a, b, block = item_blk
                    touched = []
                    for u in range(a, min(b, len(metas))):
                        job, shard_off, step = metas[u]
                        rows = block[u - a]
                        for i in range(m):
                            job.parity_flusher.put(k + i, rows[i, :step],
                                                   shard_off)
                        job.parity_flusher.account(step)
                        job.units_drained += 1
                        if job.drained_all():
                            job.finalize()
                        elif job not in touched:
                            touched.append(job)
                    for job in touched:
                        job.parity_flusher.flush()
                if not released:
                    pool.put(buf)
            except BaseException as e:
                errors.append(e)
                failed = True
                continue

    t_r = threading.Thread(target=reader, name="fleet-reader", daemon=True)
    t_d = threading.Thread(target=drain, name="fleet-drain", daemon=True)
    pjob = _pipeline.track("fleet_convert", stats, stats["bytes"],
                           meta={"volumes": len(jobs), "unit_batch": U})
    t_r.start()
    t_d.start()
    try:
        while True:
            item = q_read.get()
            if item is None:
                break
            # stage-queue depths at the consume site: a persistently full
            # q_read means the dispatch (encode) stage is the bound, a
            # deep q_disp means the drain/writers are
            pjob.queue("q_read", q_read.qsize(), depth)
            pjob.queue("q_disp", q_disp.qsize())
            buf, metas = item
            if errors:
                pool.put(buf)
                continue
            try:
                with _Timer(stats, "encode_s"):
                    parity = dispatch_parity_batch(codec, buf)
                q_disp.put((buf, metas, parity))
            except BaseException as e:
                errors.append(e)
                pool.put(buf)
    finally:
        q_disp.put(None)
        t_d.join()
        while t_r.is_alive():  # unblock a reader stuck on a full q_read
            try:
                item = q_read.get(timeout=0.05)
            except queue.Empty:
                continue
            if item is not None:
                pool.put(item[0])
        t_r.join()
        # empty volumes never enter the stream; commit them here, and on
        # any error roll every uncommitted volume back
        for job in jobs:
            try:
                if not errors and not job.committed and job.drained_all():
                    job.finalize()
            except BaseException as e:
                errors.append(e)
        for job in jobs:
            if errors and not job.committed:
                job.abort()
            job.release()
        _netflow.reset(_flow_token)
        stats["wall_s"] = time.perf_counter() - t_wall
        # analytic stage bytes (the layout fixes them; zero hot-path
        # cost): the occupancy timeline gets achieved GB/s per stage.
        # Only COMMITTED volumes' bytes count — an aborted half-run must
        # not credit the full planned bytes and report achieved GB/s
        # (even ceiling_frac > 1) the hardware never moved
        done_jobs = [j for j in jobs if j.committed]
        _book_stage_bytes(pjob, stats,
                          sum(j.dat_size for j in done_jobs),
                          layout.PARITY_SHARDS *
                          sum(j.shard_size for j in done_jobs))
        pjob.finish(errors[0] if errors else None)
    if errors:
        raise errors[0]
    for job in jobs:
        if job.writers.errors:
            raise job.writers.errors[0]
    stats["volumes"] = len(jobs)
    stats["units"] = sum(j.units_read for j in jobs)
    frac = overlap_fraction(stats)
    if frac is not None:
        stats["overlap_frac"] = frac
    return {"volumes": {j.base: {"bytes": j.dat_size,
                                 "shard_size": j.shard_size}
                        for j in jobs},
            "bytes": stats["bytes"], "units": stats["units"],
            "wall_s": round(stats["wall_s"], 4)}
