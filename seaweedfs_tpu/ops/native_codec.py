"""CPU codec over the native C++ AVX2 GF(2^8) kernels (native.py).

The host-side twin of ops.gfmat_jax / ops.pallas_gf with the same
encode/reconstruct surface but numpy arrays in and out.  Fills the role
klauspost/reedsolomon's SIMD assembly plays in the reference (invoked from
weed/storage/erasure_coding/ec_encoder.go:214 enc.Encode and
weed/storage/store_ec.go:374 enc.ReconstructData): the fast path when no
TPU is attached, and the honest CPU baseline for bench.py.

Code-generic like codec_base: anything with k/m/n, `parity_matrix` and
`decode_matrix` plugs in; non-MDS codes steer survivor choice through
their `decode_select` hook.
"""

from __future__ import annotations

import collections

from seaweedfs_tpu import native
from seaweedfs_tpu.models import rs
from seaweedfs_tpu.ops import codec_base

import numpy as np


class NativeRSCodec:
    host_backend = True  # dispatch.py routes through native.gf_matmul

    def __init__(self, code):
        self.code = code
        self.k, self.m, self.n = code.k, code.m, code.n
        self._decode_cache: collections.OrderedDict = collections.OrderedDict()

    def encode_parity(self, data: np.ndarray) -> np.ndarray:
        """[k, n] data -> [m, n] parity."""
        return native.gf_matmul(self.code.parity_matrix, np.asarray(data))

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        return np.concatenate([data, self.encode_parity(data)], axis=0)

    def reconstruct(self, shards: dict[int, np.ndarray],
                    wanted: list[int] | None = None) -> dict[int, np.ndarray]:
        present = tuple(sorted(shards))
        if wanted is None:
            wanted = [i for i in range(self.n) if i not in shards]
        if not wanted:
            return {}
        basis = codec_base.select_survivors(self.code, present, list(wanted))
        key = (basis, tuple(wanted))
        mat = self._decode_cache.get(key)
        if mat is None:
            mat = self.code.decode_matrix(list(present), list(wanted))
            self._decode_cache[key] = mat
            while len(self._decode_cache) > codec_base.decode_cache_cap():
                self._decode_cache.popitem(last=False)
        else:
            self._decode_cache.move_to_end(key)
        stack = np.stack([np.asarray(shards[i]) for i in basis])
        out = native.gf_matmul(mat, stack)
        return {w: out[i] for i, w in enumerate(wanted)}


_CODECS: dict = {}


def get_codec(k: int, m: int, construction: str = "vandermonde") -> NativeRSCodec:
    key = (k, m, construction)
    c = _CODECS.get(key)
    if c is None:
        c = NativeRSCodec(rs.get_code(k, m, construction))
        _CODECS[key] = c
    return c
