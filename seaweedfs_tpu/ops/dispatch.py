"""Backend dispatch seam between the EC data path and the codec registry.

The storage layer (storage/ec/ec_files.py) needs exactly three
capabilities from whatever codec `_get_codec` hands it: dispatch a parity
encode, materialise the result on the host, and reconstruct missing rows.
The backends differ in a way that matters to the I/O engine — host codecs
(native C++ / numpy) compute eagerly and return numpy, while JAX device
codecs dispatch asynchronously and return an un-materialised device array
whose d2h transfer is the sync point.  Centralising the isinstance
fan-out here keeps the storage layer free of backend imports and gives
the overlapped pipeline one seam to time the sync point through.

Every call also feeds the per-kernel profile (stats/profile.KERNELS):
host wall, H2D conversion, `block_until_ready` device time, and D2H
transfer are recorded separately per entry point, so a 225 ms `encode`
span finally decomposes into matmul vs transfer vs host codec time at
/debug/pprof?format=table.
"""

from __future__ import annotations

import time

import numpy as np

from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.stats.profile import KERNELS


def _host_classes():
    from seaweedfs_tpu.models.rs import RSCode
    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    return NativeRSCodec, RSCode


def _is_host(codec) -> bool:
    """Eager host backend: computes synchronously, numpy in/out.  The
    native AVX2 shell plus anything flagged `host_backend` (the MSR
    file wrapper and the registry's numpy shell propagate the flag so
    wrapped codecs route like the shell they wrap)."""
    NativeRSCodec, _ = _host_classes()
    return isinstance(codec, NativeRSCodec) or getattr(
        codec, "host_backend", False)


def _is_numpy_ref(codec) -> bool:
    """Bare reference code object (RSCode / LRCCode): no backend shell,
    just encode_numpy / reconstruct_numpy."""
    return hasattr(codec, "encode_numpy") and not hasattr(codec, "_factory")


def dispatch_parity(codec, batch: np.ndarray):
    """Dispatch [k, B] -> [m, B] parity. JAX backends return the device
    array WITHOUT materialising it; host backends compute eagerly."""
    if _is_host(codec):
        with trace.span("codec.dispatch_parity", backend="host",
                        bytes=batch.nbytes), \
                KERNELS.timed("encode_parity", nbytes=batch.nbytes):
            return codec.encode_parity(batch)
    if _is_numpy_ref(codec):
        with trace.span("codec.dispatch_parity", backend="host",
                        bytes=batch.nbytes), \
                KERNELS.timed("encode_parity", nbytes=batch.nbytes):
            return codec.encode_numpy(batch)[codec.k:]
    import jax.numpy as jnp
    # a device dispatch returns un-materialised: this span times only the
    # h2d + async enqueue — the sync cost shows up under codec.d2h
    with trace.span("codec.dispatch_parity", backend="device",
                    bytes=batch.nbytes):
        t0 = time.perf_counter()
        dev = jnp.asarray(batch)
        t1 = time.perf_counter()
        out = codec.encode_parity(dev)
        KERNELS.record("encode_parity", "device",
                       wall_s=time.perf_counter() - t1,
                       h2d_s=t1 - t0, h2d_bytes=batch.nbytes,
                       nbytes=batch.nbytes)
        return out


def materialize(parity, kernel: str = "encode_parity") -> np.ndarray:
    """Sync point of an async dispatch: host backends already returned
    numpy; device arrays `block_until_ready` (device time, attributed to
    `kernel`) and then transfer d2h here."""
    if isinstance(parity, np.ndarray):
        return parity
    nbytes = getattr(parity, "nbytes", 0)
    with trace.span("codec.d2h", bytes=nbytes):
        t0 = time.perf_counter()
        if hasattr(parity, "block_until_ready"):
            parity.block_until_ready()
        t1 = time.perf_counter()
        out = np.asarray(parity)
        KERNELS.record(kernel, "device", calls=0,
                       device_s=t1 - t0,
                       d2h_s=time.perf_counter() - t1, d2h_bytes=nbytes)
        return out


def dispatch_parity_batch(codec, units, placed=None):
    """Dispatch a [U, k, B] unit batch -> [U, m, B] parity in ONE kernel
    launch — the fleet-conversion hot path (ops/fleet_convert.py).

    `placed`, when given, is the already-device-resident (and, on a mesh,
    unit-sharded) twin of the host batch `units`: the pipeline H2Ds
    through the encoder's matched in_sharding up front so the dispatch
    never reshards.  Host backends loop eagerly per unit (they have no
    batch geometry to win; the pipeline's value there is the interleaved
    I/O).  Device dispatches return un-materialised; `unit_parity_shards`
    is the streaming sync point."""
    nbytes = units.nbytes
    if _is_host(codec) or _is_numpy_ref(codec):
        with trace.span("codec.dispatch_parity_batch", backend="host",
                        bytes=nbytes), \
                KERNELS.timed("fleet_encode", nbytes=nbytes):
            if _is_numpy_ref(codec):
                return np.stack([codec.encode_numpy(units[u])[codec.k:]
                                 for u in range(units.shape[0])], axis=0)
            batched = getattr(codec, "encode_parity_batch", None)
            if batched is not None:
                return batched(units)
            return np.stack([codec.encode_parity(units[u])
                             for u in range(units.shape[0])], axis=0)
    import jax.numpy as jnp
    with trace.span("codec.dispatch_parity_batch", backend="device",
                    bytes=nbytes):
        t0 = time.perf_counter()
        # the H2D is booked exactly once: by the mesh place() seam when
        # one exists (whether the caller pre-placed or we place here),
        # else by this record — double-booking would inflate the
        # fleet_encode h2d roofline row 2x
        booked_by_place = placed is not None
        if placed is None:
            place = getattr(codec, "place", None)
            if place is not None:
                placed = place(units)
                booked_by_place = True
            else:
                placed = jnp.asarray(units)
        t1 = time.perf_counter()
        out = codec.encode_parity_batch(placed)
        KERNELS.record("fleet_encode", "device",
                       wall_s=time.perf_counter() - t1,
                       h2d_s=0.0 if booked_by_place else t1 - t0,
                       h2d_bytes=0.0 if booked_by_place else nbytes,
                       nbytes=nbytes)
        return out


def unit_parity_shards(parity, kernel: str = "fleet_encode"):
    """Streaming sync point of a batched dispatch: yield
    (unit_start, unit_stop, np.ndarray) per device-local block as each
    block's D2H completes — on a mesh the drain hands shards to their
    writers as they come off each chip instead of waiting for a full
    gather.  Host arrays yield one block immediately."""
    if isinstance(parity, np.ndarray):
        yield 0, parity.shape[0], parity
        return
    nbytes = getattr(parity, "nbytes", 0)
    with trace.span("codec.d2h", bytes=nbytes, streamed=True):
        t0 = time.perf_counter()
        if hasattr(parity, "block_until_ready"):
            parity.block_until_ready()
        t1 = time.perf_counter()
        KERNELS.record(kernel, "device", calls=0, device_s=t1 - t0)
        shards = getattr(parity, "addressable_shards", None)
        if not shards:
            out = np.asarray(parity)
            KERNELS.record(kernel, "device", calls=0,
                           d2h_s=time.perf_counter() - t1,
                           d2h_bytes=out.nbytes)
            yield 0, out.shape[0], out
            return
        for sh in sorted(shards, key=lambda s: s.index[0].start or 0):
            start = sh.index[0].start or 0
            t2 = time.perf_counter()
            data = np.asarray(sh.data)
            KERNELS.record(kernel, "device", calls=0,
                           d2h_s=time.perf_counter() - t2,
                           d2h_bytes=data.nbytes)
            yield int(start), int(start) + data.shape[0], data


def parity_mismatch(codec, data: np.ndarray,
                    parity_rows: dict[int, np.ndarray]
                    ) -> dict[int, np.ndarray]:
    """Scrub seam: recompute the parity of a [k, B] data-stripe window
    through the SAME backend dispatch the encoder uses and compare
    against the stored parity bytes.  Returns a boolean mismatch mask
    per supplied parity row (row index is parity-relative: 0..m-1).
    One dispatch verifies the whole window — RS(10,4) syndrome checking
    IS a batched GF(2^8) matmul, the workload this seam accelerates.
    (Profiled under `encode_parity` — it runs the encode kernel.)"""
    expect = materialize(dispatch_parity(codec, data))
    return {r: np.not_equal(expect[r],
                            np.frombuffer(stored, dtype=np.uint8)
                            if isinstance(stored, (bytes, bytearray))
                            else stored)
            for r, stored in parity_rows.items()}


# device-side matrix applies for the reduced-read repair plane
# (ops/regen.py): the coefficient matrices are tiny ([1, j] slices of a
# decode matrix) but arbitrary, so device backends pre-lift each one to
# its bit-matrix via the codec's matrix_apply factory and cache it —
# repair plans reuse the same few windows for a whole shard
_APPLY_CACHE: dict = {}
_APPLY_CACHE_MAX = 64


def apply_matrix(codec, C: np.ndarray, stack: np.ndarray) -> np.ndarray:
    """out[r, n] = C[r, j] @ stack[j, n] over GF(2^8) through the same
    backend seam as encode/reconstruct — the partial-sum kernel of the
    reduced-read repair path (profiled as `repair_partial`)."""
    C = np.ascontiguousarray(C, dtype=np.uint8)
    nbytes = stack.nbytes
    if _is_host(codec):
        from seaweedfs_tpu import native
        with trace.span("codec.apply_matrix", backend="host",
                        bytes=nbytes), \
                KERNELS.timed("repair_partial", nbytes=nbytes):
            if native.available():
                return native.gf_matmul(C, np.ascontiguousarray(stack))
            from seaweedfs_tpu.ops import gf
            return gf.gf_matmul(C, stack)
    factory = getattr(codec, "_factory", None)
    if _is_numpy_ref(codec) or factory is None:
        from seaweedfs_tpu.ops import gf
        with trace.span("codec.apply_matrix", backend="host",
                        bytes=nbytes), \
                KERNELS.timed("repair_partial", nbytes=nbytes):
            return gf.gf_matmul(C, stack)
    key = (id(codec), C.shape, C.tobytes())
    mat = _APPLY_CACHE.get(key)
    if mat is None:
        if len(_APPLY_CACHE) >= _APPLY_CACHE_MAX:
            _APPLY_CACHE.clear()
        mat = _APPLY_CACHE[key] = factory(C)
    import jax.numpy as jnp
    with trace.span("codec.apply_matrix", backend="device", bytes=nbytes):
        t0 = time.perf_counter()
        dev = jnp.asarray(stack)
        t1 = time.perf_counter()
        out = mat(dev)
        t2 = time.perf_counter()
        host = np.asarray(out)
        KERNELS.record("repair_partial", "device",
                       wall_s=t2 - t1, h2d_s=t1 - t0, h2d_bytes=nbytes,
                       d2h_s=time.perf_counter() - t2,
                       d2h_bytes=host.nbytes, nbytes=nbytes)
        return host


def reconstruct_batch(codec, shards: dict[int, np.ndarray],
                      wanted: list[int]) -> dict[int, np.ndarray]:
    """Rebuild `wanted` shard rows from >=k survivor rows (host bytes
    in/out)."""
    nbytes = sum(v.nbytes for v in shards.values())
    if _is_host(codec):
        with trace.span("codec.reconstruct", backend="host",
                        bytes=nbytes, wanted=len(wanted)), \
                KERNELS.timed("reconstruct", nbytes=nbytes):
            return codec.reconstruct(shards, wanted=wanted)
    if _is_numpy_ref(codec):
        with trace.span("codec.reconstruct", backend="host",
                        bytes=nbytes, wanted=len(wanted)), \
                KERNELS.timed("reconstruct", nbytes=nbytes):
            return codec.reconstruct_numpy(shards, wanted=wanted)
    import jax.numpy as jnp
    with trace.span("codec.reconstruct", backend="device",
                    bytes=nbytes, wanted=len(wanted)):
        t0 = time.perf_counter()
        dev = {i: jnp.asarray(v) for i, v in shards.items()}
        t1 = time.perf_counter()
        out = codec.reconstruct(dev, wanted=wanted)
        t2 = time.perf_counter()
        host = {i: np.asarray(v) for i, v in out.items()}
        KERNELS.record("reconstruct", "device",
                       wall_s=t2 - t1, h2d_s=t1 - t0, h2d_bytes=nbytes,
                       d2h_s=time.perf_counter() - t2,
                       d2h_bytes=sum(v.nbytes for v in host.values()),
                       nbytes=nbytes)
        return host
