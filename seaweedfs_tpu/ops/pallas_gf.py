"""Fused Pallas TPU kernel for the bit-sliced GF(2^8) matmul.

The XLA path (`ops.gfmat_jax`) materialises the 8x bit-plane expansion in
HBM; this kernel keeps it in VMEM. Each grid step DMAs a [k, TN] byte tile,
unpacks bit-planes in VMEM, runs one int8 MXU dot against the pre-lifted
coding matrix, folds parity-mask + repack into the epilogue, and writes only
the [m, TN] output bytes — HBM traffic is the information-theoretic minimum.

Measured on v5e-1 (RS(10,4), 640MB/iter, BENCH_r04): 336.5 GB/s of data
encoded vs ~90 GB/s for the XLA path and ~6.4 GB/s for the AVX2 CPU kernel
(klauspost/reedsolomon scheme driven by weed/storage/erasure_coding/
ec_encoder.go; ~0.84 GB/s in the reference's full file-I/O shape).

Kernel-shape notes (why it looks the way it does):
- Bit extraction is `(x & (1<<s)) != 0`: Mosaic has no 8-bit shifts
  (`arith.shrui` on i8 fails to legalize) but and/cmp/select are native and
  uint8 lanes are 4x-packed, so this is the cheapest unpack.
- Bit-planes are *plane-major* (all of bit s for every shard, then bit s+1)
  and each plane is padded to KPAD=16 sublanes: concatenation then happens on
  16-sublane-aligned int8 blocks, which Mosaic lays out without relayout
  copies. The coding bit-matrix gets matching zero columns (free MXU work —
  the MXU is nowhere near the bottleneck; the VPU unpack is).
- The dot is int8 x int8 -> int32: 0/1 operands, sums bounded by 8k <= 128,
  exact. preferred_element_type=int8 trips a Mosaic verifier bug; int32 also
  keeps the <<r repack shifts legal (no 8-bit shifts, see above).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.ops import codec_base, gf

DEFAULT_TILE = 32768  # interpreter/CPU default: small pads for small inputs
TPU_TILE = 131072  # measured best on v5e (round-5 sweep: ~+25% over 32K;
#                    256K regresses — xbits VMEM block passes 16MB)
# candidate byte-column tiles for the bench re-tune sweep
# (bench._bench_tile_sweep): the r04->r05 swing (336 -> 108 GB/s) showed
# the best tile is a property of the chip + runtime, not the repo, so
# every TPU bench run re-measures and records its choice instead of
# trusting a constant picked under different weather
SWEEP_TILES = (32768, 65536, 131072, 262144)
PLANE_PAD = 16  # sublane alignment for each bit-plane block


def resolved_tile(tile: int | None = None) -> int:
    """The tile a codec will actually use: explicit argument, else the
    WEEDTPU_EC_TILE env override (how the bench sweep's winning config —
    and an operator pinning a known-good shape — reaches every codec
    constructed afterwards), else the persisted tile pin from the last
    bench sweep when its backend/chip fingerprint matches THIS runtime
    (a pin measured on different hardware must not leak in), else the
    backend default."""
    if tile is not None:
        return tile
    import os
    env = os.environ.get("WEEDTPU_EC_TILE")
    if env:
        try:
            t = int(env)
            if t > 0:
                return t
        except ValueError:
            pass
    pin = load_tile_pin()
    if pin and pin.get("tile") and \
            pin.get("fingerprint") == chip_fingerprint():
        return int(pin["tile"])
    return TPU_TILE if jax.default_backend() == "tpu" else DEFAULT_TILE


# -- tile pin: the bench sweep's winner, persisted with provenance --------
#
# The r04->r05 collapse (336 -> 108 GB/s) was a pinned tile constant
# nobody re-measured.  The sweep now records its winner + the measured
# sweep table + a backend/chip fingerprint; resolved_tile() honours a
# matching pin, and the tile-drift sentinel (stats/pipeline.py)
# re-validates it in the background so a pin that stops winning fires
# an alert instead of shipping a silent 3x loss.

_fingerprint: str | None = None


def chip_fingerprint() -> str:
    """backend:device-kind:device-count — what a tile measurement is a
    property of.  A pin recorded under a different fingerprint is
    provenance-only (never applied, never alerted against).  Memoized:
    the device set is fixed per process, and resolved_tile() consults
    this from codec-lookup paths."""
    global _fingerprint
    if _fingerprint is not None:
        return _fingerprint
    try:
        devs = jax.devices()
        kind = devs[0].device_kind if devs else "none"
        _fingerprint = f"{jax.default_backend()}:{kind}:{len(devs)}"
        return _fingerprint
    except Exception:
        return "unknown"


def pin_path(path: str | None = None) -> str:
    import os
    return path or os.environ.get("WEEDTPU_TILE_PIN") or \
        os.path.join(os.path.expanduser("~"), ".weedtpu_tile_pin.json")


def save_tile_pin(tile: int, gbps: float, sweep: dict | None = None,
                  path: str | None = None) -> str:
    """Persist the sweep winner (atomically: tmp + rename) for
    resolved_tile() and the drift sentinel.  Returns the path written."""
    import json
    import os
    p = pin_path(path)
    rec = {"tile": int(tile), "gbps": round(float(gbps), 3),
           "fingerprint": chip_fingerprint(),
           "ts": time.time()}
    if sweep:
        rec["sweep"] = {str(k): v for k, v in sweep.items()}
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, p)
    return p


_pin_cache: dict[str, tuple[tuple, dict | None]] = {}


def load_tile_pin(path: str | None = None) -> dict | None:
    """Read the persisted pin, cached by (mtime, size, inode) — this
    rides resolved_tile() and therefore codec-lookup hot paths (the
    degraded-read engine constructs codecs per reconstruct batch), so
    a stat() must be the steady-state cost, not open+json.load.  A
    save_tile_pin/direct rewrite changes the stat key and refreshes."""
    import json
    import os
    p = pin_path(path)
    try:
        st = os.stat(p)
    except OSError:
        _pin_cache.pop(p, None)
        return None
    key = (st.st_mtime_ns, st.st_size, st.st_ino)
    hit = _pin_cache.get(p)
    if hit is not None and hit[0] == key:
        rec = hit[1]
        return dict(rec) if rec is not None else None
    try:
        with open(p) as f:
            rec = json.load(f)
    except OSError:
        # raced away between stat and open: don't cache, re-stat next
        return None
    except ValueError:
        # a corrupt pin caches as None under its stat key — hot-path
        # callers must not re-parse the same broken bytes per lookup
        rec = None
    rec = rec if isinstance(rec, dict) and rec.get("tile") else None
    _pin_cache[p] = (key, rec)
    # callers may annotate/mutate the verdict they build from this —
    # hand out a copy so the cache stays pristine
    return dict(rec) if rec is not None else None


def micro_sweep(k: int = 10, m: int = 4, n: int | None = None,
                iters: int = 3,
                ensure_tile: int | None = None) -> dict[int, float]:
    """Cheap re-measure of every SWEEP_TILES candidate on this chip:
    {tile: GB/s}.  One LCM-of-tiles column width (~256K columns, a few
    MB per candidate) and a handful of iterations — enough to rank
    tiles, deliberately far from bench depth; the sentinel compares
    candidates against each other under identical conditions, so the
    absolute numbers need not match the bench's."""
    from seaweedfs_tpu.models import rs
    code = rs.get_code(k, m)
    # the sentinel passes its pinned tile: a pin outside SWEEP_TILES
    # (tiny CPU sweeps, a later-release re-tune of the candidate set,
    # an operator pin) must still be a measured candidate with n a
    # multiple of it, or the sweep can never validate the very pin it
    # watches — permanent sweep_failed silently disarms tile_pin_stale
    tiles = sorted(set(SWEEP_TILES) |
                   ({int(ensure_tile)} if ensure_tile else set()))
    if n is None:
        n = max(SWEEP_TILES)
        if jax.default_backend() != "tpu":
            n = min(SWEEP_TILES)  # the interpreter is the emulator: tiny
        if ensure_tile:
            t = int(ensure_tile)
            if t > n:
                n = t
            elif n % t:
                n = (n // t) * t  # other candidates may drop out
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (k, n), dtype=np.uint8))
    out: dict[int, float] = {}
    for t in tiles:
        if n % t:
            continue
        try:
            codec = PallasRSCodec(code, tile=t)
            codec.encode_parity(data).block_until_ready()  # compile/warm
            t0 = time.perf_counter()
            for _ in range(iters):
                codec.encode_parity(data).block_until_ready()
            el = (time.perf_counter() - t0) / iters
        except Exception:
            continue  # a tile whose VMEM blocks don't fit just drops out
        if el > 0:
            out[t] = k * n / 1e9 / el
    return out


def gf_matrix_to_bitmatrix_planemajor(C: np.ndarray, kpad: int | None = None) -> np.ndarray:
    """[m,k] GF(2^8) matrix -> [8m, 8*kpad] 0/1 matrix, plane-major:
    out[r*m + i, s*kpad + j] = bit r of (C[i,j] * 2^s); columns j >= k are 0.
    """
    C = np.asarray(C, dtype=np.uint8)
    m, k = C.shape
    if kpad is None:
        kpad = k
    assert kpad >= k
    out = np.zeros((8 * m, 8 * kpad), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            M = gf.gf_mul_bitmatrix(int(C[i, j]))  # [bit r, plane s]
            for r in range(8):
                for s in range(8):
                    out[r * m + i, s * kpad + j] = M[r, s]
    return out


def _gf_body(bitmat, x, *, k: int, m: int, kpad: int):
    """The fused unpack -> MXU dot -> repack body on VMEM-resident arrays:
    x is one [k, TN] uint8 tile, bitmat the [8m, 8*kpad] plane-major lift."""
    zpad = jnp.zeros((kpad - k, x.shape[1]), jnp.int8)
    planes = []
    for s in range(8):
        p = ((x & jnp.uint8(1 << s)) != 0).astype(jnp.int8)
        planes.append(p if kpad == k else jnp.concatenate([p, zpad], axis=0))
    xbits = jnp.concatenate(planes, axis=0)  # [8*kpad, TN] int8 0/1
    acc = jnp.dot(bitmat, xbits, preferred_element_type=jnp.int32)
    acc = acc & 1  # [8m, TN] parity bits, plane-major
    byte = acc[0:m]
    for r in range(1, 8):
        byte = byte | (acc[r * m : (r + 1) * m] << r)
    return byte.astype(jnp.uint8)


def _gf_apply_kernel(bitmat_ref, x_ref, o_ref, *, k: int, m: int, kpad: int):
    o_ref[:] = _gf_body(bitmat_ref[:], x_ref[:], k=k, m=m, kpad=kpad)


@functools.partial(jax.jit, static_argnames=("k", "m", "kpad", "tile", "interpret"))
def _gf_apply(bitmat: jax.Array, data: jax.Array, k: int, m: int, kpad: int,
              tile: int, interpret: bool) -> jax.Array:
    _, n = data.shape
    assert n % tile == 0, (n, tile)
    kernel = functools.partial(_gf_apply_kernel, k=k, m=m, kpad=kpad)
    return pl.pallas_call(
        kernel,
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec((8 * m, 8 * kpad), lambda i: (0, 0)),  # VMEM-resident
            pl.BlockSpec((k, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(bitmat, data)


def _gf_apply_batch_kernel(bitmat_ref, x_ref, o_ref, *, k: int, m: int,
                           kpad: int):
    # block shapes carry a leading unit-batch dim of 1; squeeze it through
    # the same fused body
    o_ref[0] = _gf_body(bitmat_ref[:], x_ref[0], k=k, m=m, kpad=kpad)


@functools.partial(jax.jit, static_argnames=("k", "m", "kpad", "tile",
                                             "interpret"))
def _gf_apply_batch(bitmat: jax.Array, data: jax.Array, k: int, m: int,
                    kpad: int, tile: int, interpret: bool) -> jax.Array:
    """Unit-batch geometry: [U, k, n] -> [U, m, n] in ONE pallas_call with
    a (U, n//tile) grid — the fleet-conversion stream encodes a whole
    interleaved multi-volume unit batch per dispatch instead of paying a
    kernel launch (and a host round-trip through the dispatch seam) per
    unit.  Both grid axes are parallel: units are independent stripes and
    the GF matmul is column-local."""
    U, _, n = data.shape
    assert n % tile == 0, (n, tile)
    kernel = functools.partial(_gf_apply_batch_kernel, k=k, m=m, kpad=kpad)
    return pl.pallas_call(
        kernel,
        grid=(U, n // tile),
        in_specs=[
            pl.BlockSpec((8 * m, 8 * kpad), lambda u, i: (0, 0)),
            pl.BlockSpec((1, k, tile), lambda u, i: (u, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, m, tile), lambda u, i: (u, 0, i)),
        out_shape=jax.ShapeDtypeStruct((U, m, n), jnp.uint8),
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(bitmat, data)


class PallasGFMatrix:
    """Fixed GF(2^8) matrix applied via the fused kernel.

    Pads the byte-column count up to the tile size internally; for bulk EC
    work callers should feed tile-aligned spans (the EC block sizes — 1GB/1MB,
    reference weed/storage/erasure_coding/ec_encoder.go:21-22 — are all
    tile-multiples).
    """

    def __init__(self, C: np.ndarray, tile: int | None = None,
                 interpret: bool | None = None):
        self.C = np.asarray(C, dtype=np.uint8)
        self.m, self.k = self.C.shape
        self.kpad = max(PLANE_PAD, -(-self.k // PLANE_PAD) * PLANE_PAD)
        self.tile = resolved_tile(tile)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = interpret
        self.bitmat = jnp.asarray(
            gf_matrix_to_bitmatrix_planemajor(self.C, self.kpad), dtype=jnp.int8)

    def __call__(self, data: jax.Array) -> jax.Array:
        k, n = data.shape
        assert k == self.k, (k, self.k)
        pad = (-n) % self.tile
        if pad:
            data = jnp.pad(data, ((0, 0), (0, pad)))
        out = _gf_apply(self.bitmat, data, self.k, self.m, self.kpad,
                        self.tile, self.interpret)
        return out[:, :n] if pad else out

    def apply_batch(self, data: jax.Array) -> jax.Array:
        """[U, k, n] unit batch -> [U, m, n] parity in one kernel launch
        (grid over units x column tiles)."""
        U, k, n = data.shape
        assert k == self.k, (k, self.k)
        pad = (-n) % self.tile
        if pad:
            data = jnp.pad(data, ((0, 0), (0, 0), (0, pad)))
        out = _gf_apply_batch(self.bitmat, data, self.k, self.m, self.kpad,
                              self.tile, self.interpret)
        return out[:, :, :n] if pad else out


class PallasRSCodec(codec_base.RSCodecBase):
    """Fused-kernel RS codec: `RSCodecBase` over `PallasGFMatrix` applies."""

    def __init__(self, code, tile: int | None = None,
                 interpret: bool | None = None):
        super().__init__(
            code, lambda C: PallasGFMatrix(C, tile, interpret))
        self.tile = self._parity.tile
        self.interpret = self._parity.interpret


@functools.lru_cache(maxsize=16)
def _get_codec_cached(k: int, m: int, construction: str,
                      tile: int) -> PallasRSCodec:
    from seaweedfs_tpu.models import rs
    return PallasRSCodec(rs.get_code(k, m, construction), tile)


def get_codec(k: int, m: int, construction: str = "vandermonde",
              tile: int | None = None) -> PallasRSCodec:
    """tile=None resolves via WEEDTPU_EC_TILE (the bench sweep's recorded
    winner) and then per backend: the big TPU tile for real chips, the
    small default under the (CPU) interpreter where column padding to
    the tile width is pure waste."""
    return _get_codec_cached(k, m, construction, resolved_tile(tile))
