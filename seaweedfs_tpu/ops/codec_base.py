"""Shared RS codec shell: encode/reconstruct orchestration over a
matrix-apply backend (XLA bit-sliced or fused Pallas).

Survivor selection and decode-matrix caching live here once so the two
device backends cannot diverge. The TPU analogue of the reference's
enc.Encode / enc.Reconstruct pair (weed/storage/erasure_coding/
ec_encoder.go:214,267-277; weed/storage/store_ec.go:374-393).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class RSCodecBase:
    """Encode / reconstruct for one RS(k, m) code.

    `matrix_apply_factory(C) -> callable([k, n] bytes) -> [m, n] bytes`
    supplies the device kernel for a fixed GF(2^8) matrix C.
    """

    def __init__(self, code, matrix_apply_factory):
        self.code = code
        self.k, self.m, self.n = code.k, code.m, code.n
        self._factory = matrix_apply_factory
        self._parity = matrix_apply_factory(code.parity_matrix)
        self._decode_cache: dict = {}

    def encode_parity(self, data: jax.Array) -> jax.Array:
        """[k, n] data -> [m, n] parity (systematic: data shards unchanged)."""
        return self._parity(data)

    def encode_parity_batch(self, units: jax.Array) -> jax.Array:
        """[U, k, n] unit batch -> [U, m, n] parity in ONE device dispatch
        — the fleet-conversion fast path.  Backends whose matrix apply
        has a fused batch kernel (Pallas grid over units, XLA vmap) use
        it; anything else falls back to per-unit applies."""
        batched = getattr(self._parity, "apply_batch", None)
        if batched is not None:
            return batched(units)
        return jnp.stack([self._parity(units[u])
                          for u in range(units.shape[0])], axis=0)

    def encode(self, data: jax.Array) -> jax.Array:
        """[k, n] data -> [k+m, n] shards."""
        return jnp.concatenate([data, self.encode_parity(data)], axis=0)

    def reconstruct(self, shards: dict[int, jax.Array],
                    wanted: list[int] | None = None) -> dict[int, jax.Array]:
        """Rebuild missing shards from any >= k survivors.

        The first k survivor indices (sorted) feed the inverse matrix; the
        matrix is cached per (survivors, wanted) pattern since failure
        patterns are few in practice."""
        present = tuple(sorted(shards))
        if wanted is None:
            wanted = [i for i in range(self.n) if i not in shards]
        if not wanted:
            return {}
        key = (present[: self.k], tuple(wanted))
        mat = self._decode_cache.get(key)
        if mat is None:
            mat = self._factory(self.code.decode_matrix(list(present), list(wanted)))
            self._decode_cache[key] = mat
        stack = jnp.stack([shards[i] for i in present[: self.k]], axis=0)
        out = mat(stack)
        return {w: out[i] for i, w in enumerate(wanted)}
