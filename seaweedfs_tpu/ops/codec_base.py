"""Shared codec shell: encode/reconstruct orchestration over a
matrix-apply backend (XLA bit-sliced or fused Pallas).

Survivor selection and decode-matrix caching live here once so the two
device backends cannot diverge. The TPU analogue of the reference's
enc.Encode / enc.Reconstruct pair (weed/storage/erasure_coding/
ec_encoder.go:214,267-277; weed/storage/store_ec.go:374-393).

Codec-generic: any code object exposing k/m/n, `parity_matrix` and
`decode_matrix(available, wanted)` plugs in — RS, LRC and the MSR
inner code all ride the same shell.  Non-MDS codes additionally expose
`decode_select(available, wanted)`, which names the survivor basis the
decode matrix's columns follow (RS semantics — first k sorted
survivors — are the default when the hook is absent).
"""

from __future__ import annotations

import collections
import os

import jax
import jax.numpy as jnp


def decode_cache_cap() -> int:
    """LRU bound for per-(survivors, wanted) decode matrices.  Churny
    failure patterns multiplied by the codec family's larger key space
    (LRC bases vary per loss pattern, MSR keys are virtual-row tuples)
    would otherwise grow the cache without limit."""
    try:
        return max(1, int(os.environ.get("WEEDTPU_CODEC_DECODE_CACHE", "64")))
    except ValueError:
        return 64


def select_survivors(code, present: tuple, wanted: list[int]) -> tuple:
    """The survivor basis a decode matrix is built against: the code's
    `decode_select` when it has one, else the MDS default of the first
    k sorted survivors."""
    sel = getattr(code, "decode_select", None)
    if sel is not None:
        return tuple(sel(list(present), list(wanted)))
    return tuple(present[: code.k])


class RSCodecBase:
    """Encode / reconstruct for one fixed-matrix GF(2^8) code.

    `matrix_apply_factory(C) -> callable([k, n] bytes) -> [m, n] bytes`
    supplies the device kernel for a fixed GF(2^8) matrix C.
    """

    def __init__(self, code, matrix_apply_factory):
        self.code = code
        self.k, self.m, self.n = code.k, code.m, code.n
        self._factory = matrix_apply_factory
        self._parity = matrix_apply_factory(code.parity_matrix)
        self._decode_cache: collections.OrderedDict = collections.OrderedDict()

    def _cached_decode(self, present: tuple, wanted: tuple):
        """(basis, lifted matrix) for a survivor/wanted pattern, LRU-bounded
        by WEEDTPU_CODEC_DECODE_CACHE."""
        basis = select_survivors(self.code, present, list(wanted))
        key = (basis, wanted)
        hit = self._decode_cache.get(key)
        if hit is not None:
            self._decode_cache.move_to_end(key)
            return basis, hit
        mat = self._lift(self.code.decode_matrix(list(present), list(wanted)))
        self._decode_cache[key] = mat
        while len(self._decode_cache) > decode_cache_cap():
            self._decode_cache.popitem(last=False)
        return basis, mat

    def _lift(self, C):
        return self._factory(C)

    def encode_parity(self, data: jax.Array) -> jax.Array:
        """[k, n] data -> [m, n] parity (systematic: data shards unchanged)."""
        return self._parity(data)

    def encode_parity_batch(self, units: jax.Array) -> jax.Array:
        """[U, k, n] unit batch -> [U, m, n] parity in ONE device dispatch
        — the fleet-conversion fast path.  Backends whose matrix apply
        has a fused batch kernel (Pallas grid over units, XLA vmap) use
        it; anything else falls back to per-unit applies."""
        batched = getattr(self._parity, "apply_batch", None)
        if batched is not None:
            return batched(units)
        return jnp.stack([self._parity(units[u])
                          for u in range(units.shape[0])], axis=0)

    def encode(self, data: jax.Array) -> jax.Array:
        """[k, n] data -> [k+m, n] shards."""
        return jnp.concatenate([data, self.encode_parity(data)], axis=0)

    def reconstruct(self, shards: dict[int, jax.Array],
                    wanted: list[int] | None = None) -> dict[int, jax.Array]:
        """Rebuild missing shards from sufficient survivors.

        The code's survivor basis (first k sorted for MDS codes, the
        decode_select choice otherwise) feeds the decode matrix; the
        matrix is cached per (basis, wanted) pattern since failure
        patterns are few in practice."""
        present = tuple(sorted(shards))
        if wanted is None:
            wanted = [i for i in range(self.n) if i not in shards]
        if not wanted:
            return {}
        basis, mat = self._cached_decode(present, tuple(wanted))
        stack = jnp.stack([shards[i] for i in basis], axis=0)
        out = mat(stack)
        return {w: out[i] for i, w in enumerate(wanted)}
