"""Reduced-read shard repair: partial-sum decode plans over helper groups.

A naive single-shard rebuild reads k full shards over the network — the
fleet-scale bottleneck the Facebook warehouse study (arXiv:1309.0186)
measures, and the cost regenerating codes (arXiv:1412.3022) attack by
shipping *functions of* helper data instead of the data itself.  Our
shard files must stay byte-identical to the reference RS(10,4) layout,
so instead of a new code we exploit the linearity of the existing one:

    lost_row = sum_GF( M[0, i] * survivor_i )        (GF(2^8) sum == XOR)

The sum distributes over any partition of the survivors, so each helper
NODE computes the partial product over the shards it already holds
locally — one GF(2^8) matmul through the same ops/dispatch seam the
encoder rides — and ships a single [f, range] partial.  The rebuilder
XORs the partials.  Network cost per remote node drops from
(shards_held x range) to (f x range), exactly; the output is
byte-identical to the naive decode because exact MDS repair of a given
shard yields the same bytes from ANY k-survivor set.

With d > k helper shards available, the byte range is additionally
striped into segments with a rotating k-of-d survivor window, so each
helper reads only sub-shard ranges (~k/d of the shard) instead of its
full shard — the regenerating-code read profile — while per-node
aggregation keeps the shipped bytes at the f x range floor.  Local
shards (locality class 0) are free and always participate; the rotation
spreads the read load over the remote helpers only.

Multi-shard loss is repaired as a sequence of single-shard plans (each
rebuilt shard joins the local survivor group for the next pass); callers
fall back to the naive copy+rebuild path when fewer than k survivors
remain or a plan cannot be built.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass, field

import numpy as np

# locality classes, relative to the rebuild target: 0 = same node
# (local disk, free), 1 = same rack, 2 = same DC / other rack,
# 3 = other DC — the shared ranking/naming lives in topology
from seaweedfs_tpu.topology.topology import locality_name

# segment alignment for sub-shard striping: segments smaller than this
# cost more per-fetch orchestration than the read spread saves
DEFAULT_SEG_ALIGN = 4 * 1024 * 1024


class HelperDied(IOError):
    """A helper stopped answering mid-repair; the plan must be rebuilt
    with a substitute survivor set excluding it."""

    def __init__(self, node: str, shards: tuple[int, ...] = ()):
        super().__init__(f"helper {node or '<local>'} died"
                         + (f" (shards {list(shards)})" if shards else ""))
        self.node = node
        self.shards = tuple(shards)


@dataclass(frozen=True)
class HelperGroup:
    """Survivor shards co-located on one node.  node == "" is the
    rebuilder itself (locality 0): its reads are local preads, never
    network."""
    node: str
    shards: tuple[int, ...]
    locality: int = 3

    def replace_shards(self, shards) -> "HelperGroup":
        return HelperGroup(self.node, tuple(sorted(shards)), self.locality)


@dataclass(frozen=True)
class Part:
    """One group's contribution to one segment: coeff [f, len(shards)]
    over the group's shard rows, in `shards` order.  `post`, when set,
    is a rebuilder-side matrix applied to the helper's payload before
    the XOR accumulate — the regenerating-code shape, where helper j's
    f=1 payload expands to its rank-1 contribution R[:, j] (x) payload
    across all alpha output sub-rows."""
    group: HelperGroup
    shards: tuple[int, ...]
    coeff: np.ndarray
    post: np.ndarray | None = None


@dataclass(frozen=True)
class Segment:
    offset: int
    size: int
    parts: tuple[Part, ...]


@dataclass
class RepairPlan:
    lost: int
    k: int
    d: int
    length: int
    segments: list[Segment] = field(default_factory=list)
    # output rows per offset: 1 for scalar-row plans (RS/LRC), alpha
    # for MSR sub-packetized plans, whose offsets/lengths are in
    # SUB-ROW coordinates (file bytes / alpha)
    out_rows: int = 1

    def predicted_bytes(self) -> dict:
        """Exact repair bandwidth this plan will move, per node and per
        locality class, plus the naive full-survivor-copy baseline.
        The accounting contract: executing the plan fetches EXACTLY
        per_node[n] payload bytes from each remote node n."""
        per_node: dict[str, int] = {}
        by_loc: dict[str, int] = {}
        reads: dict[str, int] = {}
        local = 0
        for seg in self.segments:
            for part in seg.parts:
                n = part.coeff.shape[0] * seg.size
                if part.group.locality == 0:
                    local += n
                    continue
                per_node[part.group.node] = \
                    per_node.get(part.group.node, 0) + n
                name = locality_name(part.group.locality)
                by_loc[name] = by_loc.get(name, 0) + n
                reads[part.group.node] = reads.get(part.group.node, 0) + \
                    len(part.shards) * seg.size
        return {"per_node": per_node, "by_locality": by_loc,
                "remote": sum(per_node.values()), "local": local,
                "helper_reads": reads}

    def naive_remote_bytes(self, n_local: int) -> int:
        """Bytes the copy-survivors-then-rebuild baseline would move for
        this loss: (k - local survivors) full shard ranges.  Sub-row
        plans scale back up by out_rows — the baseline copies whole
        shard files, not sub-rows."""
        return max(0, self.k - n_local) * self.length * self.out_rows


def _order_survivors(groups: list[HelperGroup], exclude: set[int]
                     ) -> list[tuple[HelperGroup, int]]:
    """(group, shard) pairs ordered local-first then by ascending
    locality class — the planner's survivor preference."""
    out: list[tuple[HelperGroup, int]] = []
    for g in sorted(groups, key=lambda g: (g.locality, g.node)):
        for sid in sorted(set(g.shards)):
            if sid not in exclude:
                out.append((g, sid))
    return out


def plan_repair(code, lost: int, groups: list[HelperGroup], length: int,
                d: int | None = None,
                align: int = DEFAULT_SEG_ALIGN) -> RepairPlan:
    """Build the reduced-read plan for ONE lost shard over [0, length).

    `d` caps how many helper shards participate (None = all survivors;
    clamped to [k, available]).  With d > k the range stripes into
    rotating k-of-d windows; local shards are in every window.

    Codes exposing `repair_support(lost, available)` (LRC) steer the
    plan into the lost shard's LOCAL GROUP when it suffices: the window
    becomes the support set — fewer survivors than k, no cross-group
    fan-in — and the decode matrix follows the code's basis choice."""
    k = code.k
    entries = _order_survivors(groups, {lost})
    support_hook = getattr(code, "repair_support", None)
    k_eff = k
    if support_hook is not None:
        support = support_hook(lost, sorted({s for _, s in entries}))
        if support is not None:
            sup = set(support)
            entries = [(g, s) for g, s in entries if s in sup]
            k_eff = len(support)
    if len(entries) < k_eff:
        raise ValueError(
            f"need >= {k_eff} survivors to repair shard {lost}, "
            f"have {len(entries)}")
    d_eff = len(entries) if d is None \
        else max(k_eff, min(int(d), len(entries)))
    helpers = entries[:d_eff]
    local = [(g, s) for g, s in helpers if g.locality == 0]
    remote = [(g, s) for g, s in helpers if g.locality != 0]
    t = k_eff - len(local)
    plan = RepairPlan(lost=lost, k=k, d=d_eff, length=length)
    if length <= 0:
        return plan
    if t <= 0:
        windows = [local[:k_eff]]
    elif t >= len(remote):
        windows = [local + remote]
    else:
        # rotating exclusion over the remote tail: window s uses remote
        # helpers [s, s+t) mod |remote|, so each remote helper reads
        # ~t/|remote| of the range instead of all of it
        windows = [local + [remote[(s + j) % len(remote)]
                            for j in range(t)]
                   for s in range(len(remote))]
    # cut [0, length) into len(windows) align-floored segments; collapse
    # to fewer windows when the range is too small to stripe
    nseg = max(1, min(len(windows), -(-length // align)))
    base = (length // nseg) // align * align if nseg > 1 else length
    if nseg > 1 and base == 0:
        nseg, base = 1, length
    for s in range(nseg):
        off = s * base
        size = base if s < nseg - 1 else length - off
        win = windows[s]
        sids = sorted(sid for _, sid in win)
        # cols of M follow the code's survivor basis: all of sids for
        # MDS windows, possibly a subset in the code's preferred order
        # for non-MDS codes (LRC prunes to the rows its solve uses)
        sel = getattr(code, "decode_select", None)
        basis = list(sel(sids, [lost])) if sel is not None else sids
        M = code.decode_matrix(sids, [lost])  # [1, |basis|]
        col = {sid: i for i, sid in enumerate(basis)}
        parts: list[Part] = []
        for g in sorted({id(gr): gr for gr, _ in win}.values(),
                        key=lambda g: (g.locality, g.node)):
            mine = tuple(sorted(sid for gr, sid in win
                                if gr is g and sid in col))
            if not mine:
                continue
            coeff = np.ascontiguousarray(
                M[:, [col[sid] for sid in mine]], dtype=np.uint8)
            parts.append(Part(group=g, shards=mine, coeff=coeff))
        plan.segments.append(Segment(offset=off, size=size,
                                     parts=tuple(parts)))
    return plan


def plan_msr_repair(code, lost: int, groups: list[HelperGroup],
                    length: int, d: int | None = None,
                    align: int = DEFAULT_SEG_ALIGN) -> RepairPlan:
    """Build the regenerating-code repair plan for ONE lost MSR shard
    file over its full [0, length) byte range.

    Plan coordinates are SUB-ROWS (file bytes / alpha): shard ids in
    Parts are virtual ids `file_sid * alpha + j`, offsets and sizes are
    sub-row offsets, and the executor's read_local / fetch_partial /
    sink closures own the byte-interleave translation (a sub-range
    [o, o+s) of virtual rows is the contiguous file range
    [o*alpha, (o+s)*alpha)).

    Every one of d helpers ships ONE combined sub-row (coeff = phi_f
    per held shard, block-diagonal for multi-shard nodes) and the
    rebuilder expands each payload through its R-column `post` matrix —
    total network d/alpha shard-equivalents, the cut-set floor, vs k
    for the naive copy.  Raises ValueError when fewer than d helper
    shards survive; the caller falls back to whole-shard decode or the
    copy+rebuild path."""
    inner = getattr(code, "code", code)  # MSRFileCodec -> PMMSRCode
    a = inner.alpha
    need = inner.d if d is None else max(inner.d, int(d))
    if length % a != 0:
        raise ValueError(f"msr length {length} not a multiple of "
                         f"alpha={a}")
    sub_len = length // a
    entries = _order_survivors(groups, {lost})
    if len(entries) < need:
        raise ValueError(
            f"msr repair of shard {lost} needs {need} helpers, "
            f"have {len(entries)}")
    helpers = entries[:need]
    helper_sids = [sid for _, sid in helpers]
    phi = inner.repair_coeff(lost)                 # [1, alpha]
    R = inner.repair_matrix(lost, helper_sids)     # [alpha, d]
    col = {sid: i for i, sid in enumerate(helper_sids)}
    plan = RepairPlan(lost=lost, k=inner.k_nodes, d=need, length=sub_len,
                      out_rows=a)
    if sub_len <= 0:
        return plan
    parts: list[Part] = []
    for g in sorted({id(gr): gr for gr, _ in helpers}.values(),
                    key=lambda g: (g.locality, g.node)):
        mine = tuple(sorted(sid for gr, sid in helpers if gr is g))
        if not mine:
            continue
        c = len(mine)
        coeff = np.zeros((c, c * a), dtype=np.uint8)
        vids: list[int] = []
        for i, sid in enumerate(mine):
            coeff[i, i * a:(i + 1) * a] = phi[0]
            vids.extend(sid * a + j for j in range(a))
        post = np.ascontiguousarray(R[:, [col[sid] for sid in mine]],
                                    dtype=np.uint8)
        parts.append(Part(group=g, shards=tuple(vids),
                          coeff=np.ascontiguousarray(coeff), post=post))
    plan.segments.append(Segment(offset=0, size=sub_len,
                                 parts=tuple(parts)))
    return plan


def _xor_into(acc: np.ndarray | None, part: np.ndarray) -> np.ndarray:
    if acc is None:
        return np.array(part, copy=True)
    np.bitwise_xor(acc, part, out=acc)
    return acc


def execute_plan(codec, plan: RepairPlan, read_local, fetch_partial,
                 sink, batch_size: int, cancel=None, stats=None,
                 pool: ThreadPoolExecutor | None = None) -> None:
    """Run one plan: per batch chunk, compute the local partial through
    ops/dispatch, fetch each remote group's partial concurrently, XOR,
    and hand the rebuilt range to `sink(offset, ndarray)`.

    `read_local(sid, off, n) -> bytes|None`; a short/failed local read
    raises HelperDied("", (sid,)) so the caller replans without it.
    `fetch_partial(group, shards, coeff, off, n) -> bytes` raises
    HelperDied on transport failure.  Raises propagate mid-range — the
    caller owns tmp-file discipline, so a dead helper can never leave a
    partial shard visible."""
    from seaweedfs_tpu.ops import dispatch
    own_pool = pool is None
    remote_groups = {p.group.node for seg in plan.segments
                     for p in seg.parts if p.group.locality != 0}
    if own_pool and remote_groups:
        pool = ThreadPoolExecutor(max_workers=min(8, len(remote_groups)),
                                  thread_name_prefix="ec-partial")
    try:
        for seg in plan.segments:
            end = seg.offset + seg.size
            for off in range(seg.offset, end, batch_size):
                if cancel is not None and cancel():
                    from seaweedfs_tpu.storage.ec.ec_files import \
                        EncodeCancelled
                    raise EncodeCancelled("reduced rebuild cancelled")
                n = min(batch_size, end - off)
                futs = {}
                for part in seg.parts:
                    if part.group.locality != 0:
                        futs[pool.submit(fetch_partial, part.group,
                                         part.shards, part.coeff,
                                         off, n)] = part
                acc: np.ndarray | None = None
                for part in seg.parts:
                    if part.group.locality != 0:
                        continue
                    rows = []
                    for sid in part.shards:
                        data = read_local(sid, off, n)
                        if data is None or len(data) != n:
                            raise HelperDied("", (sid,))
                        rows.append(np.frombuffer(data, dtype=np.uint8))
                    out = dispatch.apply_matrix(codec, part.coeff,
                                                np.stack(rows))
                    if part.post is not None:
                        out = dispatch.apply_matrix(codec, part.post, out)
                    acc = _xor_into(acc, out)
                for fut in as_completed(futs):
                    part = futs[fut]
                    exc = fut.exception()
                    if exc is not None:
                        if isinstance(exc, HelperDied):
                            raise exc
                        raise HelperDied(part.group.node, part.shards) \
                            from exc
                    payload = fut.result()
                    want = part.coeff.shape[0] * n
                    if payload is None or len(payload) != want:
                        raise HelperDied(part.group.node, part.shards)
                    if stats is not None:
                        hb = stats.setdefault("helper_bytes", {})
                        hb[part.group.node] = \
                            hb.get(part.group.node, 0) + want
                        bl = stats.setdefault("by_locality", {})
                        name = locality_name(part.group.locality)
                        bl[name] = bl.get(name, 0) + want
                    arr = np.frombuffer(payload, dtype=np.uint8) \
                        .reshape(part.coeff.shape[0], n)
                    if part.post is not None:
                        arr = dispatch.apply_matrix(codec, part.post, arr)
                    acc = _xor_into(acc, arr)
                assert acc is not None, "plan segment with no parts"
                if plan.out_rows == 1:
                    sink(off, acc.reshape(-1, n)[0])
                else:
                    # sub-packetized plan: the sink receives all
                    # out_rows sub-rows of this offset window at once
                    # and interleaves them back into file bytes
                    sink(off, acc.reshape(plan.out_rows, n))
    finally:
        if own_pool and pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)


def repair_shard(code, codec, lost: int, groups: list[HelperGroup],
                 length: int, read_local, fetch_partial, sink, *,
                 d: int | None = None, batch_size: int = 16 * 1024 * 1024,
                 align: int = DEFAULT_SEG_ALIGN, cancel=None,
                 stats=None, planner=None) -> RepairPlan:
    """Repair one lost shard with automatic re-planning: when a helper
    dies mid-transfer (HelperDied), its node/shards leave the survivor
    pool and the WHOLE shard recomputes under a fresh plan — `sink`
    writes are offset-addressed and idempotent, so a restart simply
    overwrites.  Raises ValueError when fewer than k survivors remain.
    Returns the plan that completed.

    `planner` defaults to `plan_repair` (decode-window plans, with LRC
    local-group steering); MSR volumes pass `plan_msr_repair` and reuse
    the identical replan / pool / stats machinery — a helper death
    mid-regeneration substitutes survivors while >= d remain, then
    degrades to the caller's naive fallback via ValueError."""
    plan_fn = planner if planner is not None else plan_repair
    dead_nodes: set[str] = set()
    dead_shards: set[int] = set()
    pool: ThreadPoolExecutor | None = None
    try:
        while True:
            live = []
            for g in groups:
                if g.locality != 0 and g.node in dead_nodes:
                    continue
                keep = tuple(s for s in g.shards if s not in dead_shards)
                if keep:
                    live.append(g.replace_shards(keep))
            plan = plan_fn(code, lost, live, length, d=d, align=align)
            remote = {g.node for g in live if g.locality != 0}
            if pool is None and remote:
                # one pool for every attempt: a replan must not pay
                # pool teardown/spawn on top of the lost transfer
                pool = ThreadPoolExecutor(
                    max_workers=min(8, len(remote)),
                    thread_name_prefix="ec-partial")
            try:
                execute_plan(codec, plan, read_local, fetch_partial,
                             sink, batch_size, cancel=cancel,
                             stats=stats, pool=pool)
                return plan
            except HelperDied as e:
                # sub-packetized plans carry VIRTUAL shard ids
                # (file_sid * out_rows + j); survivor bookkeeping is in
                # file ids, so map back before excluding
                factor = max(1, plan.out_rows)
                file_shards = sorted({s // factor for s in e.shards})
                if stats is not None:
                    stats["replans"] = stats.get("replans", 0) + 1
                    stats.setdefault("dead_helpers", []).append(
                        {"node": e.node, "shards": file_shards})
                if e.node:
                    dead_nodes.add(e.node)
                else:
                    dead_shards.update(file_shards)
    finally:
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
