"""TPU-native GF(2^8) matrix codec via bit-slicing (XLA path).

Design (TPU-first, not a port): the reference crunches GF(2^8) with per-byte
SIMD table lookups (klauspost/reedsolomon AVX2, driven from
weed/storage/erasure_coding/ec_encoder.go:120-196). TPUs have no byte-LUT
unit, but they have an MXU. GF(2^8) is an 8-dim vector space over GF(2) and
multiplication by a constant is GF(2)-linear, so an RS coding matrix
C in GF(2^8)^{m x k} lifts to a 0/1 matrix B in {0,1}^{8m x 8k} with

    bits(C @ X) = (B @ bits(X)) mod 2.

Encode/decode/rebuild all become: unpack bytes to bit-planes, one int8
matmul on the MXU (values bounded by 8k <= 255, exact in int32/bf16-f32),
parity mask, repack. XLA fuses the unpack/mask/pack element-wise chains into
the matmul's prologue/epilogue; `ops.pallas_gf` does the same fully fused in
VMEM for the cases XLA schedules poorly.

Data layout: shards-major [k, n] uint8 — a stripe row of the EC layout
(weed/storage/erasure_coding/ec_locate.go block math) is exactly one such
matrix with n = block bytes. Batching stripes is vmap/reshape on n.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from seaweedfs_tpu.ops import codec_base, gf

_SHIFTS = tuple(range(8))


def unpack_bits(x: jax.Array) -> jax.Array:
    """[k, n] uint8 -> [8k, n] int8 bit-planes; row 8j+s holds bit s of shard j."""
    k, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)[None, :, None]
    bits = (x[:, None, :] >> shifts) & jnp.uint8(1)
    return bits.reshape(8 * k, n).astype(jnp.int8)


def pack_bits(y: jax.Array) -> jax.Array:
    """[8m, n] {0,1} -> [m, n] uint8; inverse of unpack_bits' layout."""
    m8, n = y.shape
    m = m8 // 8
    y = y.reshape(m, 8, n).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :, None]
    return jnp.sum(y * weights, axis=1, dtype=jnp.uint8)


def bitsliced_apply_body(bitmat: jax.Array, data: jax.Array) -> jax.Array:
    """y[m, n] = (C @ data) over GF(2^8), with bitmat the [8m, 8k] int8 lift
    of C. Un-jitted body, shared by the single-device codec and the
    shard_map per-device functions in parallel/mesh.py."""
    xbits = unpack_bits(data)
    # int8 x int8 -> int32 rides the MXU's integer path on v5e; values are
    # 0/1 so the popcount-parity sum is exact.
    acc = jax.lax.dot_general(
        bitmat, xbits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    ybits = jax.lax.bitwise_and(acc, 1)
    return pack_bits(ybits)


_bitsliced_apply = jax.jit(bitsliced_apply_body)


def bitsliced_apply_batch_body(bitmat: jax.Array, data: jax.Array
                               ) -> jax.Array:
    """[U, k, n] unit batch -> [U, m, n]: units are independent stripes,
    so the batch is one vmap of the bit-sliced apply (XLA batches the
    MXU dot over the leading dim).  Un-jitted, shared with the per-device
    shard_map bodies in parallel/mesh.py."""
    return jax.vmap(bitsliced_apply_body, in_axes=(None, 0))(bitmat, data)


_bitsliced_apply_batch = jax.jit(bitsliced_apply_batch_body)


class JaxGFMatrix:
    """A fixed GF(2^8) matrix, pre-lifted to its bit-matrix, applied on TPU."""

    def __init__(self, C: np.ndarray):
        self.C = np.asarray(C, dtype=np.uint8)
        self.m, self.k = self.C.shape
        self.bitmat = jnp.asarray(gf.gf_matrix_to_bitmatrix(self.C), dtype=jnp.int8)

    def __call__(self, data: jax.Array) -> jax.Array:
        """data [k, n] uint8 -> [m, n] uint8 product over GF(2^8)."""
        return _bitsliced_apply(self.bitmat, data)

    def apply_batch(self, data: jax.Array) -> jax.Array:
        """data [U, k, n] -> [U, m, n] in one dispatch."""
        return _bitsliced_apply_batch(self.bitmat, data)


class JaxRSCodec(codec_base.RSCodecBase):
    """XLA bit-sliced RS codec: `RSCodecBase` over `JaxGFMatrix` applies."""

    def __init__(self, code):
        super().__init__(code, JaxGFMatrix)


@functools.lru_cache(maxsize=16)
def get_codec(k: int, m: int, construction: str = "vandermonde") -> JaxRSCodec:
    from seaweedfs_tpu.models import rs
    return JaxRSCodec(rs.get_code(k, m, construction))
