"""ctypes bindings for the native C++ runtime library (native/weedtpu_native.cc).

The reference gets its CPU performance from native code in dependencies —
klauspost/reedsolomon's AVX2 GF(2^8) assembly (go.mod:61) for erasure coding,
Go's AES-NI stdlib for chunk encryption (weed/util/cipher.go), and hardware
CRC for checksums.  This module is the equivalent seam in this framework: a
small C++ library exposing a C ABI, compiled on first use with the in-repo
Makefile and loaded via ctypes (pybind11 is not in the image).

Falls back gracefully: `available()` is False when no compiler is present,
and callers (ops.codec registry, utils.cipher) keep a pure-Python/numpy path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_HERE, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libweedtpu_native.so")

_lib = None
_lib_err: str | None = None
_lock = threading.Lock()

_u8p = ctypes.POINTER(ctypes.c_uint8)


class NativeUnavailable(RuntimeError):
    pass


def _build() -> None:
    src = os.path.join(_NATIVE_DIR, "weedtpu_native.cc")

    def up_to_date() -> bool:
        return os.path.exists(_SO_PATH) and \
            os.path.getmtime(_SO_PATH) >= os.path.getmtime(src)

    if up_to_date():
        return
    # serialize concurrent first-use builds across processes so nobody
    # dlopens a half-written .so
    import fcntl
    lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if up_to_date():  # another process built it while we waited
                return
            subprocess.run(["make", "-C", _NATIVE_DIR, "libweedtpu_native.so"],
                           check=True, capture_output=True)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _load():
    global _lib, _lib_err
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            _build()
            lib = ctypes.CDLL(_SO_PATH)
        except (OSError, subprocess.CalledProcessError) as e:
            _lib_err = str(e)
            return None
        lib.wn_gf_init()
        lib.wn_gf_mul.restype = ctypes.c_uint8
        lib.wn_gf_mul.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
        lib.wn_gf_mul_slice.argtypes = [
            ctypes.c_uint8, _u8p, _u8p, ctypes.c_size_t, ctypes.c_int]
        lib.wn_gf_matmul.argtypes = [
            _u8p, ctypes.c_int, ctypes.c_int, _u8p, _u8p, ctypes.c_size_t]
        lib.wn_gf_matmul_ptrs.argtypes = [
            _u8p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(_u8p), ctypes.POINTER(_u8p), ctypes.c_size_t]
        lib.wn_gf_set_impl.argtypes = [ctypes.c_int]
        lib.wn_gf_impl.restype = ctypes.c_int
        lib.wn_crc32c.restype = ctypes.c_uint32
        lib.wn_crc32c.argtypes = [_u8p, ctypes.c_size_t, ctypes.c_uint32]
        lib.wn_aes256_ctr.argtypes = [_u8p, _u8p, _u8p, _u8p, ctypes.c_size_t]
        lib.wn_aes256_gcm_seal.argtypes = [
            _u8p, _u8p, _u8p, ctypes.c_size_t, _u8p, _u8p, ctypes.c_size_t, _u8p]
        lib.wn_aes256_gcm_open.restype = ctypes.c_int
        lib.wn_aes256_gcm_open.argtypes = [
            _u8p, _u8p, _u8p, ctypes.c_size_t, _u8p, _u8p, ctypes.c_size_t, _u8p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def load_error() -> str | None:
    _load()
    return _lib_err


def _require():
    lib = _load()
    if lib is None:
        raise NativeUnavailable(
            f"native library unavailable (need g++/make or a prebuilt "
            f"{_SO_PATH}): {_lib_err}")
    return lib


def _as_u8p(a) -> _u8p:
    return a.ctypes.data_as(_u8p)


GF_IMPL_AUTO, GF_IMPL_AVX2, GF_IMPL_SCALAR, GF_IMPL_GFNI = 0, 1, 2, 3


def gf_impl() -> int:
    """Active GF matmul kernel: 1=AVX2 split-table, 2=scalar, 3=GFNI+AVX512."""
    return int(_require().wn_gf_impl())


def set_gf_impl(impl: int) -> None:
    """Force a kernel (GF_IMPL_*): lets bench.py measure the AVX2 path (the
    klauspost-equivalent baseline) on GFNI hosts. GF_IMPL_AUTO restores
    best-available dispatch."""
    _require().wn_gf_set_impl(int(impl))


def gf_matmul(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[rows, n] = mat[rows, k] @ data[k, n] over GF(2^8) (native AVX2)."""
    lib = _require()
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    rows, k = mat.shape
    k2, n = data.shape
    assert k == k2, (mat.shape, data.shape)
    out = np.empty((rows, n), dtype=np.uint8)
    lib.wn_gf_matmul(_as_u8p(mat), rows, k, _as_u8p(data), _as_u8p(out),
                     ctypes.c_size_t(n))
    return out


def gf_matmul_ptrs(mat: np.ndarray, in_rows: list[np.ndarray],
                   out_rows: list[np.ndarray], n: int) -> None:
    """out_rows[r][:n] = sum_j mat[r, j] * in_rows[j][:n] over GF(2^8).

    Row buffers may be scattered (e.g. views straight into an mmap'd .dat),
    so the encode path runs with zero staging copies.  Each in_rows[j] /
    out_rows[r] must be C-contiguous uint8 with >= n bytes."""
    lib = _require()
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    rows, k = mat.shape
    assert len(in_rows) == k and len(out_rows) == rows, (mat.shape,)
    ins = (_u8p * k)(*[r.ctypes.data_as(_u8p) for r in in_rows])
    outs = (_u8p * rows)(*[r.ctypes.data_as(_u8p) for r in out_rows])
    lib.wn_gf_matmul_ptrs(_as_u8p(mat), rows, k, ins, outs,
                          ctypes.c_size_t(n))


def gf_mul_slice(c: int, src: np.ndarray, dst: np.ndarray,
                 accumulate: bool = False) -> None:
    lib = _require()
    assert src.dtype == np.uint8 and dst.dtype == np.uint8
    assert src.size == dst.size
    lib.wn_gf_mul_slice(c, _as_u8p(src), _as_u8p(dst),
                        ctypes.c_size_t(src.size), 1 if accumulate else 0)


def crc32c(data: bytes | np.ndarray, crc: int = 0) -> int:
    lib = _require()
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else data
    return int(lib.wn_crc32c(_as_u8p(np.ascontiguousarray(arr)),
                             ctypes.c_size_t(arr.size), crc))


def aes256_gcm_seal(key: bytes, nonce: bytes, plaintext: bytes,
                    aad: bytes = b"") -> bytes:
    """Returns ciphertext||tag, mirroring Go's gcm.Seal output layout that
    the reference stores for encrypted chunks (weed/util/cipher.go)."""
    lib = _require()
    assert len(key) == 32 and len(nonce) == 12
    pt = np.frombuffer(plaintext, dtype=np.uint8)
    ct = np.empty(len(plaintext), dtype=np.uint8)
    tag = np.empty(16, dtype=np.uint8)
    k = np.frombuffer(key, dtype=np.uint8)
    nc = np.frombuffer(nonce, dtype=np.uint8)
    ad = np.frombuffer(aad, dtype=np.uint8) if aad else np.empty(0, np.uint8)
    lib.wn_aes256_gcm_seal(_as_u8p(k), _as_u8p(nc), _as_u8p(ad),
                           ctypes.c_size_t(len(aad)), _as_u8p(pt), _as_u8p(ct),
                           ctypes.c_size_t(len(plaintext)), _as_u8p(tag))
    return ct.tobytes() + tag.tobytes()


def aes256_gcm_open(key: bytes, nonce: bytes, sealed: bytes,
                    aad: bytes = b"") -> bytes:
    lib = _require()
    assert len(key) == 32 and len(nonce) == 12 and len(sealed) >= 16
    ct = np.frombuffer(sealed[:-16], dtype=np.uint8)
    tag = np.frombuffer(sealed[-16:], dtype=np.uint8)
    pt = np.empty(len(ct), dtype=np.uint8)
    k = np.frombuffer(key, dtype=np.uint8)
    nc = np.frombuffer(nonce, dtype=np.uint8)
    ad = np.frombuffer(aad, dtype=np.uint8) if aad else np.empty(0, np.uint8)
    rc = lib.wn_aes256_gcm_open(_as_u8p(k), _as_u8p(nc), _as_u8p(ad),
                                ctypes.c_size_t(len(aad)), _as_u8p(ct),
                                _as_u8p(pt), ctypes.c_size_t(ct.size),
                                _as_u8p(tag))
    if rc != 0:
        raise ValueError("cipher: message authentication failed")
    return pt.tobytes()
