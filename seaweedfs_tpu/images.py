"""Image operations on the read path: EXIF orientation fix, resize, crop.

Reference: weed/images/ (orientation.go fixes JPEG EXIF rotation at
needle-create time, resizing.go serves ?width=&height=&mode= on reads,
invoked from weed/storage/needle/needle.go:101-106 and the volume read
handler).  PIL does the pixel work here.
"""

from __future__ import annotations

import io

RESIZABLE = ("image/jpeg", "image/png", "image/gif", "image/webp")


def is_image_mime(mime: str) -> bool:
    return (mime or "").lower() in RESIZABLE


def fix_orientation(data: bytes, mime: str = "image/jpeg") -> bytes:
    """Bake the EXIF orientation into the pixels (reference:
    images/orientation.go FixJpgOrientation)."""
    if mime != "image/jpeg":
        return data
    try:
        from PIL import Image, ImageOps
        img = Image.open(io.BytesIO(data))
        fixed = ImageOps.exif_transpose(img)
        if fixed is img:
            return data
        out = io.BytesIO()
        fixed.save(out, format="JPEG", quality=90)
        return out.getvalue()
    except Exception:
        return data


def resized(data: bytes, mime: str, width: int = 0, height: int = 0,
            mode: str = "") -> bytes:
    """Resize on read (reference: images/resizing.go Resized):
      mode ''    : preserve ratio within the WxH box
      mode 'fit' : pad to exactly WxH, preserving ratio
      mode 'fill': crop-to-fill exactly WxH."""
    if not (width or height) or not is_image_mime(mime):
        return data
    try:
        from PIL import Image, ImageOps
        img = Image.open(io.BytesIO(data))
        w0, h0 = img.size
        w, h = width or w0, height or h0
        if mode == "fill":
            img = ImageOps.fit(img, (w, h))
        elif mode == "fit":
            img = ImageOps.pad(img, (w, h))
        else:
            img = img.copy()
            img.thumbnail((w, h))
        fmt = {"image/jpeg": "JPEG", "image/png": "PNG", "image/gif": "GIF",
               "image/webp": "WEBP"}[mime.lower()]
        out = io.BytesIO()
        if fmt == "JPEG" and img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        img.save(out, format=fmt)
        return out.getvalue()
    except Exception:
        return data
