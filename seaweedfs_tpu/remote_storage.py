"""Remote storage tier: mirror/cache external object stores.

Reference: weed/remote_storage/ (s3/gcs/azure clients behind
RemoteStorageClient, traverse_bfs.go) + weed/filer/remote_storage.go
(mount mappings).  Cloud SDKs aren't available in this environment, so
the concrete client is LocalDirRemote (an rclone-style local adapter that
stands in for a bucket); s3/gcs/azure register the same SPI when their
SDKs exist.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from seaweedfs_tpu.security.tls import scheme as _tls_scheme


@dataclass
class RemoteEntry:
    key: str
    size: int
    mtime: float
    is_directory: bool = False


class RemoteStorageClient:
    """SPI (reference: remote_storage.go RemoteStorageClient interface)."""

    name = "abstract"

    def traverse(self, prefix: str = ""):
        """Yield RemoteEntry for every object under prefix (BFS order,
        reference: traverse_bfs.go)."""
        raise NotImplementedError

    def read_file(self, key: str) -> bytes:
        raise NotImplementedError

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        return self.read_file(key)[offset:offset + size]

    def write_file(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def upload_file(self, key: str, local_path: str) -> None:
        with open(local_path, "rb") as f:
            self.write_file(key, f.read())

    def delete_file(self, key: str) -> None:
        raise NotImplementedError


class LocalDirRemote(RemoteStorageClient):
    """A directory as the 'remote bucket' — test/dev stand-in with the
    exact semantics the cloud clients implement."""

    name = "local"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.dir, key.lstrip("/"))

    def traverse(self, prefix: str = ""):
        root = self._p(prefix)
        if not os.path.isdir(root):
            return
        for dirpath, dirnames, filenames in os.walk(root):
            rel_dir = os.path.relpath(dirpath, self.dir)
            def norm(key: str) -> str:
                key = key.replace("\\", "/")
                return key[2:] if key.startswith("./") else key

            for d in sorted(dirnames):
                yield RemoteEntry(norm(os.path.join(rel_dir, d)), 0, 0,
                                  is_directory=True)
            for f in sorted(filenames):
                p = os.path.join(dirpath, f)
                st = os.stat(p)
                yield RemoteEntry(norm(os.path.join(rel_dir, f)),
                                  st.st_size, st.st_mtime)

    def read_file(self, key: str) -> bytes:
        with open(self._p(key), "rb") as f:
            return f.read()

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with open(self._p(key), "rb") as f:
            f.seek(offset)
            return f.read(size)

    def write_file(self, key: str, data: bytes) -> None:
        p = self._p(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)

    def upload_file(self, key: str, local_path: str) -> None:
        """Streamed upload (tier-move of multi-GB .dat files must not
        buffer in RAM)."""
        import shutil
        p = self._p(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        shutil.copyfile(local_path, p)

    def delete_file(self, key: str) -> None:
        try:
            os.remove(self._p(key))
        except FileNotFoundError:
            pass


REMOTES = {"local": LocalDirRemote}


def make_remote(kind: str, **options) -> RemoteStorageClient:
    try:
        return REMOTES[kind](**options)
    except KeyError:
        raise ValueError(
            f"unknown remote {kind!r} (have {sorted(REMOTES)}; s3/gcs/azure "
            f"register here when their SDKs are installed)")


def sync_remote_to_filer(remote: RemoteStorageClient, filer_url: str,
                         mount_dir: str, cache: bool = False,
                         timeout: float = 60.0) -> int:
    """remote.mount / remote.cache: traverse the remote and materialize
    entries under mount_dir on the filer (reference:
    shell/command_remote_mount.go + filer/read_remote.go).  Without
    `cache`, files are created as zero-chunk placeholders carrying
    Seaweed-remote-* attrs; with it, content is pulled."""
    import urllib.parse
    import urllib.request
    n = 0
    for e in remote.traverse():
        path = mount_dir.rstrip("/") + "/" + e.key
        if e.is_directory:
            req = urllib.request.Request(
                f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path + '/')}",
                data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=timeout):
                pass
            continue
        headers = {
            "Seaweed-remote-size": str(e.size),
            "Seaweed-remote-mtime": str(int(e.mtime)),
            "Seaweed-remote-key": e.key,
        }
        data = remote.read_file(e.key) if cache else b""
        if not cache:
            headers["Seaweed-remote-placeholder"] = "true"
        req = urllib.request.Request(
            f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path)}",
            data=data, method="POST", headers=headers)
        with urllib.request.urlopen(req, timeout=timeout):
            pass
        n += 1
    return n
