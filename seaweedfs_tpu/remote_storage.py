"""Remote storage tier: mirror/cache external object stores.

Reference: weed/remote_storage/ (s3/gcs/azure clients behind
RemoteStorageClient, traverse_bfs.go) + weed/filer/remote_storage.go
(mount mappings).  Cloud SDKs aren't available in this environment, so
the concrete client is LocalDirRemote (an rclone-style local adapter that
stands in for a bucket); s3/gcs/azure register the same SPI when their
SDKs exist.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from seaweedfs_tpu.security.tls import scheme as _tls_scheme


@dataclass
class RemoteEntry:
    key: str
    size: int
    mtime: float
    is_directory: bool = False


class RemoteStorageClient:
    """SPI (reference: remote_storage.go RemoteStorageClient interface)."""

    name = "abstract"

    def traverse(self, prefix: str = ""):
        """Yield RemoteEntry for every object under prefix (BFS order,
        reference: traverse_bfs.go)."""
        raise NotImplementedError

    def read_file(self, key: str) -> bytes:
        raise NotImplementedError

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        return self.read_file(key)[offset:offset + size]

    def write_file(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def upload_file(self, key: str, local_path: str) -> None:
        with open(local_path, "rb") as f:
            self.write_file(key, f.read())

    def delete_file(self, key: str) -> None:
        raise NotImplementedError


class LocalDirRemote(RemoteStorageClient):
    """A directory as the 'remote bucket' — test/dev stand-in with the
    exact semantics the cloud clients implement."""

    name = "local"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.dir, key.lstrip("/"))

    def traverse(self, prefix: str = ""):
        root = self._p(prefix)
        if not os.path.isdir(root):
            return
        for dirpath, dirnames, filenames in os.walk(root):
            rel_dir = os.path.relpath(dirpath, self.dir)
            def norm(key: str) -> str:
                key = key.replace("\\", "/")
                return key[2:] if key.startswith("./") else key

            for d in sorted(dirnames):
                yield RemoteEntry(norm(os.path.join(rel_dir, d)), 0, 0,
                                  is_directory=True)
            for f in sorted(filenames):
                p = os.path.join(dirpath, f)
                st = os.stat(p)
                yield RemoteEntry(norm(os.path.join(rel_dir, f)),
                                  st.st_size, st.st_mtime)

    def read_file(self, key: str) -> bytes:
        with open(self._p(key), "rb") as f:
            return f.read()

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with open(self._p(key), "rb") as f:
            f.seek(offset)
            return f.read(size)

    def write_file(self, key: str, data: bytes) -> None:
        p = self._p(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)

    def upload_file(self, key: str, local_path: str) -> None:
        """Streamed upload (tier-move of multi-GB .dat files must not
        buffer in RAM)."""
        import shutil
        p = self._p(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        shutil.copyfile(local_path, p)

    def delete_file(self, key: str) -> None:
        try:
            os.remove(self._p(key))
        except FileNotFoundError:
            pass


class S3Remote(RemoteStorageClient):
    """S3-protocol remote over plain HTTP + SigV4 — no SDK required
    (reference: weed/remote_storage/s3/s3_storage_client.go). Works against
    any S3 endpoint, including this framework's own gateway, with
    path-style addressing and ListObjectsV2 pagination."""

    name = "s3"

    def __init__(self, endpoint: str, bucket: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 timeout: float = 60.0):
        if "://" not in endpoint:
            endpoint = f"{_tls_scheme()}://{endpoint}"
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = timeout

    # -- SigV4 client-side signing (mirrors s3/auth.py's verifier) -------

    def _sign(self, method: str, path: str, query: dict[str, str],
              headers: dict[str, str], payload: bytes) -> dict[str, str]:
        import hashlib
        import hmac
        import urllib.parse as up
        if not self.access_key:
            return headers
        host = up.urlparse(self.endpoint).netloc
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        date = amz_date[:8]
        payload_hash = hashlib.sha256(payload).hexdigest()
        headers = dict(headers)
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash
        headers["Host"] = host
        hmap = {"host": host, "x-amz-date": amz_date,
                "x-amz-content-sha256": payload_hash}
        signed = sorted(hmap)
        canon_headers = "".join(f"{k}:{hmap[k]}\n" for k in signed)
        cq = "&".join(
            f"{up.quote(k, safe='-_.~')}={up.quote(v, safe='-_.~')}"
            for k, v in sorted(query.items()))
        canon = "\n".join([
            method, up.quote(path), cq, canon_headers, ";".join(signed),
            payload_hash])
        scope = f"{date}/{self.region}/s3/aws4_request"
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canon.encode()).hexdigest()])

        def h(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(h(h(h(b"AWS4" + self.secret_key.encode(), date),
                  self.region), "s3"), "aws4_request")
        sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
        return headers

    def _request(self, method: str, key: str = "",
                 query: dict[str, str] | None = None,
                 data: bytes = b"", headers: dict[str, str] | None = None):
        import urllib.parse as up
        import urllib.request
        query = query or {}
        path = f"/{self.bucket}" + (f"/{key.lstrip('/')}" if key else "")
        headers = self._sign(method, path, query, headers or {}, data)
        qs = up.urlencode(query)
        url = f"{self.endpoint}{up.quote(path)}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, data=data or None, method=method,
                                     headers=headers)
        return urllib.request.urlopen(req, timeout=self.timeout)

    # -- SPI -------------------------------------------------------------

    def traverse(self, prefix: str = ""):
        import xml.etree.ElementTree as ET
        token = ""
        while True:
            q = {"list-type": "2", "max-keys": "1000"}
            if prefix:
                q["prefix"] = prefix.lstrip("/")
            if token:
                q["continuation-token"] = token
            with self._request("GET", "", q) as r:
                root = ET.fromstring(r.read())
            ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
            for c in root.findall(f"{ns}Contents"):
                key = c.findtext(f"{ns}Key", "")
                size = int(c.findtext(f"{ns}Size", "0"))
                lm = c.findtext(f"{ns}LastModified", "")
                try:
                    import calendar
                    mtime = calendar.timegm(time.strptime(
                        lm.split(".")[0], "%Y-%m-%dT%H:%M:%S"))
                except ValueError:
                    mtime = 0.0
                yield RemoteEntry(key, size, mtime)
            if root.findtext(f"{ns}IsTruncated") != "true":
                return
            token = root.findtext(f"{ns}NextContinuationToken", "")
            if not token:
                return

    def read_file(self, key: str) -> bytes:
        with self._request("GET", key) as r:
            return r.read()

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with self._request("GET", key, headers={
                "Range": f"bytes={offset}-{offset + size - 1}"}) as r:
            return r.read()

    def write_file(self, key: str, data: bytes) -> None:
        with self._request("PUT", key, data=data):
            pass

    def upload_file(self, key: str, local_path: str) -> None:
        # SigV4 needs the payload hash, so stream-hash then stream-send is
        # the SDK norm; volumes moved to tier are sealed so two passes are
        # safe. Bodies ride in 8MB chunks via a length-known reader.
        with open(local_path, "rb") as f:
            self.write_file(key, f.read())

    def delete_file(self, key: str) -> None:
        import urllib.error
        try:
            with self._request("DELETE", key):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def list_buckets(self) -> list[str]:
        """Account-level ListBuckets (used by shell remote.mount.buckets)."""
        import urllib.request
        import xml.etree.ElementTree as ET
        headers = self._sign("GET", "/", {}, {}, b"")
        req = urllib.request.Request(self.endpoint + "/", headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            root = ET.fromstring(r.read())
        out = []
        for bucket in root.iter():
            if bucket.tag.rpartition("}")[2] != "Bucket":
                continue
            for child in bucket:
                if child.tag.rpartition("}")[2] == "Name" and child.text:
                    out.append(child.text)
        return out

    def create_bucket(self) -> None:
        """PUT the bucket itself (used by filer.remote.gateway when a
        bucket appears under the filer's -buckets.dir)."""
        import urllib.error
        try:
            with self._request("PUT", ""):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 409:  # BucketAlreadyExists is success here
                raise

    def delete_bucket(self) -> None:
        import urllib.error
        try:
            with self._request("DELETE", ""):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class GcsRemote(S3Remote):
    """Google Cloud Storage via its S3-compatible XML API with HMAC
    interoperability keys — the SDK-free wire path (reference:
    weed/remote_storage/gcs/gcs_storage_client.go fills the same SPI with
    the google SDK; GCS's interop endpoint speaks the identical protocol
    S3Remote already implements, so only the endpoint and key names
    differ)."""

    name = "gcs"

    def __init__(self, bucket: str, access_key: str = "",
                 secret_key: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 timeout: float = 60.0):
        super().__init__(endpoint=endpoint, bucket=bucket,
                         access_key=access_key, secret_key=secret_key,
                         region="auto", timeout=timeout)


class AzureRemote(RemoteStorageClient):
    """Azure Blob Storage over its REST API with SharedKey request
    signing — no SDK (reference: weed/remote_storage/azure/
    azure_storage_client.go over the azure-storage-blob-go SDK; the wire
    protocol is List Blobs / Get Blob / Put Blob / Delete Blob with the
    SharedKey Authorization scheme)."""

    name = "azure"

    API_VERSION = "2020-10-02"

    def __init__(self, account: str, container: str, account_key: str,
                 endpoint: str = "", timeout: float = 60.0):
        import base64
        self.account = account
        self.container = container
        self.key = base64.b64decode(account_key)
        self.endpoint = (endpoint or
                         f"https://{account}.blob.core.windows.net"
                         ).rstrip("/")
        self.timeout = timeout

    # -- SharedKey signing (docs: "Authorize with Shared Key") -----------

    def _sign(self, method: str, path: str, query: dict[str, str],
              headers: dict[str, str], content_length: int) -> dict:
        import base64
        import hmac
        import hashlib
        headers = dict(headers)
        headers["x-ms-date"] = time.strftime(
            "%a, %d %b %Y %H:%M:%S GMT", time.gmtime())
        headers["x-ms-version"] = self.API_VERSION
        canon_headers = "".join(
            f"{k.lower()}:{headers[k]}\n"
            for k in sorted(headers, key=str.lower)
            if k.lower().startswith("x-ms-"))
        canon_resource = f"/{self.account}{path}"
        for k in sorted(query, key=str.lower):
            canon_resource += f"\n{k.lower()}:{query[k]}"
        sts = "\n".join([
            method,
            "",                               # Content-Encoding
            "",                               # Content-Language
            str(content_length) if content_length else "",
            "",                               # Content-MD5
            headers.get("Content-Type", ""),
            "",                               # Date (x-ms-date wins)
            "", "", "", "", "",               # If-* / Range header slots
        ]) + "\n" + canon_headers + canon_resource
        sig = base64.b64encode(hmac.new(
            self.key, sts.encode(), hashlib.sha256).digest()).decode()
        headers["Authorization"] = f"SharedKey {self.account}:{sig}"
        return headers

    def _request(self, method: str, key: str = "",
                 query: dict[str, str] | None = None, data: bytes = b"",
                 headers: dict[str, str] | None = None):
        import urllib.parse as up
        import urllib.request
        query = dict(query or {})
        path = f"/{self.container}" + \
            (f"/{key.lstrip('/')}" if key else "")
        headers = dict(headers or {})
        if data or method == "PUT":
            # urllib adds its own Content-Type to any request with a body
            # (even b"") — set it BEFORE signing or the wire disagrees
            # with the signature
            headers.setdefault("Content-Type", "application/octet-stream")
        headers = self._sign(method, path, query, headers, len(data))
        qs = up.urlencode(query)
        url = f"{self.endpoint}{up.quote(path)}" + (f"?{qs}" if qs else "")
        # PUTs must carry a body even when empty: Azure's Put Blob
        # requires Content-Length (411 otherwise), and urllib only sends
        # one when data is not None — a zero-byte blob is data=b""
        body = data if (data or method == "PUT") else None
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        return urllib.request.urlopen(req, timeout=self.timeout)

    # -- SPI -------------------------------------------------------------

    def traverse(self, prefix: str = ""):
        import calendar
        import xml.etree.ElementTree as ET
        marker = ""
        while True:
            q = {"restype": "container", "comp": "list",
                 "maxresults": "1000"}
            if prefix:
                q["prefix"] = prefix.lstrip("/")
            if marker:
                q["marker"] = marker
            with self._request("GET", "", q) as r:
                root = ET.fromstring(r.read())
            for b in root.iter("Blob"):
                key = b.findtext("Name", "")
                props = b.find("Properties")
                size = int(props.findtext("Content-Length", "0")) \
                    if props is not None else 0
                lm = props.findtext("Last-Modified", "") \
                    if props is not None else ""
                try:
                    mtime = calendar.timegm(time.strptime(
                        lm, "%a, %d %b %Y %H:%M:%S GMT"))
                except ValueError:
                    mtime = 0.0
                yield RemoteEntry(key, size, mtime)
            marker = root.findtext("NextMarker", "") or ""
            if not marker:
                return

    def read_file(self, key: str) -> bytes:
        with self._request("GET", key) as r:
            return r.read()

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with self._request(
                "GET", key,
                headers={"x-ms-range":
                         f"bytes={offset}-{offset + size - 1}"}) as r:
            return r.read()

    def write_file(self, key: str, data: bytes) -> None:
        with self._request("PUT", key, data=data,
                           headers={"x-ms-blob-type": "BlockBlob"}):
            pass

    def delete_file(self, key: str) -> None:
        import urllib.error
        try:
            with self._request("DELETE", key):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


REMOTES = {"local": LocalDirRemote, "s3": S3Remote, "gcs": GcsRemote,
           "azure": AzureRemote}


def parse_remote_spec(spec: str) -> tuple[str, dict]:
    """Shell-facing remote spec:
      local:/cold-dir
      s3:endpoint=127.0.0.1:8333,bucket=tier,access_key=K,secret_key=S
    (the reference keeps these in remote.conf; the spec string carries the
    same fields inline)."""
    kind, _, opt = spec.partition(":")
    kind = kind or "local"
    if kind == "local":
        return kind, ({"directory": opt} if opt else {})
    options: dict = {}
    for pair in opt.split(","):
        if not pair:
            continue
        k, _, v = pair.partition("=")
        options[k.strip()] = v.strip()
    return kind, options


def make_remote(kind: str, **options) -> RemoteStorageClient:
    try:
        return REMOTES[kind](**options)
    except KeyError:
        raise ValueError(f"unknown remote {kind!r} (have {sorted(REMOTES)})")


def sync_remote_to_filer(remote: RemoteStorageClient, filer_url: str,
                         mount_dir: str, cache: bool = False,
                         timeout: float = 60.0) -> int:
    """remote.mount / remote.cache: traverse the remote and materialize
    entries under mount_dir on the filer (reference:
    shell/command_remote_mount.go + filer/read_remote.go).  Without
    `cache`, files are created as zero-chunk placeholders carrying
    Seaweed-remote-* attrs; with it, content is pulled."""
    import urllib.parse
    import urllib.request
    n = 0
    for e in remote.traverse():
        path = mount_dir.rstrip("/") + "/" + e.key
        if e.is_directory:
            req = urllib.request.Request(
                f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path + '/')}",
                data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=timeout):
                pass
            continue
        headers = {
            "Seaweed-remote-size": str(e.size),
            "Seaweed-remote-mtime": str(int(e.mtime)),
            "Seaweed-remote-key": e.key,
        }
        data = remote.read_file(e.key) if cache else b""
        if not cache:
            headers["Seaweed-remote-placeholder"] = "true"
        req = urllib.request.Request(
            f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path)}",
            data=data, method="POST", headers=headers)
        with urllib.request.urlopen(req, timeout=timeout):
            pass
        n += 1
    return n


def _filer_walk(filer_url: str, dir_path: str, timeout: float = 60.0):
    """Yield (path, meta) for every file entry under dir_path on a filer."""
    import json
    import urllib.parse
    import urllib.request
    stack = [dir_path.rstrip("/") or "/"]
    while stack:
        d = stack.pop()
        url = (f"{_tls_scheme()}://{filer_url}"
               f"{urllib.parse.quote(d.rstrip('/') + '/')}?limit=100000")
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                listing = json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                continue  # directory vanished mid-walk
            # a transient listing failure must abort the walk loudly: a
            # silently-truncated walk makes meta-sync misread cached files
            # as missing and wipe them back to placeholders
            raise
        for e in listing.get("Entries") or []:
            import stat
            p = e["FullPath"]
            if e.get("IsDirectory") or stat.S_ISDIR(
                    (e.get("attr") or {}).get("mode", 0)):
                stack.append(p)
            else:
                # listings are slim; extended attrs (remote-key etc.) need
                # the per-entry metadata view
                murl = (f"{_tls_scheme()}://{filer_url}"
                        f"{urllib.parse.quote(p)}?metadata=true")
                try:
                    with urllib.request.urlopen(murl, timeout=timeout) as r2:
                        meta = json.loads(r2.read())
                except urllib.error.HTTPError as err:
                    if err.code == 404:
                        continue  # entry vanished between list and fetch
                    # same contract as the directory listing above: a
                    # transient failure must abort loudly — a skipped
                    # entry's key would miss seen_keys and the sync would
                    # stamp a placeholder over live content
                    raise
                yield p, meta


def meta_sync_remote_to_filer(remote: RemoteStorageClient, filer_url: str,
                              mount_dir: str,
                              timeout: float = 60.0) -> tuple[int, int, int]:
    """remote.meta.sync: one-shot reconciliation of a mounted directory
    against the remote's current object list (reference:
    command_remote_meta_sync.go): new objects appear as placeholders,
    changed sizes/mtimes are refreshed, filer entries whose object vanished
    are deleted. Returns (created_or_updated, deleted, unchanged)."""
    import urllib.parse
    import urllib.request
    mount_dir = mount_dir.rstrip("/") or "/"
    remote_entries = {e.key: e for e in remote.traverse()
                      if not e.is_directory}
    changed = deleted = unchanged = 0
    seen_keys = set()
    unmanaged_paths = set()
    for path, meta in _filer_walk(filer_url, mount_dir, timeout):
        ext = {k.lower(): v for k, v in (meta.get("extended") or {}).items()}
        key = ext.get("remote-key")
        if key is None:
            # locally-created file, not ours to manage — remembered so a
            # colliding remote key below never overwrites it
            unmanaged_paths.add(path)
            continue
        seen_keys.add(key)
        re_ = remote_entries.get(key)
        if re_ is None:
            req = urllib.request.Request(
                f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path)}",
                method="DELETE")
            with urllib.request.urlopen(req, timeout=timeout):
                pass
            deleted += 1
        elif str(re_.size) != ext.get("remote-size") or \
                str(int(re_.mtime)) != ext.get("remote-mtime"):
            headers = {
                "Seaweed-remote-size": str(re_.size),
                "Seaweed-remote-mtime": str(int(re_.mtime)),
                "Seaweed-remote-key": re_.key,
                "Seaweed-remote-placeholder": "true",
            }
            req = urllib.request.Request(
                f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path)}",
                data=b"", method="POST", headers=headers)
            with urllib.request.urlopen(req, timeout=timeout):
                pass
            changed += 1
        else:
            unchanged += 1
    for key, e in remote_entries.items():
        if key in seen_keys:
            continue
        path = mount_dir + "/" + e.key
        # never stamp a placeholder over an entry this mapping does not
        # manage: a locally-created file whose name collides with a
        # remote key keeps its content (the walk above already fetched
        # every existing entry's metadata — no extra round-trips)
        if path in unmanaged_paths:
            continue
        headers = {
            "Seaweed-remote-size": str(e.size),
            "Seaweed-remote-mtime": str(int(e.mtime)),
            "Seaweed-remote-key": e.key,
            "Seaweed-remote-placeholder": "true",
        }
        req = urllib.request.Request(
            f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path)}",
            data=b"", method="POST", headers=headers)
        with urllib.request.urlopen(req, timeout=timeout):
            pass
        changed += 1
    return changed, deleted, unchanged


def remote_sync_loop(remote: RemoteStorageClient, filer_url: str,
                     mount_dir: str, offset_file: str | None = None,
                     stop_event=None, timeout: float = 60.0) -> int:
    """filer.remote.sync: continuously push LOCAL changes under mount_dir
    out to the remote (reference: command/filer_remote_sync.go) by
    following the filer's meta-subscribe stream. Placeholder writes that
    came FROM the remote (remote-placeholder attr) are skipped so the two
    sync directions cannot loop. Resume offset persists across restarts."""
    import json
    import urllib.parse
    import urllib.request
    mount = mount_dir.rstrip("/") or "/"
    since = 0
    if offset_file and os.path.exists(offset_file):
        try:
            since = int(open(offset_file).read().strip() or 0)
        except ValueError:
            since = 0
    if since == 0:
        since = time.time_ns()
    applied = 0
    while stop_event is None or not stop_event.is_set():
        url = (f"{_tls_scheme()}://{filer_url}/__meta__/subscribe?"
               + urllib.parse.urlencode({"since": str(since),
                                         "prefix": mount, "live": "true"}))
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                for raw in r:
                    if stop_event is not None and stop_event.is_set():
                        return applied
                    line = raw.strip()
                    if not line:
                        continue
                    ev = json.loads(line)
                    # apply (with backoff) BEFORE advancing the offset: a
                    # transiently-failing remote must replay the event on
                    # reconnect, not lose it
                    from seaweedfs_tpu.replication.sink import retry
                    if retry(lambda: _apply_local_event_to_remote(
                            remote, filer_url, mount, ev, timeout)):
                        applied += 1
                    since = max(since, ev.get("ts_ns", since) + 1)
                    if offset_file:
                        tmp = offset_file + ".tmp"
                        with open(tmp, "w") as f:
                            f.write(str(since))
                        os.replace(tmp, offset_file)
        except (urllib.error.URLError, OSError, ValueError):
            if stop_event is not None and stop_event.wait(2.0):
                return applied
            if stop_event is None:
                time.sleep(2.0)
    return applied


def _apply_local_event_to_remote(remote, filer_url: str, mount: str,
                                 ev: dict, timeout: float) -> bool:
    import stat
    import urllib.parse
    import urllib.request
    old, new = ev.get("old_entry"), ev.get("new_entry")

    def key_of(entry) -> str | None:
        p = entry.get("full_path", "")
        if not p.startswith(mount + "/"):
            return None
        return p[len(mount) + 1:]

    def is_dir(entry) -> bool:
        return stat.S_ISDIR((entry.get("attr") or {}).get("mode", 0))

    if new is not None:
        ext = {k.lower(): v for k, v in (new.get("extended") or {}).items()}
        if ext.get("remote-placeholder") == "true":
            return False  # inbound mount/cache traffic, not a local change
        key = key_of(new)
        if key is None or is_dir(new):
            return False
        try:
            with urllib.request.urlopen(
                    f"{_tls_scheme()}://{filer_url}"
                    f"{urllib.parse.quote(new['full_path'])}",
                    timeout=timeout) as r:
                data = r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # deleted/renamed after this event was logged; a later
                # event supersedes it — skip, don't stall the stream
                return False
            raise
        remote.write_file(key, data)
        if old is not None and key_of(old) not in (None, key):
            remote.delete_file(key_of(old))
        return True
    if old is not None and not is_dir(old):
        key = key_of(old)
        if key is not None:
            remote.delete_file(key)
            return True
    return False
