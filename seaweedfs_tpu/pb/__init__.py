"""Protobuf wire layer: binary control-plane framing for the hot RPCs.

Reference: weed/pb/*.proto + generated code.  The schema (weedtpu.proto)
is compiled with protoc on first use (same build-on-demand discipline as
native/).  `available()` is False when protoc and a prebuilt module are
both absent — every endpoint keeps its JSON framing, so protobuf is an
upgrade, not a dependency.
"""

from __future__ import annotations

import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_PROTO = os.path.join(_HERE, "weedtpu.proto")
_GEN = os.path.join(_HERE, "weedtpu_pb2.py")

_lock = threading.Lock()
_mod = None
_err: str | None = None

CONTENT_TYPE = "application/x-protobuf"


def _load():
    global _mod, _err
    with _lock:
        if _mod is not None or _err is not None:
            return _mod
        try:
            if not os.path.exists(_GEN) or \
                    os.path.getmtime(_GEN) < os.path.getmtime(_PROTO):
                subprocess.run(
                    ["protoc", f"--python_out={_HERE}",
                     f"--proto_path={_HERE}", "weedtpu.proto"],
                    check=True, capture_output=True)
            from seaweedfs_tpu.pb import weedtpu_pb2  # noqa: PLC0415
            _mod = weedtpu_pb2
        except (OSError, subprocess.CalledProcessError, ImportError) as e:
            _err = str(e)
            return None
        return _mod


def available() -> bool:
    return _load() is not None


def messages():
    """The generated module (weedtpu_pb2); raises if unavailable."""
    mod = _load()
    if mod is None:
        raise RuntimeError(f"protobuf wire layer unavailable: {_err}")
    return mod


# -- Heartbeat dict <-> message bridging (the JSON shapes stay the
# source of truth; protobuf is an alternate framing of the same data) --

def heartbeat_to_bytes(beat: dict) -> bytes:
    m = messages()
    hb = m.Heartbeat(
        id=beat.get("id", ""), url=beat.get("url", ""),
        public_url=beat.get("public_url", ""),
        data_center=beat.get("data_center", ""),
        rack=beat.get("rack", ""),
        max_volume_count=int(beat.get("max_volume_count", 0)),
        max_file_key=int(beat.get("max_file_key", 0)))
    for v in beat.get("volumes", []):
        hb.volumes.add(
            id=int(v.get("id", 0)), size=int(v.get("size", 0)),
            collection=v.get("collection", "") or "",
            file_count=int(v.get("file_count", 0)),
            delete_count=int(v.get("delete_count", 0)),
            deleted_byte_count=int(v.get("deleted_bytes", 0)),
            read_only=bool(v.get("read_only", False)),
            replica_placement=str(v.get("replica_placement", "000")),
            ttl=str(v.get("ttl", "") or ""),
            modified_at_second=int(v.get("modified_at", 0)),
            version=int(v.get("version", 0)))
    for e in beat.get("ec_shards", []):
        hb.ec_shards.add(id=int(e.get("id", 0)),
                         collection=e.get("collection", "") or "",
                         shards=[int(s) for s in e.get("shard_ids", [])],
                         shard_size=int(e.get("shard_size", 0)),
                         codec=e.get("codec", "") or "")
    return hb.SerializeToString()


def heartbeat_from_bytes(raw: bytes) -> dict:
    m = messages()
    hb = m.Heartbeat()
    hb.ParseFromString(raw)
    return {
        "id": hb.id, "url": hb.url, "public_url": hb.public_url,
        "data_center": hb.data_center, "rack": hb.rack,
        "max_volume_count": hb.max_volume_count,
        "max_file_key": hb.max_file_key,
        "volumes": [{
            # proto3 zero-default: a 0 version means "unset" — omit it so
            # the consumer's CURRENT_VERSION default applies, matching a
            # JSON beat that never carried the key
            **({"version": v.version} if v.version else {}),
            "id": v.id, "size": v.size, "collection": v.collection,
            "file_count": v.file_count, "delete_count": v.delete_count,
            "deleted_bytes": v.deleted_byte_count,
            "read_only": v.read_only,
            "replica_placement": v.replica_placement,
            "ttl": v.ttl, "modified_at": v.modified_at_second,
        } for v in hb.volumes],
        "ec_shards": [{
            # empty codec = a pre-codec-family node: consumers default rs
            **({"codec": e.codec} if e.codec else {}),
            "id": e.id, "collection": e.collection,
            "shard_ids": list(e.shards),
            "shard_size": e.shard_size,
        } for e in hb.ec_shards],
    }
