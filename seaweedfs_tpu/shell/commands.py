"""Admin shell command environment + commands.

The shell drives the cluster purely over the master/volume-server HTTP
APIs, holding the master's exclusive admin lock while mutating — same
operating model as the reference shell (weed/shell/commands.go:23-60,
command_ec_encode.go, command_ec_rebuild.go, command_ec_decode.go,
command_ec_balance.go), synchronous code for operator predictability.
"""

from __future__ import annotations

import json
import shlex
import urllib.parse
import urllib.request

from seaweedfs_tpu.stats import netflow
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import layout
from seaweedfs_tpu.security.tls import scheme as _tls_scheme


class CommandEnv:
    def __init__(self, master: str):
        self.master = master
        self.lock_token: str | None = None
        self.cwd = "/"  # fs.cd / fs.pwd working directory

    def resolve(self, path: str) -> str:
        """Join a possibly-relative shell path against the REPL cwd."""
        if not path or path == ".":
            return self.cwd
        if not path.startswith("/"):
            path = self.cwd.rstrip("/") + "/" + path
        import posixpath
        return posixpath.normpath(path)

    # -- http helpers --------------------------------------------------

    def _call(self, url: str, body: dict | None = None,
              method: str | None = None, timeout: float = 600.0) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} \
            if body is not None else {}
        # byte-flow class: an ec.rebuild's shard copies must book as
        # class=repair whether the planner or an operator drove them
        netflow.inject(headers, "/" + url.partition("/")[2], "shell")
        req = urllib.request.Request(
            f"{_tls_scheme()}://{url}", data=data,
            method=method or ("POST" if body is not None else "GET"),
            headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read()).get("error", str(e))
            except Exception:
                err = str(e)
            raise RuntimeError(f"{url}: {err}") from None

    def master_get(self, path: str, **params) -> dict:
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        return self._call(f"{self.master}{path}{qs}")

    def master_post(self, path: str, body: dict | None = None, **params) -> dict:
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        return self._call(f"{self.master}{path}{qs}", body or {})

    def vs_post(self, url: str, path: str, body: dict) -> dict:
        return self._call(f"{url}{path}", body)

    def master_get_raw(self, node_url: str, path: str, **params) -> dict:
        """GET a JSON endpoint on an arbitrary cluster node."""
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        return self._call(f"{node_url}{path}{qs}")

    # -- filer helpers ---------------------------------------------------

    def find_filer(self) -> str:
        members = self.master_get("/cluster/status").get("Members", {})
        filers = members.get("filer", [])
        if not filers:
            raise RuntimeError("no filer registered with the master")
        return filers[0]

    def filer_list(self, filer: str, dir_path: str) -> list[dict]:
        d = dir_path.rstrip("/") + "/"
        r = self._call(f"{filer}{urllib.parse.quote(d)}?limit=100000")
        return r.get("Entries") or []

    def filer_read(self, filer: str, path: str) -> bytes:
        req = urllib.request.Request(
            f"{_tls_scheme()}://{filer}{urllib.parse.quote(path)}")
        with urllib.request.urlopen(req, timeout=600) as r:
            return r.read()

    def filer_delete(self, filer: str, path: str,
                     recursive: bool = False) -> None:
        qs = "?recursive=true" if recursive else ""
        self._call(f"{filer}{urllib.parse.quote(path)}{qs}", method="DELETE")

    # -- lock -----------------------------------------------------------

    def acquire_lock(self, owner: str = "shell") -> None:
        if self.lock_token:
            return
        self.lock_token = self.master_post("/admin/lock", {"owner": owner})["token"]

    def release_lock(self) -> None:
        if self.lock_token:
            self.master_post("/admin/unlock", {"token": self.lock_token})
            self.lock_token = None

    def require_lock(self) -> None:
        if not self.lock_token:
            raise RuntimeError("this command requires `lock` first")

    # -- topology helpers -----------------------------------------------

    def topology(self) -> dict:
        return self.master_get("/cluster/status")["Topology"]

    def volume_locations(self, vid: int) -> list[str]:
        try:
            r = self.master_get("/dir/lookup", volumeId=str(vid))
        except RuntimeError:
            return []
        return [l["url"] for l in r.get("locations", [])]

    def ec_shard_locations(self, vid: int) -> dict[int, list[str]]:
        try:
            r = self.master_get("/dir/ec/lookup", volumeId=str(vid))
        except RuntimeError:
            return {}
        return {int(s): [l["url"] for l in locs]
                for s, locs in r.get("shards", {}).items()}


# ---- commands ---------------------------------------------------------

COMMANDS: dict[str, callable] = {}


def command(name):
    def deco(fn):
        COMMANDS[name] = fn
        return fn
    return deco


def parse_flags(args: list[str]) -> dict[str, str]:
    out = {}
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("-"):
            key = a.lstrip("-")
            if "=" in key:
                k, _, v = key.partition("=")
                out[k] = v
            elif i + 1 < len(args) and not args[i + 1].startswith("-"):
                out[key] = args[i + 1]
                i += 1
            else:
                out[key] = "true"
        i += 1
    return out


@command("help")
def cmd_help(env: CommandEnv, args, out):
    """List commands, or show one command's doc: help [name]."""
    if args:
        fn = COMMANDS.get(args[0])
        if fn is None:
            print(f"unknown command {args[0]!r}", file=out)
            return
        import inspect
        doc = inspect.cleandoc(fn.__doc__) if fn.__doc__ else "(no help)"
        print(f"{args[0]}: {doc}", file=out)
        return
    for name in sorted(COMMANDS):
        doc = (COMMANDS[name].__doc__ or "").strip().splitlines()
        print(f"{name:28s} {doc[0] if doc else ''}", file=out)


@command("lock")
def cmd_lock(env: CommandEnv, args, out):
    env.acquire_lock()
    print("locked", file=out)


@command("unlock")
def cmd_unlock(env: CommandEnv, args, out):
    env.release_lock()
    print("unlocked", file=out)


@command("cluster.status")
def cmd_cluster_status(env: CommandEnv, args, out):
    print(json.dumps(env.master_get("/cluster/status"), indent=2), file=out)


@command("volume.list")
def cmd_volume_list(env: CommandEnv, args, out):
    topo = env.topology()
    for nid, node in sorted(topo["nodes"].items()):
        print(f"node {nid} dc={node['dc']} rack={node['rack']} "
              f"free={node['free_slots']}", file=out)
        for vid in node["volumes"]:
            print(f"  volume {vid}", file=out)
        for vid, shards in sorted(node["ec_shards"].items()):
            print(f"  ec volume {vid} shards {shards}", file=out)


@command("volume.vacuum")
def cmd_volume_vacuum(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    for url in env.volume_locations(vid):
        r = env.vs_post(url, "/admin/volume/vacuum", {"volume": vid})
        print(f"vacuumed {vid} on {url} (garbage was "
              f"{r.get('garbage_ratio', 0):.2%})", file=out)


@command("volume.delete")
def cmd_volume_delete(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    for url in env.volume_locations(vid):
        env.vs_post(url, "/admin/volume/delete", {"volume": vid})
        print(f"deleted {vid} on {url}", file=out)


@command("volume.mark")
def cmd_volume_mark(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    readonly = flags.get("writable", "false") != "true"
    for url in env.volume_locations(vid):
        env.vs_post(url, "/admin/volume/readonly",
                    {"volume": vid, "readonly": readonly})
        print(f"marked {vid} readonly={readonly} on {url}", file=out)


def balanced_ec_distribution(nodes: list[str],
                             racks: dict[str, str] | None = None,
                             n_shards: int = layout.TOTAL_SHARDS
                             ) -> dict[str, list[int]]:
    """Spread the volume's n shards rack-aware: each shard goes to the
    rack with the fewest shards so far, then the least-loaded node
    inside it — a rack loss never takes more shards than necessary
    (reference: command_ec_encode.go:272 balancedEcDistribution + the
    rack spread of command_ec_balance.go)."""
    racks = racks or {}
    alloc: dict[str, list[int]] = {n: [] for n in nodes}
    rack_of = {n: racks.get(n, n) for n in nodes}  # rackless: node = rack
    rack_load: dict[str, int] = {r: 0 for r in rack_of.values()}
    for sid in range(n_shards):
        # fewest-loaded rack, then fewest-loaded node within it; sorted
        # keys make ties deterministic
        rack = min(sorted(rack_load), key=lambda r: rack_load[r])
        target = min(sorted(n for n in nodes if rack_of[n] == rack),
                     key=lambda n: len(alloc[n]))
        alloc[target].append(sid)
        rack_load[rack] += 1
    return alloc


def parse_duration(s: str) -> float:
    """'1h' / '30m' / '45s' / plain seconds -> seconds."""
    s = s.strip()
    mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}.get(s[-1:], None)
    if mult is not None:
        return float(s[:-1]) * mult
    return float(s)


def collect_volume_ids_for_ec_encode(topo: dict, collection: str,
                                     full_percent: float,
                                     quiet_seconds: float) -> list[int]:
    """Pick quiet+full candidate volumes from the topology snapshot
    (reference: command_ec_encode.go:290-321
    collectVolumeIdsForEcEncode).  Pure function over the snapshot, so
    it is testable without a cluster (SURVEY §4 topology-test pattern)."""
    import time as _time
    limit = topo.get("volume_size_limit", 0) or 0
    now = _time.time()
    vids: set[int] = set()
    for node in topo["nodes"].values():
        for v in node.get("volume_infos", []):
            if v.get("collection", "") != collection:
                continue
            if v.get("modified_at", 0) + quiet_seconds >= now:
                continue  # written too recently
            if limit and v.get("size", 0) <= full_percent / 100.0 * limit:
                continue  # not full enough
            vids.add(v["id"])
    return sorted(vids)


@command("ec.encode")
def cmd_ec_encode(env: CommandEnv, args, out):
    """Convert volumes to EC shards and spread them (reference:
    command_ec_encode.go:58-321).  With -volumeId, encodes that volume;
    without it, scans the topology for candidates that are at least
    -fullPercent full (default 95) and write-quiet for -quietFor
    (default 1h) — the reference's fleet-wide operational loop."""
    env.require_lock()
    flags = parse_flags(args)
    collection = flags.get("collection", "")
    codec = flags.get("codec", "")
    if "volumeId" in flags:
        vids = [int(flags["volumeId"])]
    else:
        full_percent = float(flags.get("fullPercent", "95"))
        quiet = parse_duration(flags.get("quietFor", "1h"))
        vids = collect_volume_ids_for_ec_encode(
            env.topology(), collection, full_percent, quiet)
        print(f"{len(vids)} volume(s) ≥{full_percent}% full and quiet "
              f"for {quiet:.0f}s: {vids}", file=out)
    for vid in vids:
        _ec_encode_one(env, vid, collection, out, codec=codec)


def _ec_encode_one(env: CommandEnv, vid: int, collection: str, out,
                   codec: str = ""):
    locations = env.volume_locations(vid)
    if not locations:
        raise RuntimeError(f"volume {vid} not found")
    source = locations[0]

    # 1. freeze writes on every replica
    for url in locations:
        env.vs_post(url, "/admin/volume/readonly", {"volume": vid, "readonly": True})
    # 2. generate shards on the source (TPU codec); -codec picks the
    # erasure-code family (rs/lrc/msr tag), default per WEEDTPU_CODEC_*
    from seaweedfs_tpu.ops import codecs as _codecs
    spec = _codecs.parse_tag(codec or _codecs.default_tag())
    env.vs_post(source, "/admin/ec/generate",
                {"volume": vid, "collection": collection,
                 **({"codec": spec.tag} if codec else {})})
    print(f"generated {spec.n} {spec.tag} shards of volume {vid} "
          f"on {source}", file=out)

    # 3. spread shards over the cluster; copies fan out in parallel
    # (reference: command_ec_encode.go:213 parallelCopyEcShardsFromSource)
    import concurrent.futures
    topo = env.topology()
    nodes = sorted(topo["nodes"])
    racks = {nid: f"{nd['dc']}/{nd['rack']}"
             for nid, nd in topo["nodes"].items()}
    alloc = balanced_ec_distribution(nodes, racks, n_shards=spec.n)

    def place(target_shards):
        target, shards = target_shards
        if target != source:
            env.vs_post(target, "/admin/ec/copy",
                        {"volume": vid, "collection": collection,
                         "source": source, "shards": shards, "copy_ecx": True})
        env.vs_post(target, "/admin/ec/mount",
                    {"volume": vid, "collection": collection})
        return target, shards

    work = [(t, ss) for t, ss in alloc.items() if ss]
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        for target, shards in ex.map(place, work):
            print(f"  shards {shards} -> {target}", file=out)
    # 4. delete moved shard files from source, and the original volume
    moved = [s for tgt, ss in alloc.items() if tgt != source for s in ss]
    if moved:
        env.vs_post(source, "/admin/ec/delete_shards",
                    {"volume": vid, "shards": moved})
        env.vs_post(source, "/admin/ec/mount",
                    {"volume": vid, "collection": collection})
    for url in locations:
        env.vs_post(url, "/admin/volume/delete", {"volume": vid})
    print(f"ec.encode {vid} done", file=out)


@command("ec.rebuild")
def cmd_ec_rebuild(env: CommandEnv, args, out):
    """Rebuild missing shards (reference: command_ec_rebuild.go:58-281)."""
    env.require_lock()
    with netflow.flow("repair"):
        _ec_rebuild_all(env, out)


def _ec_rebuild_all(env: CommandEnv, out) -> None:
    topo = env.topology()
    ec_vids = {int(v) for node in topo["nodes"].values()
               for v in node["ec_shards"]}
    for vid in sorted(ec_vids):
        shard_locs = env.ec_shard_locations(vid)
        present = set(shard_locs)
        from seaweedfs_tpu.ops import codecs as _codecs
        try:
            health = env.master_get("/maintenance/status")
            spec = _codecs.parse_tag(
                (health.get("volumes", {}).get(str(vid)) or
                 {}).get("codec"))
        except RuntimeError:
            spec = _codecs.parse_tag(None)
        missing = [s for s in range(spec.n) if s not in present]
        if not missing:
            continue
        if len(present) < spec.k:
            print(f"volume {vid}: only {len(present)} shards left, "
                  f"cannot rebuild", file=out)
            continue
        # rebuilder = node holding the most shards
        counts: dict[str, int] = {}
        for locs in shard_locs.values():
            for url in locs:
                counts[url] = counts.get(url, 0) + 1
        rebuilder = max(counts, key=counts.get)
        local = {s for s, locs in shard_locs.items() if rebuilder in locs}
        # pull missing survivors to the rebuilder
        borrowed = []
        for s, locs in shard_locs.items():
            if s in local:
                continue
            env.vs_post(rebuilder, "/admin/ec/copy",
                        {"volume": vid, "source": locs[0], "shards": [s],
                         "copy_ecx": False})
            borrowed.append(s)
        r = env.vs_post(rebuilder, "/admin/ec/rebuild", {"volume": vid})
        env.vs_post(rebuilder, "/admin/ec/delete_shards",
                    {"volume": vid, "shards": borrowed})
        env.vs_post(rebuilder, "/admin/ec/mount", {"volume": vid})
        print(f"volume {vid}: rebuilt {r.get('rebuilt')} on {rebuilder}",
              file=out)


@command("ec.codecs")
def cmd_ec_codecs(env: CommandEnv, args, out):
    """List the registered erasure-codec family as configured right now
    (tag, geometry, sub-packetization, worst-case loss tolerance) plus
    the fleet's per-codec volume mix from the maintenance ledger.
    -json emits the raw spec rows."""
    from seaweedfs_tpu.ops import codecs as _codecs
    flags = parse_flags(args)
    specs = [s.describe() for s in _codecs.registered()]
    mix: dict[str, int] = {}
    try:
        st = env.master_get("/maintenance/status")
        for v in (st.get("volumes") or {}).values():
            if v.get("kind") == "ec":
                tag = _codecs.parse_tag(v.get("codec")).tag
                mix[tag] = mix.get(tag, 0) + 1
    except RuntimeError:
        pass
    if "json" in flags:
        print(json.dumps({"codecs": specs, "default":
                          _codecs.default_tag(), "mix": mix},
                         separators=(",", ":")), file=out)
        return
    print(f"default: {_codecs.default_tag()}", file=out)
    for s in specs:
        extra = f" alpha={s['alpha']}" if s["alpha"] > 1 else ""
        print(f"{s['tag']:12s} family={s['family']:4s} k={s['k']:2d} "
              f"m={s['m']:2d} n={s['n']:2d}{extra} "
              f"tolerates={s['tolerance']} loss(es)"
              + (f"  volumes={mix[s['tag']]}" if s["tag"] in mix
                 else ""), file=out)
    stray = {t: c for t, c in mix.items()
             if t not in {s["tag"] for s in specs}}
    for tag, c in sorted(stray.items()):
        print(f"{tag:12s} (not in the configured family)  "
              f"volumes={c}", file=out)


@command("ec.decode")
def cmd_ec_decode(env: CommandEnv, args, out):
    """EC shards -> normal volume (reference: command_ec_decode.go:40-292)."""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    collection = flags.get("collection", "")
    shard_locs = env.ec_shard_locations(vid)
    if not shard_locs:
        raise RuntimeError(f"no ec shards for volume {vid}")
    counts: dict[str, int] = {}
    for locs in shard_locs.values():
        for url in locs:
            counts[url] = counts.get(url, 0) + 1
    collector = max(counts, key=counts.get)
    local = {s for s, locs in shard_locs.items() if collector in locs}
    for s, locs in shard_locs.items():
        if s not in local and locs:
            env.vs_post(collector, "/admin/ec/copy",
                        {"volume": vid, "collection": collection,
                         "source": locs[0], "shards": [s], "copy_ecx": False})
    env.vs_post(collector, "/admin/ec/to_volume",
                {"volume": vid, "collection": collection})
    # drop shards everywhere
    all_nodes = {url for locs in shard_locs.values() for url in locs} | {collector}
    for url in all_nodes:
        env.vs_post(url, "/admin/ec/unmount", {"volume": vid})
        env.vs_post(url, "/admin/ec/delete_shards",
                    {"volume": vid,
                     "shards": sorted(set(range(layout.TOTAL_SHARDS)) |
                                      {int(s) for s in shard_locs})})
    print(f"ec.decode {vid} -> normal volume on {collector}", file=out)


@command("ec.balance")
def cmd_ec_balance(env: CommandEnv, args, out):
    """Even shard spread (reference: command_ec_balance.go, simplified to
    per-volume round-robin re-placement)."""
    env.require_lock()
    topo = env.topology()
    nodes = sorted(topo["nodes"])
    racks = {nid: f"{nd['dc']}/{nd['rack']}"
             for nid, nd in topo["nodes"].items()}
    ec_vids = {int(v) for node in topo["nodes"].values()
               for v in node["ec_shards"]}
    for vid in sorted(ec_vids):
        shard_locs = env.ec_shard_locations(vid)
        want = balanced_ec_distribution(nodes, racks)
        want_by_shard = {s: tgt for tgt, ss in want.items() for s in ss}
        for s, locs in shard_locs.items():
            tgt = want_by_shard.get(s)
            if tgt is None or tgt in locs:
                continue
            src = locs[0]
            env.vs_post(tgt, "/admin/ec/copy",
                        {"volume": vid, "source": src, "shards": [s],
                         "copy_ecx": True})
            env.vs_post(tgt, "/admin/ec/mount", {"volume": vid})
            env.vs_post(src, "/admin/ec/delete_shards",
                        {"volume": vid, "shards": [s]})
            env.vs_post(src, "/admin/ec/mount", {"volume": vid})
            print(f"volume {vid} shard {s}: {src} -> {tgt}", file=out)
    print("ec.balance done", file=out)


# ---- volume maintenance (reference: weed/shell/command_volume_*.go) ----


@command("volume.balance")
def cmd_volume_balance(env: CommandEnv, args, out):
    """Even out volume counts across nodes by moving volumes from the most
    to the least loaded (reference: command_volume_balance.go)."""
    env.require_lock()
    flags = parse_flags(args)
    apply = flags.get("force", "false") == "true" or \
        flags.get("apply", "false") == "true"
    topo = env.topology()
    counts = {nid: len(n["volumes"]) for nid, n in topo["nodes"].items()}
    if len(counts) < 2:
        print("volume.balance: nothing to do (single node)", file=out)
        return
    moves: list[tuple[int, str, str]] = []
    while True:
        hi = max(counts, key=counts.get)
        lo = min(counts, key=counts.get)
        if counts[hi] - counts[lo] <= 1:
            break
        movable = [v for v in topo["nodes"][hi]["volumes"]
                   if v not in set(topo["nodes"][lo]["volumes"])]
        if not movable:
            break
        vid = movable[0]
        moves.append((vid, hi, lo))
        topo["nodes"][hi]["volumes"].remove(vid)
        topo["nodes"][lo]["volumes"].append(vid)
        counts[hi] -= 1
        counts[lo] += 1
    cols = {vid: rec.get("collection", "")
            for vid, rec in collect_volume_infos(topo).items()}
    for vid, src, dst in moves:
        print(f"move volume {vid}: {src} -> {dst}"
              + ("" if apply else " (dry run, -apply to move)"), file=out)
        if apply:
            move_volume(env, vid, src, dst, cols.get(vid, ""))
    print(f"volume.balance: {len(moves)} move(s)"
          + ("" if apply else " planned"), file=out)


def move_volume(env: "CommandEnv", vid: int, source: str, target: str,
                collection: str = "") -> None:
    """Copy-then-delete volume move, the one protocol both volume.move and
    volume.balance use (reference: command_volume_move.go LiveMoveVolume).

    Live-safe: the bulk copy and tail drains run in STAGING mode — the
    target copy is read-only, hidden from heartbeats, and marked on disk,
    so neither lookups nor replicate fan-out can reach it and a crash
    mid-move can never boot it as live data. Then the source is frozen
    read-only, one finalizing catch-up closes the race window and flips
    the target live, and only then is the source deleted. If anything
    fails after the freeze, the source is made writable again before the
    error propagates (the reference rolls back the same way via a
    deferred VolumeMarkWritable, command_volume_move.go)."""
    import time as _time
    body = {"volume": vid, "source": source, "collection": collection,
            "staging": True}
    env.vs_post(target, "/admin/volume/copy", body)
    # drain the append tail while the source is still live; stop early
    # when the tail stops shrinking — the post-freeze copy closes whatever
    # remains, so chasing a write-hot volume here is wasted round-trips
    last = None
    for _ in range(10):
        r = env.vs_post(target, "/admin/volume/copy", body)
        appended = r.get("appended_bytes", 0)
        if appended == 0 or (last is not None and appended >= last):
            break
        last = appended
        _time.sleep(0.2)
    # freeze writes, then the finalizing catch-up closes the race window
    env.vs_post(source, "/admin/volume/readonly",
                {"volume": vid, "readonly": True})
    try:
        env.vs_post(target, "/admin/volume/copy",
                    dict(body, finalize=True))
    except Exception:
        # finalize failed: the target never went live, so re-enabling the
        # source is safe and restores service
        try:
            env.vs_post(source, "/admin/volume/readonly",
                        {"volume": vid, "readonly": False})
        except Exception:
            pass  # rollback is best-effort; the original error matters more
        raise
    # past this point the target IS live: never unfreeze the source (two
    # writable copies would silently diverge) — a failed delete leaves a
    # read-only source replica the operator can delete by hand
    env.vs_post(source, "/admin/volume/delete", {"volume": vid})


def collect_volume_infos(topo: dict) -> dict[int, dict]:
    """vid -> {collection, replica_placement, nodes: [node ids], ...} from
    the per-node volume_infos in a topology snapshot."""
    vols: dict[int, dict] = {}
    for nid, node in topo["nodes"].items():
        for vi in node.get("volume_infos", []):
            rec = vols.setdefault(vi["id"], dict(vi, nodes=[]))
            rec["nodes"].append(nid)
    return vols


@command("volume.fix.replication")
def cmd_volume_fix_replication(env: CommandEnv, args, out):
    """Re-replicate under-replicated volumes / purge over-replicated ones
    (reference: command_volume_fix_replication.go:36-55)."""
    env.require_lock()
    flags = parse_flags(args)
    apply = flags.get("apply", "false") == "true" or \
        flags.get("force", "false") == "true"
    topo = env.topology()
    fixed = 0
    for vid, rec in sorted(collect_volume_infos(topo).items()):
        nodes = rec["nodes"]
        rp = t.ReplicaPlacement.parse(rec.get("replica_placement", "000"))
        want = rp.copy_count
        if len(nodes) == want:
            continue
        if len(nodes) > want:
            for extra in nodes[want:]:
                print(f"volume {vid}: over-replicated, delete from {extra}"
                      + ("" if apply else " (dry run)"), file=out)
                if apply:
                    env.vs_post(extra, "/admin/volume/delete", {"volume": vid})
                fixed += 1
        else:
            targets = [nid for nid in topo["nodes"]
                       if nid not in nodes and
                       topo["nodes"][nid]["free_slots"] > 0]
            for dst in targets[: want - len(nodes)]:
                print(f"volume {vid}: under-replicated ({len(nodes)}/{want}), "
                      f"copy {nodes[0]} -> {dst}"
                      + ("" if apply else " (dry run)"), file=out)
                if apply:
                    with netflow.flow("replication"):
                        env.vs_post(dst, "/admin/volume/copy",
                                    {"volume": vid, "source": nodes[0],
                                     "collection":
                                     rec.get("collection", "")})
                fixed += 1
    print(f"volume.fix.replication: {fixed} action(s)"
          + ("" if apply else " planned"), file=out)


@command("volume.check.disk")
def cmd_volume_check_disk(env: CommandEnv, args, out):
    """Compare replicas of each volume by needle set and report divergence
    (reference: command_volume_check_disk.go)."""
    env.require_lock()
    topo = env.topology()
    locs: dict[int, list[str]] = {}
    for nid, node in topo["nodes"].items():
        for vid in node["volumes"]:
            locs.setdefault(vid, []).append(nid)
    issues = 0
    for vid, nodes in sorted(locs.items()):
        if len(nodes) < 2:
            continue
        sets = {}
        for url in nodes:
            r = env.master_get_raw(url, "/admin/volume/needles", volume=vid)
            sets[url] = set(r.get("needles", []))
        base = sets[nodes[0]]
        for url in nodes[1:]:
            if sets[url] != base:
                only_a = len(base - sets[url])
                only_b = len(sets[url] - base)
                print(f"volume {vid}: {nodes[0]} vs {url} differ "
                      f"(+{only_a}/-{only_b})", file=out)
                issues += 1
    print(f"volume.check.disk: {issues} divergent replica pair(s)", file=out)


@command("maintenance.status")
def cmd_maintenance_status(env: CommandEnv, args, out):
    """Cluster self-healing status from the master's health ledger:
    per-volume state (healthy/degraded/under_replicated/corrupt/critical),
    last-scrub time, quarantined ranges, and repair-planner state.
    -json emits the raw machine-readable ledger for CI assertions."""
    flags = parse_flags(args)
    st = env.master_get("/maintenance/status")
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    import datetime as _dt
    for vid, v in sorted(st.get("volumes", {}).items(),
                         key=lambda kv: int(kv[0])):
        if v.get("kind") == "ec":
            from seaweedfs_tpu.ops import codecs as _codecs
            spec = _codecs.parse_tag(v.get("codec"))
            present = v.get("shards_present", [])
            detail = f"{spec.tag} shards {len(present)}/{spec.n}"
            if v.get("shards_missing"):
                detail += f" missing {v['shards_missing']}"
            if v.get("corrupt"):
                detail += " corrupt " + str(
                    sorted({c.get('shard', -1) for c in v['corrupt']}))
            nq = sum(len(r) for q in (v.get("quarantined") or {}).values()
                     for r in q.values())
            if nq:
                detail += f" quarantined {nq} range(s)"
        else:
            detail = (f"replicas {len(v.get('replicas', []))}"
                      f"/{v.get('want_replicas', 1)}")
            if v.get("crc_mismatches"):
                detail += f" crc_mismatches {v['crc_mismatches']}"
        ls = v.get("last_scrub")
        scrub = _dt.datetime.fromtimestamp(ls).isoformat(" ", "seconds") \
            if ls else "never"
        print(f"volume {vid} [{v.get('kind')}]: {v.get('state'):16s} "
              f"{detail}  last-scrub {scrub}", file=out)
    states = st.get("states", {})
    print("states: " + " ".join(f"{k}={v}" for k, v in sorted(
        states.items()) if v), file=out)
    pl = st.get("planner", {})
    print(f"planner: tokens={pl.get('tokens')} active={pl.get('active')} "
          f"backoffs={len(pl.get('backoffs', {}))}", file=out)
    _print_repair_plane(pl, out)
    _print_slo(st.get("slo") or {}, out)
    _print_alerts(st.get("alerts") or {}, out)
    from seaweedfs_tpu.stats.history import FORECAST_CAP_S
    cap = st.get("capacity") or {}
    soon = [d for d in cap.get("disks", [])
            if d.get("predicted_full_seconds", FORECAST_CAP_S)
            < FORECAST_CAP_S]
    if soon:
        print("capacity: " + " ".join(
            f"{d['vs']}:{d['dir']}={_fmt_eta(d['predicted_full_seconds'])}"
            for d in soon[:5]), file=out)
    itf = st.get("interference") or {}
    gov = itf.get("governor") or {}
    if gov:
        rates = " ".join(
            f"{n}={t.get('rate'):g}/{t.get('ceiling'):g}"
            for n, t in sorted((gov.get("targets") or {}).items()))
        idx = " ".join(f"{c}={r.get('index'):g}" for c, r in
                       sorted((itf.get("classes") or {}).items()))
        print(f"governor: {'on' if gov.get('enabled') else 'OFF'} "
              f"retunes={gov.get('retunes', 0)} {rates}"
              + (f"  index: {idx}" if idx else ""), file=out)
    ge = st.get("geo") or {}
    if ge.get("directions"):
        # geo-replication one-liner (cluster.geo for the full observatory)
        dirs = " ".join(
            f"{d}={v.get('lag_s', 0):.2f}s"
            + ("[STALLED]" if v.get("stalled") else "")
            for d, v in sorted(ge["directions"].items()))
        wan = ge.get("wan") or {}
        print(f"geo: region={ge.get('region') or '-'} {dirs} "
              f"wan sent={_fmt_bytes(wan.get('sent_bytes', 0))} "
              f"recv={_fmt_bytes(wan.get('recv_bytes', 0))}", file=out)
    lp = st.get("loops") or {}
    if lp.get("headline"):
        # control-plane loops one-liner (cluster.loops for per-loop detail)
        print(f"loops: {lp['headline']}", file=out)


def _fmt_bytes(n: float) -> str:
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("KB", 1e3)):
        if n >= div:
            return f"{n / div:.1f}{unit}"
    return f"{n:.0f}B"


def _print_repair_plane(pl: dict, out) -> None:
    """Reduced-read repair plane lines shared by maintenance.status and
    chaos.status: cross-rack budget state, repair bytes by locality
    class (the cluster.heat-style one-liner), and the last
    survivor-selection decisions."""
    xr = pl.get("xrack") or {}
    if xr:
        waiting = xr.get("waiting") or []
        print(f"xrack budget: {_fmt_bytes(xr.get('tokens', 0))} of "
              f"{_fmt_bytes(xr.get('burst_bytes', 0))} "
              f"(+{_fmt_bytes(xr.get('budget_bytes_per_s', 0))}/s)"
              + (f" waiting={waiting}" if waiting else ""), file=out)
    by_loc = pl.get("repair_bytes_by_locality") or {}
    if by_loc:
        print("repair bytes: " + " ".join(
            f"{name}={_fmt_bytes(by_loc[name])}"
            for name in ("node", "rack", "dc", "remote")
            if name in by_loc), file=out)
    for d in (pl.get("decisions") or [])[-3:]:
        helpers = " ".join(
            f"{h['node']}(loc{h['locality']}x{len(h['shards'])})"
            for h in d.get("helpers", []))
        actual = d.get("actual_bytes")
        print(f"  repair vid={d['vid']} {d['mode']:14s} "
              f"lost={d.get('lost')} via {helpers or '-'} "
              f"est={_fmt_bytes(d.get('est_remote_bytes', 0))}"
              + (f" actual={_fmt_bytes(actual)}"
                 if actual is not None else "")
              + (f" replans={d['replans']}" if d.get("replans") else "")
              + (f" naive={_fmt_bytes(d.get('naive_remote_bytes', 0))}"),
              file=out)


def _fmt_eta(s: float) -> str:
    for unit, div in (("d", 86400.0), ("h", 3600.0), ("m", 60.0)):
        if s >= div:
            return f"{s / div:.1f}{unit}"
    return f"{s:.0f}s"


def _print_alerts(alerts: dict, out) -> None:
    """Shared alert pretty-printer for maintenance.status /
    cluster.alerts: one line per rule, firing groups expanded."""
    if not alerts.get("rules"):
        return
    firing = [r for r in alerts["rules"] if r["state"] == "firing"]
    print(f"alerts: {alerts.get('state', 'ok')} "
          f"({len(firing)} rule(s) firing)", file=out)
    for r in alerts["rules"]:
        if r["state"] == "ok":
            continue
        for g in r.get("groups", []):
            if g["state"] == "ok":
                continue
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(g.get("labels", {}).items())) or "-"
            val = "stale" if g.get("stale") else g.get("value")
            ex = f" trace={g['exemplar']}" if g.get("exemplar") else ""
            print(f"  {r['name']:24s} {g['state'].upper():8s} {lbl} "
                  f"value={val}{ex}", file=out)


def _print_slo(slo: dict, out) -> None:
    """Shared SLO pretty-printer for maintenance.status / cluster.slo:
    one line per rule with its per-window burn rates."""
    if not slo.get("rules"):
        return
    print(f"slo: {slo.get('state', 'unknown')} "
          f"(nodes={len(slo.get('nodes', []))} "
          f"scrape_errors={len(slo.get('scrape_errors', {}))})", file=out)
    for r in slo["rules"]:
        detail = " ".join(
            f"{w}:burn={win.get('burn_rate')}"
            + (f",p99={win['p99_ms']}ms" if win.get("p99_ms") is not None
               else "")
            for w, win in sorted(r.get("windows", {}).items()))
        if r["kind"] == "backlog":
            detail = f"value={r.get('value')}"
        print(f"  {r['name']:24s} {r['state']:9s} {detail}", file=out)


@command("cluster.slo")
def cmd_cluster_slo(env: CommandEnv, args, out):
    """Cluster SLO burn-rate status from the master's metrics aggregator
    (/cluster/slo): per-rule state + multi-window burn rates.
    -refresh forces a fleet /metrics pull first; -json emits the raw
    engine output for CI assertions."""
    flags = parse_flags(args)
    params = {"refresh": "1"} if "refresh" in flags else {}
    st = env.master_get("/cluster/slo", **params)
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    _print_slo(st, out)
    if not st.get("rules"):
        print(f"slo: {st.get('state', 'unknown')} (no data yet — "
              "try -refresh)", file=out)


@command("cluster.perf")
def cmd_cluster_perf(env: CommandEnv, args, out):
    """Fleet performance observatory (/cluster/perf): per-pipeline stage
    occupancy, the bottleneck verdict per pipeline kind (the stage whose
    busy fraction bounds throughput, with its achieved-vs-ceiling
    fraction when the resource's roofline is measured), the worst
    roofline offenders fleet-wide, and every node's tile-drift verdict.
    -top N offender rows (default 5); -json dumps the raw merge.
    Runbook: a bench trajectory regression names WHAT got slower —
    this names WHERE (stage + node + distance from the hardware)."""
    flags = parse_flags(args)
    st = env.master_get("/cluster/perf")
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    try:
        top_n = max(1, int(flags.get("top", "5")))
    except ValueError:
        top_n = 5
    print(f"perf: nodes={len(st.get('nodes', []))} "
          f"running={len(st.get('running', []))}"
          + (f" node_errors={len(st['node_errors'])}"
             if st.get("node_errors") else ""), file=out)
    occ = st.get("occupancy") or {}
    bns = st.get("bottlenecks") or {}
    for kind in sorted(occ):
        bn = bns.get(kind) or {}
        verdict = ""
        if bn:
            verdict = (f"  << bottleneck: {bn.get('stage')} "
                       f"busy={bn.get('busy_frac', 0):.0%}")
            if bn.get("ceiling_frac") is not None:
                verdict += (f" @ {bn['ceiling_frac']:.0%} of "
                            f"{bn.get('resource')} ceiling")
        print(f"{kind}:{verdict}", file=out)
        stages = occ[kind]
        for stage in sorted(stages,
                            key=lambda s: -stages[s]["busy_s"]):
            row = stages[stage]
            bar = "#" * min(20, int(20 * row["max_busy_frac"]))
            print(f"  {stage:16s} {row['busy_s']:9.3f}s busy "
                  f"[{bar:20s}] max={row['max_busy_frac']:.0%} "
                  f"{row['bytes'] / 1e9:8.3f} GB over "
                  f"{row['jobs']} jobs", file=out)
    offenders = (st.get("offenders") or [])[:top_n]
    if offenders:
        print("roofline offenders (furthest from their ceiling, "
              "busiest first):", file=out)
        for r in offenders:
            print(f"  {r.get('node', '?'):22s} {r['kernel']:14s} "
                  f"{r['resource']:6s} {r['achieved_gbps']:9.3f} GB/s "
                  f"= {r['ceiling_frac']:.0%} of "
                  f"{r.get('ceiling_gbps', 0):.3f}", file=out)
    for node, tile in sorted((st.get("tiles") or {}).items()):
        line = f"tile {node}: {tile.get('state')}"
        if tile.get("pinned_tile") is not None:
            line += (f" pinned={tile['pinned_tile']} "
                     f"best={tile.get('best_tile')} "
                     f"drift={tile.get('drift', 0):+.1%}")
        print(line, file=out)
    cx = st.get("codecs") or {}
    if cx.get("mix"):
        print("codecs: " + " ".join(
            f"{tag}={n}" for tag, n in sorted(cx["mix"].items()))
            + f" ({len(cx.get('volumes', {}))} ec volumes)", file=out)
    hot = st.get("hot_tier") or {}
    if hot:
        ev = hot.get("events") or {}
        ratio = hot.get("hit_ratio")
        print(f"hot tier: hit_ratio="
              + (f"{ratio:.1%}" if ratio is not None else "n/a")
              + f" local={ev.get('hit_local', 0)} "
              f"routed={ev.get('route_out', 0)} "
              f"served_for_peers={ev.get('route_in', 0)} "
              f"direct={ev.get('direct', 0)} "
              f"seeded={ev.get('seeded', 0)} "
              f"route_fail={ev.get('route_fail', 0)}", file=out)
        for n in hot.get("nodes") or []:
            nev = n.get("events") or {}
            vc = n.get("vid_cache") or {}
            print(f"  {n.get('node', '?'):22s} "
                  f"ring={len(n.get('ring') or [])} "
                  f"local={nev.get('hit_local', 0)} "
                  f"routed={nev.get('route_out', 0)} "
                  f"in={nev.get('route_in', 0)} "
                  f"vid_cache h/m={vc.get('hits', 0)}/"
                  f"{vc.get('misses', 0)}"
                  + (" stream" if n.get("vid_stream_live") else ""),
                  file=out)


@command("cluster.metrics")
def cmd_cluster_metrics(env: CommandEnv, args, out):
    """Dump the federated cluster exposition (/cluster/metrics): every
    node's /metrics merged with a `node` label per sample.  -refresh
    forces a fleet pull; -grep STR filters sample lines."""
    flags = parse_flags(args)
    qs = "?refresh=1" if "refresh" in flags else ""
    req = urllib.request.Request(
        f"{_tls_scheme()}://{env.master}/cluster/metrics{qs}")
    with urllib.request.urlopen(req, timeout=60) as r:
        text = r.read().decode("utf-8", "replace")
    needle = flags.get("grep")
    for line in text.splitlines():
        if needle and needle not in line:
            continue
        print(line, file=out)


@command("cluster.trace")
def cmd_cluster_trace(env: CommandEnv, args, out):
    """Cross-node trace waterfall.  `cluster.trace <trace_id>` stitches
    one trace id from every node's span ring into a parent-ordered tree
    with per-hop network time; with no id (optionally -min_ms N) it
    lists recent traces fleet-wide.  -json emits the raw assembly."""
    flags = parse_flags(args)
    tid = next((a for a in args if not a.startswith("-")
                and a not in flags.values()), None)
    if tid is None:
        qs = urllib.parse.urlencode(
            {"min_ms": flags.get("min_ms", "0"),
             "limit": flags.get("limit", "20")})
        listing = env.master_get(f"/cluster/traces?{qs}")
        for rec in listing.get("traces", []):
            mark = " ERR" if rec.get("error") else ""
            print(f"  {rec['trace_id']} {rec['ms']:10.1f}ms "
                  f"spans={rec['spans']:<4d} "
                  f"servers={','.join(rec['servers'])}{mark}", file=out)
        if not listing.get("traces"):
            print("no traces (raise the sample rate or lower -min_ms)",
                  file=out)
        return
    wf = env.master_get(f"/cluster/trace/{tid}")
    if "json" in flags:
        print(json.dumps(wf, separators=(",", ":")), file=out)
        return
    print(f"trace {wf['trace_id']}: {wf['ms']}ms, "
          f"{wf['span_count']} spans across "
          f"{', '.join(wf['servers']) or 'unknown servers'}"
          + (" [ERROR]" if wf.get("error") else ""), file=out)
    for sp in wf.get("spans", []):
        pad = "  " * (sp.get("depth", 0) + 1)
        net = f" net={sp['net_ms']}ms" if "net_ms" in sp else ""
        err = " ERR" if sp.get("error") else ""
        node = f" @{sp['node']}" if sp.get("node") else ""
        print(f"{pad}{sp['name']:<28s} {sp['ms']:9.2f}ms"
              f"{net}{node}{err}", file=out)


@command("cluster.canary")
def cmd_cluster_canary(env: CommandEnv, args, out):
    """Canary prober status (/cluster/canary): per-gateway-path probe
    outcomes, latency quantiles, and the pinned trace id of the last
    probe (feed it to cluster.trace).  -probe runs one round now;
    -json dumps the raw status."""
    flags = parse_flags(args)
    params = {"probe": "1"} if "probe" in flags else {}
    st = env.master_get("/cluster/canary", **params)
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    print(f"canary: interval={st.get('interval_s')}s "
          f"running={st.get('running')} "
          f"paths={','.join(st.get('enabled_paths', []))}", file=out)
    if not st.get("paths"):
        print("  no probes recorded yet (try -probe)", file=out)
    for path, rec in sorted(st.get("paths", {}).items()):
        p99 = f" p99={rec['p99_ms']:.1f}ms" if rec.get("p99_ms") else ""
        err = f" error={rec['error']}" if rec.get("error") else ""
        print(f"  {path:9s} {rec['outcome']:5s} {rec['ms']:8.1f}ms"
              f"{p99} trace={rec['trace_id']}{err}", file=out)


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _ascii_spark(points: list) -> str:
    """Unicode sparkline over [ts, value|None] points (gaps become
    spaces) — the terminal twin of the dashboard's SVG lines."""
    vals = [v for _, v in points if v is not None]
    if not vals:
        return "(no data)"
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        " " if v is None else
        _SPARK_CHARS[min(len(_SPARK_CHARS) - 1,
                         int((v - lo) / span * len(_SPARK_CHARS)))]
        for _, v in points)


@command("cluster.history")
def cmd_cluster_history(env: CommandEnv, args, out):
    """Range query over the master's embedded history store
    (/cluster/history).  cluster.history -series NAME [-labels k=v,k2=v2]
    [-range SECONDS] [-step SECONDS] [-agg min|max|last|sum|avg|rate|p99]
    [-refresh] [-json].  One sparkline per label set; `-agg p99` reads a
    histogram family's quantile over time (e.g. -series
    weedtpu_volume_request_seconds -agg p99).  Runbook: an alert names
    the series — this shows WHEN it started moving, and cluster.trace
    shows why."""
    flags = parse_flags(args)
    if "series" not in flags:
        raise RuntimeError("cluster.history requires -series NAME")
    params = {"series": flags["series"],
              "range": flags.get("range", "600")}
    for k in ("labels", "step", "agg"):
        if k in flags:
            params[k] = flags[k]
    if "refresh" in flags:
        params["refresh"] = "1"
    res = env.master_get("/cluster/history", **params)
    if "json" in flags:
        print(json.dumps(res, separators=(",", ":")), file=out)
        return
    print(f"{res['series']} agg={res['agg']} range="
          f"{int(res['end'] - res['start'] + res['step'])}s "
          f"step={res['step']:g}s"
          + (f" res={res['resolution_s']:g}s"
             if "resolution_s" in res else ""), file=out)
    for vec in res.get("vectors", []):
        lbl = ",".join(f"{k}={v}" for k, v in
                       sorted(vec["labels"].items())) or "(all)"
        pts = vec["points"]
        last = next((v for _, v in reversed(pts) if v is not None), None)
        last_s = "-" if last is None else f"{last:.4g}"
        print(f"  {lbl:44s} {_ascii_spark(pts)} {last_s}", file=out)
    if not res.get("vectors"):
        print("  no matching series (check -series/-labels; the store "
              "records on aggregator ticks — try -refresh)", file=out)


@command("cluster.alerts")
def cmd_cluster_alerts(env: CommandEnv, args, out):
    """Alert-rule engine state (/cluster/alerts): per-rule, per-label-set
    ok/pending/firing with hysteresis timestamps and the pinned exemplar
    trace of whatever fired.  -refresh runs one scrape+evaluate tick
    first; -json dumps the raw status.  Runbook: alert fires ->
    cluster.history -series <its series> (when did it start) ->
    cluster.trace <exemplar> (why)."""
    flags = parse_flags(args)
    params = {"refresh": "1"} if "refresh" in flags else {}
    st = env.master_get("/cluster/alerts", **params)
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    if not st.get("rules"):
        print("no alert rules configured (WEEDTPU_ALERT_RULES)", file=out)
        return
    print(f"alerts: {st.get('state', 'ok')}", file=out)
    for r in st["rules"]:
        n_fire = sum(1 for g in r.get("groups", [])
                     if g["state"] == "firing")
        print(f"  {r['name']:24s} {r['state']:8s} [{r['kind']}] "
              f"series={r['series']} window={r['window_s']:g}s "
              f"for={r['for_s']:g}s groups={len(r.get('groups', []))} "
              f"firing={n_fire}", file=out)
        for g in r.get("groups", []):
            if g["state"] == "ok":
                continue
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(g.get("labels", {}).items())) or "-"
            val = "stale" if g.get("stale") else g.get("value")
            ex = f" trace={g['exemplar']}" if g.get("exemplar") else ""
            print(f"    {g['state'].upper():8s} {lbl} value={val}{ex}",
                  file=out)


@command("cluster.geo")
def cmd_cluster_geo(env: CommandEnv, args, out):
    """Geo-replication observatory (/cluster/geo): per sync direction,
    replication lag (seconds since the last applied event's mtime),
    source backlog depth, applied/skipped/error counters and the stall
    flag; plus the divergence auditor's verdict per prefix, WAN byte
    totals by region, registered peer masters, and the geo alert
    states.  -refresh runs one scrape tick first; -json dumps raw.
    Runbook: replication_stalled fires -> cluster.geo (which direction?
    backlog growing means the WAN link or the remote filer; errors
    growing with zero backlog means a poisoned event) -> cluster.trace
    <its last_trace_id> (where the apply died, which region's hop)."""
    flags = parse_flags(args)
    params = {"refresh": "1"} if "refresh" in flags else {}
    st = env.master_get("/cluster/geo", **params)
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    region = st.get("region") or "-"
    peers = ",".join(st.get("peers") or []) or "-"
    print(f"region: {region}  peer_masters: {peers}", file=out)
    dirs = st.get("directions") or {}
    if not dirs:
        print("no replication pumps reporting (FilerSync not running,"
              " or no scrape yet: try -refresh)", file=out)
    for d, rec in sorted(dirs.items()):
        stall = "  STALLED" if rec.get("stalled") else ""
        rate = rec.get("apply_rate_eps")
        rate_s = f" rate={rate:.2f}/s" if rate is not None else ""
        print(f"  {d:10s} lag={rec.get('lag_s', 0.0):8.2f}s "
              f"backlog={rec.get('backlog_events', 0.0):g} "
              f"applied={rec.get('applied', 0.0):g} "
              f"skipped={rec.get('skipped', 0.0):g} "
              f"errors={rec.get('errors', 0.0):g}"
              f"{rate_s}{stall}", file=out)
    div = st.get("divergence") or {}
    for prefix, v in sorted((div.get("prefixes") or {}).items()):
        verdict = "DIVERGED" if v else "clean"
        print(f"  divergence {prefix}: {verdict}", file=out)
    audits = div.get("audits") or {}
    if audits:
        print("  audits: " + " ".join(
            f"{k}={v:g}" for k, v in sorted(audits.items())), file=out)
    wan = st.get("wan") or {}
    print(f"  wan: sent={wan.get('sent_bytes', 0.0):g}B "
          f"recv={wan.get('recv_bytes', 0.0):g}B", file=out)
    for region_, by_dir in sorted((wan.get("by_region") or {}).items()):
        for direction, by_cls in sorted(by_dir.items()):
            tot = sum(by_cls.values())
            print(f"    -> {region_} {direction}={tot:g}B", file=out)
    alerts = st.get("alerts") or {}
    if alerts:
        print("  alerts: " + " ".join(
            f"{k}={v}" for k, v in sorted(alerts.items())), file=out)


@command("cluster.loops")
def cmd_cluster_loops(env: CommandEnv, args, out):
    """Control-plane observatory (/cluster/loops): per master background
    loop, tick wall time (last/EMA/max vs its interval), CPU seconds,
    items processed, backlog depth, overrun and error counts — plus
    live subsystem cardinality (registry/history/alert/interference/
    heat/trace entries).  -refresh runs one scrape tick first; -json
    dumps raw.  Runbook: loop_overrun fires -> cluster.loops (which
    loop, how far past its interval, does wall time track node count?)
    -> if it's the aggregator/fan-out plane, raise WEEDTPU_FANOUT_POOL;
    otherwise raise that loop's own interval knob or shed its input."""
    flags = parse_flags(args)
    params = {"refresh": "1"} if "refresh" in flags else {}
    st = env.master_get("/cluster/loops", **params)
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    print(f"loops: {st.get('headline', '')}", file=out)
    loops = st.get("loops") or {}
    for name, lp in sorted(loops.items()):
        iv = lp.get("interval")
        iv_s = f"{iv:g}s" if iv else "-"
        flag = ""
        if iv and lp.get("wall_last", 0.0) > iv:
            flag = "  OVERRUN"
        elif lp.get("overruns"):
            flag = f"  overruns={lp['overruns']}"
        err = lp.get("last_error")
        err_s = f"  last_error={err['error']}" if err else ""
        print(f"  {name:16s} ticks={lp.get('ticks', 0):<6d} "
              f"last={lp.get('wall_last', 0.0) * 1000:8.2f}ms "
              f"ema={lp.get('wall_ema', 0.0) * 1000:8.2f}ms "
              f"max={lp.get('wall_max', 0.0) * 1000:8.2f}ms "
              f"interval={iv_s:6s} cpu={lp.get('cpu_total', 0.0):.3f}s "
              f"items={lp.get('items_total', 0.0):g} "
              f"backlog={lp.get('backlog', 0.0):g}"
              f"{flag}{err_s}", file=out)
    subs = st.get("subsystems") or {}
    if subs:
        print("entries: " + " ".join(f"{k}={v}" for k, v in
                                     sorted(subs.items())), file=out)


@command("cluster.interference")
def cmd_cluster_interference(env: CommandEnv, args, out):
    """Live interference observatory + governor (/cluster/interference):
    per background traffic class, the fleet foreground-impact index
    (fractional foreground read-p99 inflation, worst node shown), the
    governed rates (repair cross-rack budget, conversion pacing, fleet
    scrub) against their floors/ceilings, and the last retune decisions
    with their pinned traces.  -refresh runs one scrape+observe+retune
    tick first; -json dumps raw.  Runbook: interference_high fires ->
    cluster.interference (which class, which node, is the rate at its
    floor) -> cluster.trace <retune trace_id> (what the governor did and
    when)."""
    flags = parse_flags(args)
    params = {"refresh": "1"} if "refresh" in flags else {}
    st = env.master_get("/cluster/interference", **params)
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    obs = st.get("interference") or {}
    gov = st.get("governor") or {}
    print(f"interference: {'on' if obs.get('enabled') else 'OFF'} "
          f"ticks={obs.get('ticks', 0)} · governor: "
          f"{'on' if gov.get('enabled') else 'OFF'} "
          f"target={gov.get('target_index')} "
          f"retunes={gov.get('retunes', 0)}", file=out)
    classes = obs.get("classes") or {}
    if classes:
        for cls, rec in sorted(classes.items()):
            print(f"  index {cls:12s} {rec.get('index', 0.0):7.4f}  "
                  f"worst {rec.get('node', '-')}", file=out)
    else:
        print("  no impact measured yet (quiet fleet or no baseline)",
              file=out)
    for name, t in sorted((gov.get("targets") or {}).items()):
        at = ""
        if t.get("rate", 0) <= t.get("floor", 0):
            at = "  [AT FLOOR]"
        elif t.get("rate", 0) >= t.get("ceiling", 0):
            at = "  [at ceiling]"
        print(f"  rate  {name:12s} {t.get('rate'):>12g} "
              f"(floor {t.get('floor'):g}, ceiling {t.get('ceiling'):g}, "
              f"class {t.get('class')}, index {t.get('index')}){at}",
              file=out)
    for d in (gov.get("decisions") or [])[-5:]:
        print(f"  retune {d.get('target'):12s} {d.get('direction'):4s} "
              f"{d.get('from'):g} -> {d.get('to'):g} "
              f"index={d.get('index')} trace={d.get('trace_id')}",
              file=out)
    nodes = obs.get("nodes") or {}
    for node, rec in sorted(nodes.items()):
        busy = {c: v for c, v in (rec.get("index") or {}).items()
                if v > 0.001}
        idx = " ".join(f"{c}={v:g}" for c, v in sorted(busy.items())) \
            or "-"
        print(f"  node {node}: quiet_p99="
              f"{rec.get('quiet_p99_ms')}ms last_p99="
              f"{rec.get('last_p99_ms')}ms index {idx}", file=out)


@command("cluster.autopilot")
def cmd_cluster_autopilot(env: CommandEnv, args, out):
    """Autopilot decision plane (/cluster/autopilot): mode
    (plan/execute/off), per-policy pacing buckets, hysteresis clocks,
    and the plan ledger with states and pinned trace ids.  -tick runs
    one policy pass first; -approve <id> executes one plan (the
    plan-mode runbook step); -abort <id> kills a not-yet-executing
    plan; -wait blocks until launched executions settle; -json dumps
    raw.  Runbook: cluster.autopilot -> inspect a plan's reason ->
    cluster.autopilot -approve <id> (or -abort) -> cluster.trace
    <trace_id> for the full planning+execution waterfall."""
    flags = parse_flags(args)
    body = {}
    if "tick" in flags:
        body["tick"] = True
    if "approve" in flags:
        body["approve"] = flags["approve"]
    if "abort" in flags:
        body["abort"] = flags["abort"]
    if "wait" in flags:
        body["wait"] = True
    if body:
        resp = env.master_post("/cluster/autopilot", body)
        st = resp.get("status") or {}
    else:
        resp = {}
        st = env.master_get("/cluster/autopilot")
    if "json" in flags:
        print(json.dumps(resp or st, separators=(",", ":")), file=out)
        return
    counts = st.get("states") or {}
    print(f"autopilot: mode={st.get('mode')} ticks={st.get('ticks', 0)} "
          f"actuator_calls={st.get('actuator_calls', 0)} · plans "
          + " ".join(f"{s}={counts.get(s, 0)}"
                     for s in ("planned", "approved", "executing",
                               "done", "aborted")), file=out)
    for name, b in sorted((st.get("buckets") or {}).items()):
        print(f"  bucket {name:8s} rate={b.get('rate_per_s'):g}/s "
              f"burst={b.get('burst'):g} tokens={b.get('tokens'):g}",
              file=out)
    hys = st.get("hysteresis") or {}
    cold = hys.get("cold_tracking") or {}
    if cold:
        line = " ".join(f"v{v}:{s:.0f}s" for v, s in
                        sorted(cold.items())[:8])
        print(f"  cold-tracking {line}", file=out)
    for p in (st.get("plans") or [])[-10:]:
        reason = p.get("reason") or {}
        why = " ".join(f"{k}={v}" for k, v in sorted(reason.items()))
        where = p.get("node") or (f"{p.get('source')} -> "
                                  f"{p.get('target')}"
                                  if p.get("source") else "")
        print(f"  {p.get('id'):>6s} {p.get('policy'):16s} "
              f"vid={p.get('vid')} [{p.get('state')}] {where} {why} "
              f"trace={p.get('trace_id')}", file=out)
        if p.get("error"):
            print(f"         error: {p['error']}", file=out)
    if resp.get("approved"):
        print(f"approved {resp['approved']['id']}", file=out)
    if resp.get("aborted"):
        print(f"aborted {resp['aborted']['id']}", file=out)


@command("chaos.status")
def cmd_chaos_status(env: CommandEnv, args, out):
    """Resilience-plane status: per-peer circuit-breaker states, the
    retry-budget fill, hedging config, armed chaos faults (partitions /
    injected latency / error rates / disk faults), and the canary's
    last outcomes — the operator's one-stop "what is broken vs what did
    we break on purpose" view.  -json dumps the raw snapshot.  Runbook:
    SLO burn alert -> cluster.canary (which path) -> cluster.trace
    (which hop) -> chaos.status (is a breaker open / a fault armed)."""
    flags = parse_flags(args)
    st = env.master_get("/maintenance/status")
    res = st.get("resilience") or {}
    try:
        canary = env.master_get("/cluster/canary")
    except RuntimeError:
        canary = {}
    pl = st.get("planner") or {}
    if "json" in flags:
        print(json.dumps({"resilience": res,
                          "states": st.get("states", {}),
                          "canary": canary.get("paths", {}),
                          "xrack": pl.get("xrack", {}),
                          "decisions": pl.get("decisions", []),
                          "repair_bytes_by_locality":
                              pl.get("repair_bytes_by_locality", {})},
                         separators=(",", ":")), file=out)
        return
    breakers = res.get("breakers") or {}
    if breakers:
        for peer, b in sorted(breakers.items()):
            extra = f" reopens_in={b['open_for_s']}s" \
                if "open_for_s" in b else ""
            print(f"breaker {peer}: {b.get('state'):9s} "
                  f"failures={b.get('failures')} trips={b.get('trips')}"
                  f"{extra}", file=out)
    else:
        print("breakers: all closed", file=out)
    budget = res.get("retry_budget") or {}
    classes = budget.get("classes") or {}
    print(f"retry budget: rate={budget.get('rate')}/s "
          f"burst={budget.get('burst')}"
          + ("".join(f" {c}={v}" for c, v in sorted(classes.items()))
             if classes else ""), file=out)
    print(f"hedge: pct={res.get('hedge_pct')}", file=out)
    faults = res.get("faults") or {}
    armed = [f"partition {a}<->{b}"
             for a, b in faults.get("partitions", [])]
    armed += [f"latency {d}={ms[0]}ms±{ms[1]}"
              for d, ms in (faults.get("latency_ms") or {}).items()]
    armed += [f"error_rate {d}={p}%"
              for d, p in (faults.get("error_rate") or {}).items()]
    if faults.get("shard_write_error"):
        armed.append(f"shard_write_error={faults['shard_write_error']}")
    print("faults: " + ("; ".join(armed) if armed else "none armed"),
          file=out)
    _print_repair_plane(pl, out)
    states = st.get("states", {})
    if any(v for k, v in states.items() if k != "healthy"):
        print("volume states: " + " ".join(
            f"{k}={v}" for k, v in sorted(states.items()) if v),
            file=out)
    for path, rec in sorted((canary.get("paths") or {}).items()):
        print(f"canary {path:9s} {rec.get('outcome'):5s} "
              f"{rec.get('ms', 0):8.1f}ms trace={rec.get('trace_id')}",
              file=out)


@command("cluster.heat")
def cmd_cluster_heat(env: CommandEnv, args, out):
    """Fleet workload heat (/cluster/heat): top-K hot chunks, volumes,
    and tenants from the decayed streaming sketches, with estimated RPS,
    byte rates, read/write mix, and per-volume degraded-read fraction.
    -refresh forces a fresh fleet fan-out; -top N rows per dimension
    (default 10); -json dumps the raw merge.  Runbook: an SLO burn alert
    names the symptom — this names the tenant/volume driving it, and
    cluster.trace shows where its requests spend their time."""
    flags = parse_flags(args)
    params = {"refresh": "1"} if "refresh" in flags else {}
    st = env.master_get("/cluster/heat", **params)
    if "json" in flags:
        print(json.dumps(st, separators=(",", ":")), file=out)
        return
    try:
        top_n = max(1, int(flags.get("top", "10")))
    except ValueError:
        top_n = 10
    print(f"heat: k={st.get('k')} halflife={st.get('halflife_s')}s "
          f"nodes={len(st.get('nodes', []))}"
          + (f" node_errors={len(st['node_errors'])}"
             if st.get("node_errors") else ""), file=out)
    for dim in ("chunks", "volumes", "tenants"):
        d = st.get(dim, {})
        rows = d.get("top", [])[:top_n]
        print(f"{dim}: total ~{d.get('total_rps', 0)} rps", file=out)
        if not rows:
            print("  (no samples yet)", file=out)
            continue
        for r in rows:
            extras = []
            if r.get("read_fraction") is not None:
                extras.append(f"read%={100 * r['read_fraction']:.0f}")
            if r.get("degraded_fraction") is not None:
                extras.append(
                    f"degraded%={100 * r['degraded_fraction']:.1f}")
            print(f"  {r['key']:32s} ~{r['rps']:9.2f} rps "
                  f"~{r['bytes_rate'] / 1e6:8.3f} MB/s "
                  f"(est={r['est']:.1f}±{r['err']:.1f}) "
                  + " ".join(extras), file=out)


@command("volume.fsck")
def cmd_volume_fsck(env: CommandEnv, args, out):
    """Cross-check filer chunk references against volume needles
    (reference: command_volume_fsck.go:60-75).  Reports orphan needles
    (in volumes but unreferenced) and broken refs (referenced but gone).
    -json emits a machine-readable report including each volume's health
    state, last-scrub time, and quarantined ranges from the master's
    maintenance ledger."""
    env.require_lock()
    flags = parse_flags(args)
    as_json = "json" in flags
    filer = env.find_filer()
    # collect all chunk fids from the filer
    referenced: dict[int, set[int]] = {}
    stack = ["/"]
    while stack:
        d = stack.pop()
        listing = env.filer_list(filer, d)
        for e in listing:
            if e.get("IsDirectory"):
                stack.append(e["FullPath"])
                continue
            if not e.get("chunks"):
                continue
            # raw chunks (incl. manifest-blob fids) + manifest-resolved data
            # chunk fids are all legitimately referenced needles
            raw = env._call(
                f"{filer}{urllib.parse.quote(e['FullPath'])}?metadata=true")
            chunks = list(raw.get("chunks") or [])
            if any(c.get("is_chunk_manifest") for c in chunks):
                resolved = env._call(
                    f"{filer}{urllib.parse.quote(e['FullPath'])}"
                    "?metadata=true&resolveManifest=true")
                chunks += resolved.get("chunks") or []
            for c in chunks:
                try:
                    f = t.FileId.parse(c.get("fid", ""))
                    referenced.setdefault(f.volume_id, set()).add(f.key)
                except ValueError:
                    pass
    topo = env.topology()
    stored: dict[int, set[int]] = {}
    vol_nodes: dict[int, str] = {}
    for nid_, node in topo["nodes"].items():
        for vid in node["volumes"]:
            r = env.master_get_raw(nid_, "/admin/volume/needles", volume=vid)
            stored.setdefault(vid, set()).update(r.get("needles", []))
            vol_nodes[vid] = nid_
    report: dict[str, dict] = {}
    orphans = broken = 0
    for vid, needles in sorted(stored.items()):
        refs = referenced.get(vid, set())
        o = needles - refs
        b = refs - needles
        orphans += len(o)
        broken += len(b)
        report[str(vid)] = {"orphans": len(o), "broken_refs": len(b),
                            "needles": len(needles), "node": vol_nodes[vid]}
        if (o or b) and not as_json:
            print(f"volume {vid}: {len(o)} orphan needle(s), "
                  f"{len(b)} broken ref(s)", file=out)
    # refs into volumes that no longer exist anywhere are all broken —
    # but a volume converted to EC shards still exists (its needles just
    # can't be enumerated over /admin/volume/needles), so refs into it
    # are fine, not broken
    ec_vids = {int(v) for node in topo["nodes"].values()
               for v in node.get("ec_shards", {})}
    for vid in sorted(set(referenced) - set(stored)):
        if vid in ec_vids:
            report[str(vid)] = {"ec": True, "refs": len(referenced[vid])}
            continue
        b = len(referenced[vid])
        broken += b
        report[str(vid)] = {"missing": True, "broken_refs": b}
        if not as_json:
            print(f"volume {vid}: MISSING, {b} broken ref(s)", file=out)
    # fold in the master's health ledger so both output modes gate on
    # cluster health (state / quarantined ranges), not just refs
    try:
        health = env.master_get("/maintenance/status")
    except RuntimeError:
        health = {}
    for vid, v in (health.get("volumes") or {}).items():
        rec = report.setdefault(vid, {})
        rec["health"] = {
            "state": v.get("state"), "kind": v.get("kind"),
            "last_scrub": v.get("last_scrub"),
            "quarantined": v.get("quarantined") or {},
            "shards_missing": v.get("shards_missing", []),
        }
        if v.get("kind") == "ec":
            rec["codec"] = v.get("codec", "rs_10_4")
    # `ok` is the chaos/CI gate: false — and a nonzero shell exit — on
    # anything that means data is damaged or being served around damage
    # (broken refs, corrupt/critical state, quarantined ranges).
    # Degraded/under-replicated volumes still read correctly, and
    # orphans are garbage not damage: neither flips it.  `healthy`
    # stays the stricter everything-is-green bit.  BOTH output modes
    # return the same exit code — a gate written without -json must not
    # quietly pass on a quarantined cluster.
    damaged = broken > 0
    for r in report.values():
        h = r.get("health") or {}
        if h.get("state") in ("corrupt", "critical") or \
                h.get("quarantined"):
            damaged = True
    if as_json:
        print(json.dumps({
            "volumes": report, "orphans": orphans, "broken_refs": broken,
            "states": health.get("states", {}),
            "ok": not damaged,
            "healthy": broken == 0 and all(
                (r.get("health") or {}).get("state") in (None, "healthy")
                for r in report.values()),
        }, separators=(",", ":")), file=out)
        return 1 if damaged else 0
    print(f"volume.fsck: {orphans} orphan(s), {broken} broken ref(s) "
          f"across {len(stored)} volume(s)"
          + ("" if not damaged else " — DAMAGED (corrupt/quarantined "
             "state; see maintenance.status)"), file=out)
    return 1 if damaged else 0


@command("collection.list")
def cmd_collection_list(env: CommandEnv, args, out):
    topo = env.topology()
    cols = {rec.get("collection", "")
            for rec in collect_volume_infos(topo).values()}
    for name in sorted(cols):
        print(f"collection {name or '(default)'}", file=out)
    if not cols:
        print("no collections", file=out)


@command("collection.delete")
def cmd_collection_delete(env: CommandEnv, args, out):
    """Delete every volume of a collection, writable or not (reference:
    command_collection_delete.go)."""
    env.require_lock()
    flags = parse_flags(args)
    name = flags.get("collection", flags.get("name", ""))
    topo = env.topology()
    deleted = 0
    for vid, rec in sorted(collect_volume_infos(topo).items()):
        if rec.get("collection", "") != name:
            continue
        for url in rec["nodes"]:
            env.vs_post(url, "/admin/volume/delete", {"volume": vid})
            deleted += 1
    print(f"collection.delete {name!r}: {deleted} volume replica(s) removed",
          file=out)


# ---- filesystem commands over the filer (reference: weed/shell/command_fs_*.go)


@command("fs.ls")
def cmd_fs_ls(env: CommandEnv, args, out):
    flags = parse_flags(args)
    path = env.resolve(
        (args and not args[-1].startswith("-") and args[-1]) or ".")
    long = "l" in flags or "long" in flags
    filer = env.find_filer()
    for e in env.filer_list(filer, path):
        name = e["FullPath"].rsplit("/", 1)[-1]
        if e.get("IsDirectory"):
            name += "/"
        if long:
            print(f"{e.get('FileSize', 0):>12} {name}", file=out)
        else:
            print(name, file=out)


@command("fs.cat")
def cmd_fs_cat(env: CommandEnv, args, out):
    path = env.resolve(args[-1])
    filer = env.find_filer()
    data = env.filer_read(filer, path)
    out.write(data.decode(errors="replace"))


@command("fs.rm")
def cmd_fs_rm(env: CommandEnv, args, out):
    flags = parse_flags(args)
    path = env.resolve(args[-1])
    filer = env.find_filer()
    env.filer_delete(filer, path, recursive="r" in flags or "rf" in flags)
    print(f"removed {path}", file=out)


@command("fs.mkdir")
def cmd_fs_mkdir(env: CommandEnv, args, out):
    path = env.resolve(args[-1]).rstrip("/") + "/"
    filer = env.find_filer()
    env._call(f"{filer}{urllib.parse.quote(path)}", {}, method="POST")
    print(f"created {path}", file=out)


@command("fs.mv")
def cmd_fs_mv(env: CommandEnv, args, out):
    src, dst = env.resolve(args[-2]), env.resolve(args[-1])
    filer = env.find_filer()
    env._call(f"{filer}{urllib.parse.quote(dst)}?mv.from="
              f"{urllib.parse.quote(src)}", {}, method="POST")
    print(f"moved {src} -> {dst}", file=out)


@command("fs.du")
def cmd_fs_du(env: CommandEnv, args, out):
    path = env.resolve(
        (args and not args[-1].startswith("-") and args[-1]) or ".")
    filer = env.find_filer()
    total = [0]
    files = [0]

    def walk(d):
        for e in env.filer_list(filer, d):
            if e.get("IsDirectory"):
                walk(e["FullPath"])
            else:
                total[0] += e.get("FileSize", 0)
                files[0] += 1
    walk(path.rstrip("/") or "/")
    print(f"{total[0]} bytes in {files[0]} file(s) under {path}", file=out)


@command("fs.meta.cat")
def cmd_fs_meta_cat(env: CommandEnv, args, out):
    path = env.resolve(args[-1])
    filer = env.find_filer()
    meta = env._call(f"{filer}{urllib.parse.quote(path)}?metadata=true")
    print(json.dumps(meta, indent=2, default=str), file=out)


@command("s3.bucket.list")
def cmd_s3_bucket_list(env: CommandEnv, args, out):
    filer = env.find_filer()
    for e in env.filer_list(filer, "/buckets"):
        if e.get("IsDirectory"):
            print(e["FullPath"].rsplit("/", 1)[-1], file=out)


@command("s3.bucket.create")
def cmd_s3_bucket_create(env: CommandEnv, args, out):
    flags = parse_flags(args)
    name = flags.get("name", args[-1] if args else "")
    filer = env.find_filer()
    env._call(f"{filer}/buckets/{name}/", {}, method="POST")
    print(f"created bucket {name}", file=out)


@command("s3.bucket.delete")
def cmd_s3_bucket_delete(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    name = flags.get("name", args[-1] if args else "")
    filer = env.find_filer()
    env.filer_delete(filer, f"/buckets/{name}", recursive=True)
    print(f"deleted bucket {name}", file=out)


@command("volume.tier.move")
def cmd_volume_tier_move(env: CommandEnv, args, out):
    """Move a volume's data file to a remote tier (reference:
    command_volume_tier_move.go).  -dest kind:option, e.g.
    -dest local:/cold-storage."""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    from seaweedfs_tpu.remote_storage import parse_remote_spec
    kind, options = parse_remote_spec(flags.get("dest", ""))
    if kind == "local" and not options.get("directory"):
        raise RuntimeError(
            "volume.tier.move needs -dest local:<directory> or "
            "-dest s3:endpoint=..,bucket=..")
    for url in env.volume_locations(vid):
        r = env.vs_post(url, "/admin/volume/tier_move",
                        {"volume": vid, "kind": kind,
                         "options": options})
        print(f"volume {vid} on {url} -> tier {kind} "
              f"(backend={r.get('backend')})", file=out)


@command("remote.mount")
def cmd_remote_mount(env: CommandEnv, args, out):
    """Mount a remote store's objects under a filer directory (reference:
    command_remote_mount.go).  -remote kind:option -dir /mounted"""
    flags = parse_flags(args)
    from seaweedfs_tpu.remote_storage import (make_remote,
                                              parse_remote_spec,
                                              sync_remote_to_filer)
    kind, options = parse_remote_spec(flags.get("remote", ""))
    mount_dir = flags.get("dir", "/remote")
    cache = flags.get("cache", "false") == "true"
    remote = make_remote(kind, **options)
    filer = env.find_filer()
    n = sync_remote_to_filer(remote, filer, mount_dir, cache=cache)
    # record the mapping so the filer can read placeholders THROUGH the
    # remote on demand (reference: remote_mapping.go + read_remote.go)
    env._call(f"{filer}/__admin__/remote_mounts",
              {"set": {mount_dir: flags.get("remote", "")}})
    print(f"remote.mount: {n} object(s) from {kind} -> {mount_dir}"
          + ("" if cache else " (placeholders; read-through live, "
                              "remote.cache to pin)"),
          file=out)


@command("remote.cache")
def cmd_remote_cache(env: CommandEnv, args, out):
    """Pull remote object content into the mounted directory (reference:
    command_remote_cache.go)."""
    flags = parse_flags(args)
    from seaweedfs_tpu.remote_storage import (make_remote,
                                              parse_remote_spec,
                                              sync_remote_to_filer)
    kind, options = parse_remote_spec(flags.get("remote", ""))
    mount_dir = flags.get("dir", "/remote")
    remote = make_remote(kind, **options)
    filer = env.find_filer()
    n = sync_remote_to_filer(remote, filer, mount_dir, cache=True)
    print(f"remote.cache: {n} object(s) cached under {mount_dir}", file=out)


@command("volume.grow")
def cmd_volume_grow(env: CommandEnv, args, out):
    """Pre-allocate writable volumes (reference: command_volume_grow /
    the master /vol/grow endpoint)."""
    env.require_lock()
    flags = parse_flags(args)
    r = env.master_post("/vol/grow",
                        count=flags.get("count", "1"),
                        collection=flags.get("collection", ""),
                        replication=flags.get("replication", ""),
                        ttl=flags.get("ttl", ""))
    print(f"grew {r.get('count', 0)} volume(s)", file=out)


@command("volume.move")
def cmd_volume_move(env: CommandEnv, args, out):
    """Move one volume between servers: copy to target, delete from
    source (reference: command_volume_move.go)."""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    target = flags["target"]
    locs = env.volume_locations(vid)
    if not locs:
        raise RuntimeError(f"volume {vid} not found")
    source = flags.get("source", locs[0])
    col = collect_volume_infos(env.topology()).get(vid, {})
    move_volume(env, vid, source, target, col.get("collection", ""))
    print(f"moved volume {vid}: {source} -> {target}", file=out)


@command("volume.mount")
def cmd_volume_mount(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    node = flags["node"]
    env.vs_post(node, "/admin/volume/mount",
                {"volume": vid, "collection": flags.get("collection", "")})
    print(f"mounted volume {vid} on {node}", file=out)


@command("volume.unmount")
def cmd_volume_unmount(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    node = flags.get("node")
    if not node:
        locs = env.volume_locations(vid)
        if not locs:
            raise RuntimeError(f"volume {vid} not found")
        node = locs[0]
    env.vs_post(node, "/admin/volume/unmount", {"volume": vid})
    print(f"unmounted volume {vid} on {node}", file=out)


@command("fs.tree")
def cmd_fs_tree(env: CommandEnv, args, out):
    """Recursive directory tree (reference: command_fs_tree.go)."""
    path = (args and not args[-1].startswith("-") and args[-1]) or "/"
    filer = env.find_filer()

    def walk(d, depth):
        for e in env.filer_list(filer, d):
            name = e["FullPath"].rsplit("/", 1)[-1]
            print("  " * depth + ("+" if e.get("IsDirectory") else "-")
                  + " " + name, file=out)
            if e.get("IsDirectory"):
                walk(e["FullPath"], depth + 1)
    print(path, file=out)
    walk(path.rstrip("/") or "/", 1)


@command("s3.clean.uploads")
def cmd_s3_clean_uploads(env: CommandEnv, args, out):
    """Purge abandoned multipart uploads older than -timeAgo (reference:
    command_s3_clean_uploads.go)."""
    env.require_lock()
    flags = parse_flags(args)
    max_age = _parse_duration(flags.get("timeAgo", "24h"))
    filer = env.find_filer()
    import time as _time
    cutoff = _time.time() - max_age
    removed = 0
    for bucket in env.filer_list(filer, "/buckets"):
        if not bucket.get("IsDirectory"):
            continue
        uploads_dir = bucket["FullPath"] + "/.uploads"
        for up in env.filer_list(filer, uploads_dir):
            if up.get("Mtime", 0) < cutoff:
                env.filer_delete(filer, up["FullPath"], recursive=True)
                removed += 1
                print(f"removed {up['FullPath']}", file=out)
    print(f"s3.clean.uploads: {removed} abandoned upload(s) removed",
          file=out)


def _parse_duration(s: str) -> float:
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400}
    if s and s[-1] in units:
        return float(s[:-1]) * units[s[-1]]
    return float(s or 0)


@command("fs.meta.save")
def cmd_fs_meta_save(env: CommandEnv, args, out):
    """Dump a filer subtree's metadata (entries incl. chunk refs) to a
    local JSONL file (reference: command_fs_meta_save.go).
      fs.meta.save -o meta.jsonl [/path]"""
    flags = parse_flags(args)
    # first token that is neither a flag nor a flag's value is the path
    path = flags.get("path", "/")
    skip_next = False
    for tok in args:
        if skip_next:
            skip_next = False
            continue
        if tok.startswith("-"):
            skip_next = "=" not in tok
            continue
        path = tok
        break
    out_path = flags.get("o", "filer_meta.jsonl")
    filer = env.find_filer()
    count = 0
    with open(out_path, "w", encoding="utf-8") as f:
        stack = [path.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            for e in env.filer_list(filer, d):
                if e.get("IsDirectory"):
                    stack.append(e["FullPath"])
                meta = env._call(
                    f"{filer}{urllib.parse.quote(e['FullPath'])}"
                    "?metadata=true")
                f.write(json.dumps(meta, separators=(",", ":")) + "\n")
                count += 1
    print(f"fs.meta.save: {count} entr(ies) -> {out_path}", file=out)


@command("fs.meta.load")
def cmd_fs_meta_load(env: CommandEnv, args, out):
    """Restore entries from an fs.meta.save dump via the filer raw-entry
    API (reference: command_fs_meta_load.go).  Chunk refs are restored
    as-is — blob data must still exist on the volume servers."""
    flags = parse_flags(args)
    in_path = flags.get("i", args[-1] if args else "filer_meta.jsonl")
    filer = env.find_filer()
    count = 0
    with open(in_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            entry = json.loads(line)
            env._call(f"{filer}/__admin__/entry", {"entry": entry})
            count += 1
    print(f"fs.meta.load: {count} entr(ies) restored", file=out)


@command("volume.configure.replication")
def cmd_volume_configure_replication(env: CommandEnv, args, out):
    """Change a volume's replica placement in its super block
    (reference: command_volume_configure_replication.go)."""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    rp = flags.get("replication", "000")
    t.ReplicaPlacement.parse(rp)  # validate
    for url in env.volume_locations(vid):
        env.vs_post(url, "/admin/volume/configure_replication",
                    {"volume": vid, "replication": rp})
        print(f"volume {vid} on {url}: replication -> {rp}", file=out)


@command("s3.configure")
def cmd_s3_configure(env: CommandEnv, args, out):
    """Manage S3 identities in the filer-stored identity.json, which
    running gateways hot-reload (reference: command_s3_configure.go).
      s3.configure -user NAME -access_key AK -secret_key SK -actions Admin
      s3.configure -user NAME -delete
      s3.configure -list"""
    env.require_lock()
    flags = parse_flags(args)
    filer = env.find_filer()
    from seaweedfs_tpu.s3.iamapi_server import IDENTITY_PATH
    try:
        cfg = json.loads(env.filer_read(filer, IDENTITY_PATH))
    except Exception:
        cfg = {"identities": []}
    idents = cfg.setdefault("identities", [])
    if flags.get("list"):
        for i in idents:
            keys = ",".join(c.get("accessKey", "") for c in
                            i.get("credentials", []))
            print(f"{i.get('name')}: actions={i.get('actions')} "
                  f"keys=[{keys}]", file=out)
        if not idents:
            print("no identities configured", file=out)
        return
    user = flags.get("user", "")
    if not user:
        raise RuntimeError("s3.configure needs -user (or -list)")
    existing = next((i for i in idents if i.get("name") == user), None)
    if flags.get("delete"):
        if existing:
            idents.remove(existing)
            print(f"deleted identity {user}", file=out)
    else:
        if existing is None:
            existing = {"name": user, "credentials": [], "actions": []}
            idents.append(existing)
        if flags.get("access_key"):
            existing["credentials"] = [{
                "accessKey": flags["access_key"],
                "secretKey": flags.get("secret_key", "")}]
        if flags.get("actions"):
            existing["actions"] = flags["actions"].split(",")
        print(f"configured identity {user}: {existing['actions']}", file=out)
    payload = json.dumps(cfg, indent=1).encode()
    req = urllib.request.Request(
        f"{_tls_scheme()}://{filer}{urllib.parse.quote(IDENTITY_PATH)}",
        data=payload, method="PUT")
    with urllib.request.urlopen(req, timeout=30):
        pass


@command("cluster.ps")
def cmd_cluster_ps(env: CommandEnv, args, out):
    """List non-volume cluster processes (reference: command_cluster_ps.go)."""
    members = env.master_get("/cluster/status").get("Members", {})
    if not members:
        print("no registered cluster processes", file=out)
    for kind, addrs in sorted(members.items()):
        for a in addrs:
            print(f"{kind} {a}", file=out)


@command("volume.vacuum.all")
def cmd_volume_vacuum_all(env: CommandEnv, args, out):
    """Master-driven vacuum scan (reference: topology_vacuum.go)."""
    env.require_lock()
    flags = parse_flags(args)
    r = env.master_post("/vol/vacuum",
                        garbageThreshold=flags.get("garbageThreshold", "0.3"))
    print(f"vacuumed {r.get('vacuumed', 0)} volume(s)", file=out)


def run_command(env: CommandEnv, line: str, out) -> int:
    """Run one shell line; returns the command's exit code (commands
    return None for success — a nonzero int marks an assertion-style
    failure, e.g. volume.fsck finding corruption, so scripted/CI
    invocations can gate on it)."""
    parts = shlex.split(line)
    if not parts:
        return 0
    fn = COMMANDS.get(parts[0])
    if fn is None:
        raise RuntimeError(f"unknown command {parts[0]!r} "
                           f"(have: {', '.join(sorted(COMMANDS))})")
    rc = fn(env, parts[1:], out)
    return int(rc) if rc else 0


# ---- breadth pass: cluster/raft/fs/tier/remote/mq commands --------------
# (reference command set: weed/shell/commands.go:41-48 — these close the
# largest remaining gaps against its ~80 commands)

@command("cluster.raft.ps")
def cmd_cluster_raft_ps(env: CommandEnv, args, out):
    """Show each master's raft state (reference: command_cluster_raft_ps)."""
    masters = {env.master}
    try:
        st = env.master_get("/raft/status")
        masters.update(st.get("peers", []))
        rows = [st]
    except RuntimeError:
        rows = []
    for m in sorted(masters - {env.master}):
        try:
            rows.append(env.master_get_raw(m, "/raft/status"))
        except RuntimeError as e:
            rows.append({"node_id": m, "state": f"unreachable ({e})"})
    for r in rows:
        print(f"{r.get('node_id', env.master):24s} state={r.get('state')} "
              f"term={r.get('term', '-')} leader={r.get('leader', '-')} "
              f"log={r.get('log_len', '-')} snap@{r.get('snap_index', '-')}",
              file=out)


@command("cluster.raft.add")
def cmd_cluster_raft_add(env: CommandEnv, args, out):
    """Add a master peer to every member's raft config:
    cluster.raft.add -peer host:port (reference: command_cluster_raft_add)."""
    env.require_lock()
    flags = parse_flags(args)
    peer = flags["peer"]
    st = env.master_get("/raft/status")
    members = set(st.get("peers", [])) | {st.get("node_id", env.master)}
    for m in sorted(members):
        r = env._call(f"{m}/raft/peers/add", {"peer": peer})
        print(f"{m}: peers now {r.get('peers')}", file=out)
    # the new member must also learn every existing peer, or it sees a
    # single-node cluster, elects itself, and split-brains
    for m in sorted(members):
        r = env._call(f"{peer}/raft/peers/add", {"peer": m})
    print(f"{peer}: peers now {r.get('peers')}", file=out)


@command("cluster.raft.remove")
def cmd_cluster_raft_remove(env: CommandEnv, args, out):
    """Remove a master peer from every member's raft config
    (reference: command_cluster_raft_remove)."""
    env.require_lock()
    flags = parse_flags(args)
    peer = flags["peer"]
    st = env.master_get("/raft/status")
    members = set(st.get("peers", [])) | {st.get("node_id", env.master)}
    for m in sorted(members - {peer}):
        r = env._call(f"{m}/raft/peers/remove", {"peer": peer})
        print(f"{m}: peers now {r.get('peers')}", file=out)


@command("cluster.leader")
def cmd_cluster_leader(env: CommandEnv, args, out):
    """Print the master leader address."""
    st = env.master_get("/cluster/status")
    print(st.get("Leader") or env.master, file=out)


@command("cluster.check")
def cmd_cluster_check(env: CommandEnv, args, out):
    """Reachability sweep over every registered cluster process
    (reference: command_cluster_check)."""
    st = env.master_get("/cluster/status")
    print(f"master {env.master:24s} ok (leader={st.get('Leader')})",
          file=out)
    topo = st.get("Topology", {})
    for nid in sorted(topo.get("nodes", {})):
        try:
            env.master_get_raw(nid, "/status")
            print(f"volume {nid:24s} ok", file=out)
        except RuntimeError as e:
            print(f"volume {nid:24s} UNREACHABLE: {e}", file=out)
    for kind, members in sorted(
            (st.get("Members") or {}).items()):
        for m in members:
            try:
                env.master_get_raw(m, "/status")
                print(f"{kind:6s} {m:24s} ok", file=out)
            except RuntimeError as e:
                print(f"{kind:6s} {m:24s} UNREACHABLE: {e}", file=out)


@command("fs.pwd")
def cmd_fs_pwd(env: CommandEnv, args, out):
    """Print the shell's filer working directory."""
    print(env.cwd, file=out)


@command("fs.cd")
def cmd_fs_cd(env: CommandEnv, args, out):
    """Change the shell's filer working directory: fs.cd /buckets"""
    target = env.resolve(args[0] if args else "/")
    filer = env.find_filer()
    if target != "/":
        env.filer_list(filer, target)  # raises if missing
    env.cwd = target
    print(env.cwd, file=out)


@command("fs.cp")
def cmd_fs_cp(env: CommandEnv, args, out):
    """Copy one filer file: fs.cp /src/path /dst/path."""
    if len(args) < 2:
        raise RuntimeError("fs.cp needs <src> <dst>")
    src, dst = env.resolve(args[0]), env.resolve(args[1])
    filer = env.find_filer()
    data = env.filer_read(filer, src)
    import urllib.request
    req = urllib.request.Request(
        f"{_tls_scheme()}://{filer}{urllib.parse.quote(dst)}",
        data=data, method="PUT")
    with urllib.request.urlopen(req, timeout=600):
        pass
    print(f"copied {src} -> {dst} ({len(data)} bytes)", file=out)


@command("fs.verify")
def cmd_fs_verify(env: CommandEnv, args, out):
    """Verify every chunk of a file (or tree) is readable on its volume
    server (reference: command_fs_verify)."""
    path = env.resolve(args[0] if args and not args[0].startswith("-")
                       else ".")
    filer = env.find_filer()
    import json as _json
    import urllib.request

    def chunks_of(p):
        with urllib.request.urlopen(
                f"{_tls_scheme()}://{filer}{urllib.parse.quote(p)}"
                "?metadata=true&resolveManifest=true", timeout=60) as r:
            meta = _json.loads(r.read())
        return meta.get("chunks") or []

    bad = ok = 0
    for ck in chunks_of(path):
        fid = ck.get("fid", "")
        vid = fid.split(",")[0]
        locs = env.volume_locations(int(vid)) if vid.isdigit() else []
        readable = False
        for url in locs:
            try:
                req = urllib.request.Request(
                    f"{_tls_scheme()}://{url}/{fid}", method="HEAD")
                with urllib.request.urlopen(req, timeout=30):
                    readable = True
                    break
            except Exception:
                continue
        if readable:
            ok += 1
        else:
            bad += 1
            print(f"  missing chunk {fid} ({path})", file=out)
    print(f"fs.verify: {ok} chunk(s) ok, {bad} missing", file=out)


@command("fs.configure")
def cmd_fs_configure(env: CommandEnv, args, out):
    """Show or set per-path filer rules (reference: command_fs_configure +
    filer.conf): fs.configure [-locationPrefix /p -collection c
    -replication 010 -ttl 1d -readOnly true -apply]"""
    flags = parse_flags(args)
    filer = env.find_filer()
    conf = env.master_get_raw(filer, "/__admin__/filer_conf")
    if not flags.get("locationPrefix"):
        print(json.dumps(conf, indent=2), file=out)
        return
    rule = {"location_prefix": flags["locationPrefix"]}
    for src, dst in (("collection", "collection"),
                     ("replication", "replication"), ("ttl", "ttl")):
        if flags.get(src):
            rule[dst] = flags[src]
    if flags.get("readOnly"):
        rule["read_only"] = flags["readOnly"] == "true"
    rules = [r for r in conf.get("locations", [])
             if r.get("location_prefix") != rule["location_prefix"]]
    if not flags.get("delete"):
        rules.append(rule)
    if flags.get("apply"):
        env._call(f"{filer}/__admin__/filer_conf", {"locations": rules})
        print(f"applied {len(rules)} rule(s)", file=out)
    else:
        print(json.dumps({"locations": rules}, indent=2), file=out)
        print("(dry run; add -apply)", file=out)


@command("volume.tier.upload")
def cmd_volume_tier_upload(env: CommandEnv, args, out):
    """Upload a volume's data to a remote tier — alias of volume.tier.move
    matching the reference's command name (command_volume_tier_upload)."""
    cmd_volume_tier_move(env, args, out)


@command("volume.tier.download")
def cmd_volume_tier_download(env: CommandEnv, args, out):
    """Bring a tiered volume's data back to local disk (reference:
    command_volume_tier_download): volume.tier.download -volumeId N
    [-deleteRemote true]"""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    for url in env.volume_locations(vid):
        r = env.vs_post(url, "/admin/volume/tier_download",
                        {"volume": vid,
                         "delete_remote":
                             flags.get("deleteRemote", "false") == "true"})
        print(f"volume {vid} on {url} back on local disk "
              f"(backend={r.get('backend')})", file=out)


@command("volume.deleteEmpty")
def cmd_volume_delete_empty(env: CommandEnv, args, out):
    """Delete volumes holding no live needles (reference:
    command_volume_delete_empty): volume.deleteEmpty [-apply]"""
    env.require_lock()
    flags = parse_flags(args)
    apply = flags.get("apply", "false") == "true" or "apply" in args
    topo = env.topology()
    n = 0
    for vid, rec in sorted(collect_volume_infos(topo).items()):
        if rec.get("file_count", 0) - rec.get("delete_count", 0) > 0:
            continue
        if rec.get("size", 0) <= 64 * 1024:  # header-only .dat
            n += 1
            print(f"empty volume {vid} on {rec['nodes']}"
                  + ("" if apply else " (dry run, -apply to delete)"),
                  file=out)
            if apply:
                for url in rec["nodes"]:
                    env.vs_post(url, "/admin/volume/delete", {"volume": vid})
    print(f"volume.deleteEmpty: {n} volume(s)"
          + ("" if apply else " planned"), file=out)


@command("volume.copy")
def cmd_volume_copy(env: CommandEnv, args, out):
    """Copy a volume to another server WITHOUT deleting the source
    (reference: command_volume_copy): volume.copy -volumeId N -target url"""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    target = flags["target"]
    locs = env.volume_locations(vid)
    if not locs:
        raise RuntimeError(f"volume {vid} not found")
    source = flags.get("source", locs[0])
    cols = {v: rec.get("collection", "")
            for v, rec in collect_volume_infos(env.topology()).items()}
    r = env.vs_post(target, "/admin/volume/copy",
                    {"volume": vid, "source": source,
                     "collection": cols.get(vid, "")})
    print(f"copied volume {vid}: {source} -> {target} "
          f"({r.get('file_count', r.get('appended_bytes', 0))})", file=out)


@command("volume.vacuum.disable")
def cmd_volume_vacuum_disable(env: CommandEnv, args, out):
    """Pause the master's automatic vacuum scan (reference:
    command_volume_vacuum_disable)."""
    env.require_lock()
    env.master_post("/vol/vacuum_toggle", {"enabled": False})
    print("automatic vacuum disabled", file=out)


@command("volume.vacuum.enable")
def cmd_volume_vacuum_enable(env: CommandEnv, args, out):
    """Resume the master's automatic vacuum scan (reference:
    command_volume_vacuum_enable)."""
    env.require_lock()
    env.master_post("/vol/vacuum_toggle", {"enabled": True})
    print("automatic vacuum enabled", file=out)


@command("remote.meta.sync")
def cmd_remote_meta_sync(env: CommandEnv, args, out):
    """Reconcile a mounted directory's metadata against the remote's
    current object list (reference: command_remote_meta_sync):
    remote.meta.sync -remote kind:spec -dir /mounted"""
    flags = parse_flags(args)
    from seaweedfs_tpu.remote_storage import (make_remote,
                                              meta_sync_remote_to_filer,
                                              parse_remote_spec)
    kind, options = parse_remote_spec(flags.get("remote", ""))
    remote = make_remote(kind, **options)
    filer = env.find_filer()
    changed, deleted, same = meta_sync_remote_to_filer(
        remote, filer, flags.get("dir", "/remote"))
    print(f"remote.meta.sync: {changed} updated, {deleted} deleted, "
          f"{same} unchanged", file=out)


@command("remote.uncache")
def cmd_remote_uncache(env: CommandEnv, args, out):
    """Drop cached content under a mounted directory, reverting entries to
    placeholders (reference: command_remote_uncache):
    remote.uncache -dir /mounted"""
    flags = parse_flags(args)
    mount = flags.get("dir", "/remote")
    filer = env.find_filer()
    from seaweedfs_tpu.remote_storage import _filer_walk
    import urllib.request
    n = 0
    for path, meta in _filer_walk(filer, mount):
        ext = {k.lower(): v
               for k, v in (meta.get("extended") or {}).items()}
        if "remote-key" not in ext or \
                ext.get("remote-placeholder") == "true":
            continue
        headers = {
            "Seaweed-remote-size": ext.get("remote-size", "0"),
            "Seaweed-remote-mtime": ext.get("remote-mtime", "0"),
            "Seaweed-remote-key": ext["remote-key"],
            "Seaweed-remote-placeholder": "true",
        }
        req = urllib.request.Request(
            f"{_tls_scheme()}://{filer}{urllib.parse.quote(path)}",
            data=b"", method="POST", headers=headers)
        with urllib.request.urlopen(req, timeout=60):
            pass
        n += 1
    print(f"remote.uncache: {n} file(s) reverted to placeholders", file=out)


@command("remote.configure")
def cmd_remote_configure(env: CommandEnv, args, out):
    """Store named remote specs on the filer (reference:
    command_remote_configure): remote.configure -name cold
    -spec s3:endpoint=..,bucket=.. | -list | -delete -name cold"""
    flags = parse_flags(args)
    filer = env.find_filer()
    path = "/etc/remote.conf"
    import urllib.error
    import urllib.request
    try:
        conf = json.loads(env.filer_read(filer, path) or b"{}")
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise  # a transient failure must NOT read as "no remotes"
        conf = {}
    mutated = False
    if flags.get("name") and flags.get("spec"):
        conf[flags["name"]] = flags["spec"]
        mutated = True
    elif flags.get("delete") and flags.get("name"):
        mutated = conf.pop(flags["name"], None) is not None
    if mutated:  # plain listing never rewrites the config file
        req = urllib.request.Request(
            f"{_tls_scheme()}://{filer}{urllib.parse.quote(path)}",
            data=json.dumps(conf, indent=2).encode(), method="PUT")
        with urllib.request.urlopen(req, timeout=60):
            pass
    for name, spec in sorted(conf.items()):
        print(f"{name:16s} {spec}", file=out)
    if not conf:
        print("(no remotes configured)", file=out)


def _find_broker(env: CommandEnv) -> str:
    members = env.master_get("/cluster/status").get("Members", {})
    brokers = members.get("broker", [])
    if not brokers:
        raise RuntimeError("no mq broker registered with the master")
    return brokers[0]


@command("mq.topic.list")
def cmd_mq_topic_list(env: CommandEnv, args, out):
    """List MQ topics with partition next-offsets (reference:
    command_mq_topic_list)."""
    broker = _find_broker(env)
    r = env.master_get_raw(broker, "/topics/list")
    for t_ in r.get("topics", []):
        print(f"{t_['name']:32s} partitions={t_['partition_count']} "
              f"next_offsets={t_['next_offsets']}", file=out)
    if not r.get("topics"):
        print("(no topics)", file=out)


@command("mq.topic.configure")
def cmd_mq_topic_configure(env: CommandEnv, args, out):
    """Create/configure an MQ topic (reference: command_mq_topic_configure):
    mq.topic.configure -topic ns.name -partitionCount 4"""
    flags = parse_flags(args)
    broker = _find_broker(env)
    r = env._call(f"{broker}/topics/configure",
                  {"topic": flags["topic"],
                   "partition_count": int(flags.get("partitionCount", "4"))})
    print(f"topic {r.get('topic')} partitions={r.get('partition_count')}",
          file=out)


@command("mq.topic.desc")
def cmd_mq_topic_desc(env: CommandEnv, args, out):
    """Describe one topic's partitions and broker assignment (reference:
    command_mq_topic_describe)."""
    flags = parse_flags(args)
    topic = flags["topic"]
    broker = _find_broker(env)
    r = env.master_get_raw(broker, "/topics/list")
    brokers = r.get("brokers", [broker])
    for t_ in r.get("topics", []):
        if t_["name"] != topic:
            continue
        for pi, nxt in enumerate(t_["next_offsets"]):
            owner = brokers[pi % len(brokers)]
            print(f"partition {pi}: owner={owner} next_offset={nxt}",
                  file=out)
        return
    raise RuntimeError(f"topic {topic!r} not found")


@command("ec.cleanup")
def cmd_ec_cleanup(env: CommandEnv, args, out):
    """Remove leftover EC shards for volumes that are back to normal
    replication (post-decode orphans): ec.cleanup [-apply]"""
    env.require_lock()
    flags = parse_flags(args)
    apply = flags.get("apply", "false") == "true" or "apply" in args
    topo = env.topology()
    normal_vids = {vid for node in topo["nodes"].values()
                   for vid in node["volumes"]}
    n = 0
    for nid, node in sorted(topo["nodes"].items()):
        for vid_s, shards in sorted(node.get("ec_shards", {}).items()):
            vid = int(vid_s)
            if vid not in normal_vids:
                continue
            n += 1
            print(f"orphan ec shards of volume {vid} on {nid}: {shards}"
                  + ("" if apply else " (dry run, -apply to delete)"),
                  file=out)
            if apply:
                env.vs_post(nid, "/admin/ec/delete_shards",
                            {"volume": vid, "shards": shards})
    print(f"ec.cleanup: {n} orphan group(s)"
          + ("" if apply else " planned"), file=out)


@command("ec.progress")
def cmd_ec_progress(env: CommandEnv, args, out):
    """Watch a running EC encode: ec.progress -volumeId N [-server url]
    [-cancel true]"""
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    if flags.get("cancel") == "true":
        # cancelling aborts another operator's encode: single-writer rule
        env.require_lock()
    urls = [flags["server"]] if flags.get("server") else \
        env.volume_locations(vid) or \
        [n for n in env.topology()["nodes"]]
    cancelled = 0
    for url in urls:
        try:
            if flags.get("cancel") == "true":
                env.vs_post(url, "/admin/ec/cancel", {"volume": vid})
                print(f"{url}: cancel requested", file=out)
                cancelled += 1
                continue
            r = env.master_get_raw(url, "/admin/ec/progress",
                                   volumeId=str(vid))
        except RuntimeError:
            continue
        pct = 100.0 * r.get("bytes_done", 0) / max(1, r.get("total", 1))
        print(f"{url}: {r.get('state')} {pct:.1f}% "
              f"({r.get('bytes_done', 0)}/{r.get('total', 0)} bytes)"
              + (f" error={r['error']}" if r.get("error") else ""),
              file=out)
        return
    if flags.get("cancel") != "true" or not cancelled:
        print(f"no encode job found for volume {vid}", file=out)


@command("volume.delete.empty")
def cmd_volume_delete_empty(env: CommandEnv, args, out):
    """Delete volumes holding zero live files, fleet-wide (reference:
    command_volume_delete_empty.go).  Dry-run unless -force; -quietFor
    (default 24h) keeps freshly-created volumes safe."""
    env.require_lock()
    flags = parse_flags(args)
    force = "force" in flags
    quiet = parse_duration(flags.get("quietFor", "24h"))
    import time as _time
    now = _time.time()
    topo = env.topology()
    victims: dict[int, list[str]] = {}
    for nid, node in topo["nodes"].items():
        for v in node.get("volume_infos", []):
            if v.get("file_count", 0) - v.get("delete_count", 0) > 0:
                continue
            if v.get("modified_at", 0) + quiet >= now:
                continue
            victims.setdefault(v["id"], []).append(nid)
    for vid in sorted(victims):
        if force:
            for nid in victims[vid]:
                env.vs_post(nid, "/admin/volume/delete", {"volume": vid})
            print(f"deleted empty volume {vid} from "
                  f"{len(victims[vid])} node(s)", file=out)
        else:
            print(f"would delete empty volume {vid} on "
                  f"{victims[vid]} (use -force)", file=out)
    if not victims:
        print("no empty volumes", file=out)


@command("volume.server.evacuate")
def cmd_volume_server_evacuate(env: CommandEnv, args, out):
    """Move every volume and EC shard off -node onto the least-loaded
    other servers (reference: command_volume_server_evacuate.go) — drain
    before maintenance/decommission."""
    env.require_lock()
    flags = parse_flags(args)
    node = flags["node"]
    topo = env.topology()
    if node not in topo["nodes"]:
        raise RuntimeError(f"unknown volume server {node}")
    others = {nid: nd for nid, nd in topo["nodes"].items() if nid != node}
    if not others:
        raise RuntimeError("no other servers to evacuate onto")
    load = {nid: len(nd.get("volumes", [])) for nid, nd in others.items()}
    free = {nid: nd.get("free_slots", 0) for nid, nd in others.items()}
    moved = 0
    for v in topo["nodes"][node].get("volume_infos", []):
        vid = v["id"]
        # a target must have a slot and must not already hold a replica
        candidates = sorted(
            (nid for nid in others
             if free.get(nid, 0) > 0
             and vid not in others[nid].get("volumes", [])),
            key=lambda nid: load[nid])
        if not candidates:
            print(f"  volume {vid}: no target with free slots", file=out)
            continue
        target = candidates[0]
        move_volume(env, vid, node, target,
                    v.get("collection", ""))
        load[target] += 1
        free[target] -= 1
        moved += 1
        print(f"  volume {vid} -> {target}", file=out)
    # EC shards: copy to the least-loaded target, mount there, drop here
    ec = topo["nodes"][node].get("ec_shards", {})
    ec_cols = topo.get("ec_collections", {})
    for vid_s, shards in sorted(ec.items()):
        vid = int(vid_s)
        if not shards:
            continue
        col = ec_cols.get(vid_s, "")
        target = min(sorted(others), key=lambda nid: load[nid])
        env.vs_post(target, "/admin/ec/copy",
                    {"volume": vid, "collection": col, "source": node,
                     "shards": shards, "copy_ecx": True})
        env.vs_post(target, "/admin/ec/mount",
                    {"volume": vid, "collection": col})
        env.vs_post(node, "/admin/ec/delete_shards",
                    {"volume": vid, "shards": shards})
        # ALL shards left the node: unmount clears the empty EcVolume (a
        # re-mount would 404 on the missing files and abort the drain)
        env.vs_post(node, "/admin/ec/unmount", {"volume": vid})
        load[target] += 1
        moved += 1
        print(f"  ec shards {shards} of {vid} -> {target}", file=out)
    print(f"evacuated {moved} volume(s)/shard set(s) off {node}", file=out)


@command("volume.server.leave")
def cmd_volume_server_leave(env: CommandEnv, args, out):
    """Ask a volume server to stop heartbeating so the master drops it
    from placement (reference: command_volume_server_leave.go); pair with
    volume.server.evacuate for a clean decommission."""
    env.require_lock()
    flags = parse_flags(args)
    node = flags["node"]
    env.vs_post(node, "/admin/leave", {})
    print(f"{node} is leaving the cluster (heartbeats stopped)", file=out)


@command("remote.unmount")
def cmd_remote_unmount(env: CommandEnv, args, out):
    """Detach a remote mapping from a directory (reference:
    command_remote_unmount.go).  Cached/placeholder entries under the
    directory stay unless -deleteEntries."""
    flags = parse_flags(args)
    mount_dir = flags.get("dir", "/remote")
    filer = env.find_filer()
    env._call(f"{filer}/__admin__/remote_mounts",
              {"remove": [mount_dir]})
    if flags.get("deleteEntries", "false") == "true":
        try:
            env.filer_delete(filer, mount_dir, recursive=True)
        except Exception as e:
            print(f"  entry cleanup failed: {e}", file=out)
    print(f"remote.unmount: {mount_dir} detached", file=out)


@command("s3.bucket.quota")
def cmd_s3_bucket_quota(env: CommandEnv, args, out):
    """Set/clear a bucket's byte quota, stored on the bucket entry
    (reference: command_s3_bucket_quota.go).  -name b -quotaMB 100 |
    -name b -delete; s3.bucket.quota.check enforces."""
    flags = parse_flags(args)
    name = flags.get("name", "")
    if not name:
        raise RuntimeError("-name required")
    filer = env.find_filer()
    entry = env.master_get_raw(filer, f"/buckets/{name}",
                               metadata="true")
    if "delete" in flags:
        entry["quota"] = 0
    elif "quotaMB" not in flags:
        raise RuntimeError("-quotaMB <megabytes> or -delete required")
    else:
        entry["quota"] = int(float(flags["quotaMB"]) * 1024 * 1024)
    env._call(f"{filer}/__admin__/entry", {"entry": entry})
    q = entry["quota"]
    print(f"bucket {name}: quota "
          + (f"{q} bytes" if q else "removed"), file=out)


@command("s3.bucket.quota.check")
def cmd_s3_bucket_quota_check(env: CommandEnv, args, out):
    """Walk each bucket's usage and enforce its quota by toggling a
    read-only filer rule on the bucket prefix (reference:
    command_s3_bucket_quota_check.go; the reference emails/flips
    read-only the same way).  Dry-run unless -apply."""
    flags = parse_flags(args)
    apply = "apply" in flags
    filer = env.find_filer()

    def usage(d: str) -> int:
        total = 0
        for e in env.filer_list(filer, d):
            if e.get("IsDirectory"):
                total += usage(e["FullPath"])
            else:
                total += e.get("FileSize", 0)
        return total

    conf = env.master_get_raw(filer, "/__admin__/filer_conf")
    rules = conf.get("locations", [])
    changed = 0
    for b in env.filer_list(filer, "/buckets"):
        if not b.get("IsDirectory"):
            continue
        name = b["FullPath"].rsplit("/", 1)[-1]
        entry = env.master_get_raw(filer, f"/buckets/{name}",
                                   metadata="true")
        quota = int(entry.get("quota", 0) or 0)
        if quota <= 0:
            continue
        used = usage(f"/buckets/{name}")
        prefix = f"/buckets/{name}/"
        rule = next((r for r in rules
                     if r.get("location_prefix") == prefix), None)
        over = used > quota
        state = "OVER" if over else "ok"
        print(f"bucket {name}: {used}/{quota} bytes [{state}]", file=out)
        # merge into any existing rule at this prefix — a lifecycle TTL
        # (or other settings) at /buckets/<b>/ must survive the toggle
        if over and not (rule and rule.get("read_only")):
            if apply:
                merged = dict(rule or {"location_prefix": prefix,
                                       "collection": name})
                merged["read_only"] = True
                env._call(f"{filer}/__admin__/filer_conf", merged)
                changed += 1
            else:
                print(f"  would mark {prefix} read-only (-apply)",
                      file=out)
        elif not over and rule and rule.get("read_only"):
            if apply:
                keeps_other = any(rule.get(k) for k in
                                  ("ttl", "replication", "fsync",
                                   "disk_type"))
                if keeps_other:
                    env._call(f"{filer}/__admin__/filer_conf",
                              dict(rule, read_only=False))
                else:
                    env._call(f"{filer}/__admin__/filer_conf",
                              {"delete_prefix": prefix})
                changed += 1
            else:
                print(f"  would clear read-only on {prefix} (-apply)",
                      file=out)
    if apply:
        print(f"{changed} rule change(s) applied", file=out)


@command("mq.balance")
def cmd_mq_balance(env: CommandEnv, args, out):
    """Show the deterministic partition->broker assignment for every topic
    (reference: command_mq_balance.go triggers the balancer; this ring
    balances continuously, so the command reports the settled layout)."""
    brokers = env.master_get_raw(env.master, "/cluster/status") \
        .get("Members", {}).get("broker", [])
    if not brokers:
        print("no brokers registered", file=out)
        return
    listing = env.master_get_raw(sorted(brokers)[0], "/topics/list")
    # the queried broker's ring can momentarily be [] during a master
    # heartbeat lapse; fall back to the registry view
    ring = listing.get("brokers") or sorted(brokers)
    print(f"broker ring: {ring}", file=out)
    for t in listing.get("topics", []):
        n = t["partition_count"]
        print(f"{t['name']}: {n} partition(s)", file=out)
        for pi in range(n):
            follower = ring[(pi + 1) % len(ring)] if len(ring) > 1 else "-"
            print(f"  p{pi}: owner {ring[pi % len(ring)]} "
                  f"follower {follower} "
                  f"next_offset {t['next_offsets'][pi]}", file=out)


@command("fs.meta.notify")
def cmd_fs_meta_notify(env: CommandEnv, args, out):
    """Recursively re-send a directory's metadata to the filer's
    notification queue (reference: command_fs_meta_notify.go) — primes a
    replication consumer with the existing tree."""
    path = env.resolve(
        (args and not args[-1].startswith("-") and args[-1]) or ".")
    filer = env.find_filer()
    r = env._call(f"{filer}/__admin__/notify", {"prefix": path})
    print(f"notified {r.get('sent', 0)} entr(ies) under {path}", file=out)


@command("fs.meta.change.volume.id")
def cmd_fs_meta_change_volume_id(env: CommandEnv, args, out):
    """Rewrite chunk fids from one volume id to another across a subtree
    (reference: command_fs_meta_change_volume_id.go) — the metadata half
    of renumbering a volume.  -dir / -fromVolumeId X -toVolumeId Y
    [-mapping file-with-x=>y-lines] [-force to apply]."""
    flags = parse_flags(args)
    mapping: dict[int, int] = {}
    if flags.get("mapping"):
        with open(flags["mapping"]) as f:
            for line in f:
                line = line.strip()
                if not line or "=>" not in line:
                    continue
                a, b = line.split("=>", 1)
                mapping[int(a)] = int(b)
    else:
        src, dst = int(flags.get("fromVolumeId", "0")), \
            int(flags.get("toVolumeId", "0"))
        if not src or not dst or src == dst:
            raise RuntimeError("-fromVolumeId and -toVolumeId must be "
                               "distinct and non-zero (or use -mapping)")
        mapping[src] = dst
    force = "force" in flags
    root = flags.get("dir", "/")
    filer = env.find_filer()
    changed = 0

    def walk(d: str) -> None:
        nonlocal changed
        for e in env.filer_list(filer, d):
            if e.get("IsDirectory"):
                walk(e["FullPath"])
                continue
            entry = env.master_get_raw(
                filer, urllib.parse.quote(e["FullPath"]), metadata="true")
            dirty = False
            for c in entry.get("chunks", []):
                if c.get("is_chunk_manifest"):
                    print(f"  skip manifest file {e['FullPath']} "
                          "(not implemented)", file=out)
                    break
                vid_s, _, rest = c.get("fid", "").partition(",")
                try:
                    vid = int(vid_s)
                except ValueError:
                    continue
                if vid in mapping:
                    c["fid"] = f"{mapping[vid]},{rest}"
                    dirty = True
            else:
                if dirty:
                    changed += 1
                    print(f"  {'updating' if force else 'would update'} "
                          f"{e['FullPath']}", file=out)
                    if force:
                        env._call(f"{filer}/__admin__/entry",
                                  {"entry": entry})

    walk(root.rstrip("/") or "/")
    print(f"{changed} file(s) {'updated' if force else 'need updating'}"
          + ("" if force else " (dry run; add -force)"), file=out)


@command("fs.merge.volumes")
def cmd_fs_merge_volumes(env: CommandEnv, args, out):
    """Re-upload the chunks of files under -dir that live on
    -fromVolumeId into freshly assigned volumes, consolidating data off
    small/fragmented volumes so they can be deleted (reference:
    command_fs_merge_volumes.go).  Dry-run by default; -apply commits.
    -dir /path -fromVolumeId N [-collection c] [-apply]"""
    flags = parse_flags(args)
    root = env.resolve(flags.get("dir", "/"))
    src_vid = int(flags.get("fromVolumeId", "0"))
    if not src_vid:
        raise RuntimeError("-fromVolumeId is required")
    apply = "apply" in flags
    filer = env.find_filer()
    from seaweedfs_tpu.client import WeedClient
    client = WeedClient(env.master) if apply else None
    files = chunks = 0
    try:
        def walk(d: str) -> None:
            nonlocal files, chunks
            for e in env.filer_list(filer, d):
                if e.get("IsDirectory"):
                    walk(e["FullPath"])
                    continue
                entry = env.master_get_raw(
                    filer, urllib.parse.quote(e["FullPath"]),
                    metadata="true")
                dirty = False
                for c in entry.get("chunks", []):
                    fid = c.get("fid", "")
                    vid_s = fid.split(",")[0]
                    if not vid_s.isdigit() or int(vid_s) != src_vid:
                        continue
                    chunks += 1
                    if not apply:
                        dirty = True
                        continue
                    data = None
                    for u in env.volume_locations(src_vid):
                        try:
                            with urllib.request.urlopen(
                                    f"{_tls_scheme()}://{u}/{fid}",
                                    timeout=120) as r:
                                data = r.read()
                            break
                        except Exception:
                            continue
                    if data is None:
                        raise RuntimeError(f"chunk {fid} unreadable on "
                                           f"volume {src_vid}")
                    # the point is moving OFF the source volume: retry
                    # assign past it, growing fresh volumes if the source
                    # is the only writable one (a grown volume becomes
                    # assignable only after it registers — wait that
                    # window out instead of burning the retries)
                    import time as _time
                    a = None
                    for attempt in range(20):
                        cand = client.assign(
                            collection=flags.get("collection", ""))
                        if int(cand["fid"].split(",")[0]) != src_vid:
                            a = cand
                            break
                        if attempt == 3:
                            env.master_post(
                                "/vol/grow", count="1",
                                collection=flags.get("collection", ""))
                        if attempt >= 3:
                            _time.sleep(0.2)
                    if a is None:
                        raise RuntimeError(
                            f"could not assign a target volume != "
                            f"{src_vid}")
                    client.upload_to(a["url"], a["fid"], data)
                    c["fid"] = a["fid"]
                    dirty = True
                if dirty:
                    files += 1
                    print(f"  {'moved' if apply else 'would move'} "
                          f"{e['FullPath']}", file=out)
                    if apply:
                        env._call(f"{filer}/__admin__/entry",
                                  {"entry": entry})

        walk(root.rstrip("/") or "/")
    finally:
        if client is not None:
            client.close()
    print(f"fs.merge.volumes: {chunks} chunk(s) in {files} file(s) "
          f"{'moved off' if apply else 'on'} volume {src_vid}"
          + ("" if apply else " (dry run; add -apply)"), file=out)


@command("remote.mount.buckets")
def cmd_remote_mount_buckets(env: CommandEnv, args, out):
    """Mount every bucket of an S3-class remote under -dir (reference:
    command_remote_mount_buckets.go): one subdirectory per bucket, each
    with placeholder entries + a recorded read-through mapping.
    -remote s3:endpoint=..,access_key=..,secret_key=.. [-dir /buckets]
    [-bucketPattern glob]"""
    import fnmatch
    flags = parse_flags(args)
    from seaweedfs_tpu.remote_storage import (make_remote,
                                              parse_remote_spec,
                                              sync_remote_to_filer)
    kind, options = parse_remote_spec(flags.get("remote", ""))
    options.pop("bucket", None)
    base_dir = flags.get("dir", "/buckets").rstrip("/")
    pattern = flags.get("bucketPattern", "")
    probe = make_remote(kind, bucket="", **options)
    if not hasattr(probe, "list_buckets"):
        raise RuntimeError(f"remote kind {kind!r} cannot list buckets")
    filer = env.find_filer()
    mounted = 0
    for bucket in probe.list_buckets():
        if pattern and not fnmatch.fnmatch(bucket, pattern):
            continue
        remote = make_remote(kind, bucket=bucket, **options)
        mount_dir = f"{base_dir}/{bucket}"
        n = sync_remote_to_filer(remote, filer, mount_dir, cache=False)
        spec = f"{kind}:bucket={bucket}," + ",".join(
            f"{k}={v}" for k, v in options.items())
        env._call(f"{filer}/__admin__/remote_mounts",
                  {"set": {mount_dir: spec}})
        print(f"  {bucket}: {n} object(s) -> {mount_dir}", file=out)
        mounted += 1
    print(f"remote.mount.buckets: {mounted} bucket(s) mounted", file=out)


@command("mount.configure")
def cmd_mount_configure(env: CommandEnv, args, out):
    """Configure a RUNNING weedtpu mount through its admin unix socket
    (reference: command_mount_configure.go over the mount's local socket).
    -dir /mountpoint [-quotaMB N]  (0 clears the quota; no -quotaMB just
    prints the mount's current state)"""
    import socket as _socket
    flags = parse_flags(args)
    mountpoint = flags.get("dir")
    if not mountpoint:
        raise RuntimeError("-dir (the mountpoint) is required")
    from seaweedfs_tpu.mount.weedfs import admin_socket_path
    payload: dict = {}
    if "quotaMB" in flags:
        payload["quota"] = int(float(flags["quotaMB"]) * 1024 * 1024)
    sock = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    try:
        sock.settimeout(10)
        sock.connect(admin_socket_path(mountpoint))
        sock.sendall(json.dumps(payload).encode())
        sock.shutdown(_socket.SHUT_WR)
        resp = json.loads(sock.recv(65536))
    except (OSError, ValueError) as e:
        raise RuntimeError(
            f"no responding mount at {mountpoint} ({e})") from None
    finally:
        sock.close()
    if not resp.get("ok"):
        raise RuntimeError(f"mount.configure: {resp.get('error')}")
    quota = resp.get("quota", 0)
    print(f"mount at {mountpoint}: root={resp.get('root')} quota="
          + (f"{quota / (1024 * 1024):.0f}MB" if quota else "unlimited"),
          file=out)


@command("s3.circuitbreaker")
def cmd_s3_circuitbreaker(env: CommandEnv, args, out):
    """Show or set the S3 gateway circuit-breaker limits, stored in the
    filer at /etc/s3/circuit_breaker.json and hot-reloaded by every
    gateway (reference: command_s3_circuitbreaker.go).
    [-global.requests N] [-global.uploadBytes N] [-bucket.requests N]
    [-apply]   (without -apply: print the stored config)"""
    flags = parse_flags(args)
    from seaweedfs_tpu.s3.s3api_server import CIRCUIT_BREAKER_PATH
    filer = env.find_filer()
    if "apply" not in flags:
        try:
            raw = env.filer_read(filer, CIRCUIT_BREAKER_PATH)
            print(raw.decode(), file=out)
        except Exception:
            print("no circuit breaker configured", file=out)
        return
    cfg = {
        "global_max_requests": int(flags.get("global.requests", "0")),
        "global_max_upload_bytes": int(flags.get("global.uploadBytes", "0")),
        "bucket_max_requests": int(flags.get("bucket.requests", "0")),
    }
    req = urllib.request.Request(
        f"{_tls_scheme()}://{filer}"
        + urllib.parse.quote(CIRCUIT_BREAKER_PATH),
        data=json.dumps(cfg).encode(), method="PUT")
    with urllib.request.urlopen(req, timeout=60):
        pass
    print(f"s3.circuitbreaker applied: {json.dumps(cfg)}", file=out)
