"""Admin shell command environment + commands.

The shell drives the cluster purely over the master/volume-server HTTP
APIs, holding the master's exclusive admin lock while mutating — same
operating model as the reference shell (weed/shell/commands.go:23-60,
command_ec_encode.go, command_ec_rebuild.go, command_ec_decode.go,
command_ec_balance.go), synchronous code for operator predictability.
"""

from __future__ import annotations

import json
import shlex
import urllib.parse
import urllib.request

from seaweedfs_tpu.storage.ec import layout


class CommandEnv:
    def __init__(self, master: str):
        self.master = master
        self.lock_token: str | None = None

    # -- http helpers --------------------------------------------------

    def _call(self, url: str, body: dict | None = None,
              method: str | None = None, timeout: float = 600.0) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"http://{url}", data=data,
            method=method or ("POST" if body is not None else "GET"),
            headers={"Content-Type": "application/json"} if body is not None else {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read()).get("error", str(e))
            except Exception:
                err = str(e)
            raise RuntimeError(f"{url}: {err}") from None

    def master_get(self, path: str, **params) -> dict:
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        return self._call(f"{self.master}{path}{qs}")

    def master_post(self, path: str, body: dict | None = None, **params) -> dict:
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        return self._call(f"{self.master}{path}{qs}", body or {})

    def vs_post(self, url: str, path: str, body: dict) -> dict:
        return self._call(f"{url}{path}", body)

    # -- lock -----------------------------------------------------------

    def acquire_lock(self, owner: str = "shell") -> None:
        if self.lock_token:
            return
        self.lock_token = self.master_post("/admin/lock", {"owner": owner})["token"]

    def release_lock(self) -> None:
        if self.lock_token:
            self.master_post("/admin/unlock", {"token": self.lock_token})
            self.lock_token = None

    def require_lock(self) -> None:
        if not self.lock_token:
            raise RuntimeError("this command requires `lock` first")

    # -- topology helpers -----------------------------------------------

    def topology(self) -> dict:
        return self.master_get("/cluster/status")["Topology"]

    def volume_locations(self, vid: int) -> list[str]:
        try:
            r = self.master_get("/dir/lookup", volumeId=str(vid))
        except RuntimeError:
            return []
        return [l["url"] for l in r.get("locations", [])]

    def ec_shard_locations(self, vid: int) -> dict[int, list[str]]:
        try:
            r = self.master_get("/dir/ec/lookup", volumeId=str(vid))
        except RuntimeError:
            return {}
        return {int(s): [l["url"] for l in locs]
                for s, locs in r.get("shards", {}).items()}


# ---- commands ---------------------------------------------------------

COMMANDS: dict[str, callable] = {}


def command(name):
    def deco(fn):
        COMMANDS[name] = fn
        return fn
    return deco


def parse_flags(args: list[str]) -> dict[str, str]:
    out = {}
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("-"):
            key = a.lstrip("-")
            if "=" in key:
                k, _, v = key.partition("=")
                out[k] = v
            elif i + 1 < len(args) and not args[i + 1].startswith("-"):
                out[key] = args[i + 1]
                i += 1
            else:
                out[key] = "true"
        i += 1
    return out


@command("lock")
def cmd_lock(env: CommandEnv, args, out):
    env.acquire_lock()
    print("locked", file=out)


@command("unlock")
def cmd_unlock(env: CommandEnv, args, out):
    env.release_lock()
    print("unlocked", file=out)


@command("cluster.status")
def cmd_cluster_status(env: CommandEnv, args, out):
    print(json.dumps(env.master_get("/cluster/status"), indent=2), file=out)


@command("volume.list")
def cmd_volume_list(env: CommandEnv, args, out):
    topo = env.topology()
    for nid, node in sorted(topo["nodes"].items()):
        print(f"node {nid} dc={node['dc']} rack={node['rack']} "
              f"free={node['free_slots']}", file=out)
        for vid in node["volumes"]:
            print(f"  volume {vid}", file=out)
        for vid, shards in sorted(node["ec_shards"].items()):
            print(f"  ec volume {vid} shards {shards}", file=out)


@command("volume.vacuum")
def cmd_volume_vacuum(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    for url in env.volume_locations(vid):
        r = env.vs_post(url, "/admin/volume/vacuum", {"volume": vid})
        print(f"vacuumed {vid} on {url} (garbage was "
              f"{r.get('garbage_ratio', 0):.2%})", file=out)


@command("volume.delete")
def cmd_volume_delete(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    for url in env.volume_locations(vid):
        env.vs_post(url, "/admin/volume/delete", {"volume": vid})
        print(f"deleted {vid} on {url}", file=out)


@command("volume.mark")
def cmd_volume_mark(env: CommandEnv, args, out):
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    readonly = flags.get("writable", "false") != "true"
    for url in env.volume_locations(vid):
        env.vs_post(url, "/admin/volume/readonly",
                    {"volume": vid, "readonly": readonly})
        print(f"marked {vid} readonly={readonly} on {url}", file=out)


def balanced_ec_distribution(nodes: list[str]) -> dict[str, list[int]]:
    """Round-robin the 14 shards over nodes (reference:
    command_ec_encode.go:272 balancedEcDistribution)."""
    alloc: dict[str, list[int]] = {n: [] for n in nodes}
    order = sorted(nodes)
    for sid in range(layout.TOTAL_SHARDS):
        target = order[sid % len(order)]
        alloc[target].append(sid)
    return alloc


@command("ec.encode")
def cmd_ec_encode(env: CommandEnv, args, out):
    """Convert a volume to EC shards and spread them
    (reference: command_ec_encode.go:58-321)."""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    collection = flags.get("collection", "")

    locations = env.volume_locations(vid)
    if not locations:
        raise RuntimeError(f"volume {vid} not found")
    source = locations[0]

    # 1. freeze writes on every replica
    for url in locations:
        env.vs_post(url, "/admin/volume/readonly", {"volume": vid, "readonly": True})
    # 2. generate shards on the source (TPU codec)
    env.vs_post(source, "/admin/ec/generate",
                {"volume": vid, "collection": collection})
    print(f"generated 14 shards of volume {vid} on {source}", file=out)

    # 3. spread shards over the cluster
    topo = env.topology()
    nodes = sorted(topo["nodes"])
    alloc = balanced_ec_distribution(nodes)
    for target, shards in alloc.items():
        if not shards:
            continue
        if target != source:
            env.vs_post(target, "/admin/ec/copy",
                        {"volume": vid, "collection": collection,
                         "source": source, "shards": shards, "copy_ecx": True})
        env.vs_post(target, "/admin/ec/mount",
                    {"volume": vid, "collection": collection})
        print(f"  shards {shards} -> {target}", file=out)
    # 4. delete moved shard files from source, and the original volume
    moved = [s for tgt, ss in alloc.items() if tgt != source for s in ss]
    if moved:
        env.vs_post(source, "/admin/ec/delete_shards",
                    {"volume": vid, "shards": moved})
        env.vs_post(source, "/admin/ec/mount",
                    {"volume": vid, "collection": collection})
    for url in locations:
        env.vs_post(url, "/admin/volume/delete", {"volume": vid})
    print(f"ec.encode {vid} done", file=out)


@command("ec.rebuild")
def cmd_ec_rebuild(env: CommandEnv, args, out):
    """Rebuild missing shards (reference: command_ec_rebuild.go:58-281)."""
    env.require_lock()
    topo = env.topology()
    ec_vids = {int(v) for node in topo["nodes"].values()
               for v in node["ec_shards"]}
    for vid in sorted(ec_vids):
        shard_locs = env.ec_shard_locations(vid)
        present = set(shard_locs)
        missing = [s for s in range(layout.TOTAL_SHARDS) if s not in present]
        if not missing:
            continue
        if len(present) < layout.DATA_SHARDS:
            print(f"volume {vid}: only {len(present)} shards left, "
                  f"cannot rebuild", file=out)
            continue
        # rebuilder = node holding the most shards
        counts: dict[str, int] = {}
        for locs in shard_locs.values():
            for url in locs:
                counts[url] = counts.get(url, 0) + 1
        rebuilder = max(counts, key=counts.get)
        local = {s for s, locs in shard_locs.items() if rebuilder in locs}
        # pull missing survivors to the rebuilder
        borrowed = []
        for s, locs in shard_locs.items():
            if s in local:
                continue
            env.vs_post(rebuilder, "/admin/ec/copy",
                        {"volume": vid, "source": locs[0], "shards": [s],
                         "copy_ecx": False})
            borrowed.append(s)
        r = env.vs_post(rebuilder, "/admin/ec/rebuild", {"volume": vid})
        env.vs_post(rebuilder, "/admin/ec/delete_shards",
                    {"volume": vid, "shards": borrowed})
        env.vs_post(rebuilder, "/admin/ec/mount", {"volume": vid})
        print(f"volume {vid}: rebuilt {r.get('rebuilt')} on {rebuilder}",
              file=out)


@command("ec.decode")
def cmd_ec_decode(env: CommandEnv, args, out):
    """EC shards -> normal volume (reference: command_ec_decode.go:40-292)."""
    env.require_lock()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    collection = flags.get("collection", "")
    shard_locs = env.ec_shard_locations(vid)
    if not shard_locs:
        raise RuntimeError(f"no ec shards for volume {vid}")
    counts: dict[str, int] = {}
    for locs in shard_locs.values():
        for url in locs:
            counts[url] = counts.get(url, 0) + 1
    collector = max(counts, key=counts.get)
    local = {s for s, locs in shard_locs.items() if collector in locs}
    for s, locs in shard_locs.items():
        if s not in local and locs:
            env.vs_post(collector, "/admin/ec/copy",
                        {"volume": vid, "collection": collection,
                         "source": locs[0], "shards": [s], "copy_ecx": False})
    env.vs_post(collector, "/admin/ec/to_volume",
                {"volume": vid, "collection": collection})
    # drop shards everywhere
    all_nodes = {url for locs in shard_locs.values() for url in locs} | {collector}
    for url in all_nodes:
        env.vs_post(url, "/admin/ec/unmount", {"volume": vid})
        env.vs_post(url, "/admin/ec/delete_shards",
                    {"volume": vid, "shards": list(range(layout.TOTAL_SHARDS))})
    print(f"ec.decode {vid} -> normal volume on {collector}", file=out)


@command("ec.balance")
def cmd_ec_balance(env: CommandEnv, args, out):
    """Even shard spread (reference: command_ec_balance.go, simplified to
    per-volume round-robin re-placement)."""
    env.require_lock()
    topo = env.topology()
    nodes = sorted(topo["nodes"])
    ec_vids = {int(v) for node in topo["nodes"].values()
               for v in node["ec_shards"]}
    for vid in sorted(ec_vids):
        shard_locs = env.ec_shard_locations(vid)
        want = balanced_ec_distribution(nodes)
        want_by_shard = {s: tgt for tgt, ss in want.items() for s in ss}
        for s, locs in shard_locs.items():
            tgt = want_by_shard.get(s)
            if tgt is None or tgt in locs:
                continue
            src = locs[0]
            env.vs_post(tgt, "/admin/ec/copy",
                        {"volume": vid, "source": src, "shards": [s],
                         "copy_ecx": True})
            env.vs_post(tgt, "/admin/ec/mount", {"volume": vid})
            env.vs_post(src, "/admin/ec/delete_shards",
                        {"volume": vid, "shards": [s]})
            env.vs_post(src, "/admin/ec/mount", {"volume": vid})
            print(f"volume {vid} shard {s}: {src} -> {tgt}", file=out)
    print("ec.balance done", file=out)


def run_command(env: CommandEnv, line: str, out) -> None:
    parts = shlex.split(line)
    if not parts:
        return
    fn = COMMANDS.get(parts[0])
    if fn is None:
        raise RuntimeError(f"unknown command {parts[0]!r} "
                           f"(have: {', '.join(sorted(COMMANDS))})")
    fn(env, parts[1:], out)
