"""Interactive admin shell (reference: weed/shell/shell.go REPL)."""

from __future__ import annotations

import sys

from seaweedfs_tpu.shell.commands import CommandEnv, run_command


def _setup_completion() -> None:
    """Tab-completes command names (reference: the shell's
    prompt autocompletion, weed/shell/shell.go + weed autocomplete)."""
    try:
        import readline
    except ImportError:  # no libreadline: plain input() still works
        return
    from seaweedfs_tpu.shell.commands import COMMANDS

    def complete(text: str, state: int):
        matches = [c for c in sorted(COMMANDS) if c.startswith(text)]
        return matches[state] if state < len(matches) else None

    readline.set_completer(complete)
    readline.set_completer_delims(" \t")
    readline.parse_and_bind("tab: complete")


def repl(master: str, script: str | None = None) -> int:
    env = CommandEnv(master)
    rc = 0
    try:
        if script is not None:
            for line in script.split(";"):
                line = line.strip()
                if line:
                    # a command's nonzero rc (volume.fsck on a corrupt
                    # cluster) must surface as the process exit code so
                    # CI/chaos harnesses can gate on `weedtpu shell -c`
                    rc = max(rc, run_command(env, line, sys.stdout))
            return rc
        _setup_completion()
        while True:
            try:
                line = input("> ").strip()
            except EOFError:
                break
            if line in ("exit", "quit"):
                break
            if not line:
                continue
            try:
                run_command(env, line, sys.stdout)
            except RuntimeError as e:
                print(f"error: {e}", file=sys.stderr)
    finally:
        try:
            env.release_lock()
        except RuntimeError:
            pass
    return rc
