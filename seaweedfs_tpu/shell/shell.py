"""Interactive admin shell (reference: weed/shell/shell.go REPL)."""

from __future__ import annotations

import sys

from seaweedfs_tpu.shell.commands import CommandEnv, run_command


def repl(master: str, script: str | None = None) -> int:
    env = CommandEnv(master)
    rc = 0
    try:
        if script is not None:
            for line in script.split(";"):
                line = line.strip()
                if line:
                    run_command(env, line, sys.stdout)
            return 0
        while True:
            try:
                line = input("> ").strip()
            except EOFError:
                break
            if line in ("exit", "quit"):
                break
            if not line:
                continue
            try:
                run_command(env, line, sys.stdout)
            except RuntimeError as e:
                print(f"error: {e}", file=sys.stderr)
    finally:
        try:
            env.release_lock()
        except RuntimeError:
            pass
    return rc
