"""FTP gateway stub (reference: weed/ftpd/ftp_server.go — an 81-line stub
in the reference too: option struct + a Run that errors pending a real
implementation).  Kept as the registration seam so an FTP library can slot
in without touching callers."""

from __future__ import annotations


class FtpServerOption:
    def __init__(self, filer_url: str, host: str = "127.0.0.1",
                 port: int = 8021, passive_port_start: int = 30000,
                 passive_port_stop: int = 30100):
        self.filer_url = filer_url
        self.host, self.port = host, port
        self.passive_port_start = passive_port_start
        self.passive_port_stop = passive_port_stop


class FtpServer:
    def __init__(self, option: FtpServerOption):
        self.option = option

    async def start(self) -> None:
        raise NotImplementedError(
            "the FTP gateway is a stub (as in the reference's weed/ftpd); "
            "use the S3, WebDAV, or mount gateways")
