"""Message queue: partitioned pub/sub broker
(reference: weed/mq/ broker + topic packages)."""
