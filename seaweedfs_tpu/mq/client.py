"""MQ client library: publisher with partition-ring routing + consumer
groups.

Reference: weed/mq/client/pub_client (publishes straight to each
partition's assigned broker, refreshing assignments from the balancer) and
weed/mq/client/sub_client (joins a consumer group, gets partitions from
the coordinator, streams each and commits progress).  Same roles over the
broker HTTP surface, synchronous (usable from tests, shell, and plain
scripts):

    client = MQClient(["127.0.0.1:17777"])
    client.configure("chat.room1", partition_count=4)
    client.publish("chat.room1", b"hello", key=b"alice")

    consumer = client.consumer("chat.room1", group="readers")
    for msg in consumer.poll(max_messages=100):
        ...
    consumer.commit()
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request

from seaweedfs_tpu.mq.topic import Topic, ring_slot, split_ring
from seaweedfs_tpu.security.tls import scheme as _tls_scheme


class MQError(RuntimeError):
    pass


class MQClient:
    """Seed-broker client: keeps a live ring view (the same sorted broker
    list every broker derives), routes each publish to the partition's
    owner, and falls back through the ring on failures."""

    def __init__(self, brokers: list[str], timeout: float = 30.0):
        if not brokers:
            raise ValueError("need at least one seed broker")
        self.seeds = list(brokers)
        self.timeout = timeout
        self.ring: list[str] = sorted(brokers)
        self._topic_parts: dict[str, int] = {}

    # -- http ----------------------------------------------------------

    def _req(self, broker: str, path: str, data: bytes | None = None,
             method: str | None = None) -> tuple[int, bytes, dict]:
        req = urllib.request.Request(
            f"{_tls_scheme()}://{broker}{path}", data=data,
            method=method or ("POST" if data is not None else "GET"))
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            # a 4xx/5xx is an ANSWER (fenced, repartition conflict, ...),
            # not a dead broker — hand the status to the caller
            return e.code, e.read(), dict(e.headers)

    def _any_broker(self, path: str, data: bytes | None = None):
        """Try the ring then the seeds; first broker that answers wins."""
        last: Exception | None = None
        for broker in list(self.ring) + [s for s in self.seeds
                                         if s not in self.ring]:
            try:
                return broker, self._req(broker, path, data)
            except (urllib.error.URLError, OSError) as e:
                last = e
        raise MQError(f"no broker reachable: {last}")

    def refresh(self) -> None:
        """Update the ring + topic partition counts from any live broker."""
        _, (st, body, _) = self._any_broker("/topics/list")
        if st != 200:
            return
        listing = json.loads(body)
        if listing.get("brokers"):
            self.ring = sorted(listing["brokers"])
        for t in listing.get("topics", []):
            self._topic_parts[t["name"]] = t["partition_count"]

    # -- admin ----------------------------------------------------------

    def configure(self, topic: str, partition_count: int = 4) -> None:
        body = json.dumps({"topic": topic,
                           "partition_count": partition_count}).encode()
        _, (st, resp, _) = self._any_broker("/topics/configure", body)
        if st != 200:
            raise MQError(f"configure failed: {resp!r}")
        self._topic_parts[str(Topic.parse(topic))] = partition_count

    # -- publish ---------------------------------------------------------

    def _partition_of(self, topic: str, key: bytes) -> int:
        t = str(Topic.parse(topic))
        n = self._topic_parts.get(t)
        if n is None:
            self.refresh()
            n = self._topic_parts.get(t, 4)
        slot = ring_slot(key)
        for i, p in enumerate(split_ring(n)):
            if p.range_start <= slot < p.range_stop:
                return i
        return slot % n

    def publish(self, topic: str, value: bytes,
                key: bytes = b"") -> tuple[int, int]:
        """-> (partition, offset).  Routed to the owner directly (the
        reference's pub_client does the same; any broker forwards anyway)."""
        import base64
        pi = self._partition_of(topic, key)
        owner = self.ring[pi % len(self.ring)] if self.ring else self.seeds[0]
        path = "/pub?" + urllib.parse.urlencode(
            {"topic": topic,
             "key_b64": base64.b64encode(key).decode()})
        order = [owner] + [b for b in self.ring if b != owner]
        last: Exception | str = "no brokers"
        for attempt, broker in enumerate(order):
            try:
                st, body, _ = self._req(broker, path, value)
            except (urllib.error.URLError, OSError) as e:
                last = e
                continue
            if st == 200:
                out = json.loads(body)
                return out["partition"], out["offset"]
            last = body.decode("utf-8", "replace")
            if st == 503:  # fenced / owner moved: refresh and retry
                self.refresh()
        raise MQError(f"publish failed: {last}")

    # -- subscribe -------------------------------------------------------

    def fetch(self, topic: str, partition: int, offset: int,
              limit: int = 1024, wait: float = 0.0) -> tuple[list[dict], int]:
        """One batch from one partition -> (messages, next_offset)."""
        path = "/sub?" + urllib.parse.urlencode(
            {"topic": topic, "partition": str(partition),
             "offset": str(offset), "limit": str(limit),
             "wait": str(wait)})
        _, (st, body, headers) = self._any_broker(path)
        if st != 200:
            raise MQError(f"fetch failed: {body!r}")
        msgs = [json.loads(line) for line in body.splitlines() if line]
        nxt = int(headers.get("X-Next-Offset", offset))
        return msgs, nxt

    def consumer(self, topic: str, group: str,
                 member: str | None = None) -> "GroupConsumer":
        return GroupConsumer(self, topic, group,
                             member or f"member-{time.time_ns()}")


class GroupConsumer:
    """Consumer-group member: join assigns partitions (round-robin over
    live members at the group's coordinator broker), poll() walks them
    from the committed offsets, commit() persists progress."""

    def __init__(self, client: MQClient, topic: str, group: str,
                 member: str):
        self.client = client
        self.topic = topic
        self.group = group
        self.member = member
        self.partitions: list[int] = []
        self.positions: dict[int, int] = {}  # partition -> next offset

    def join(self) -> list[int]:
        body = json.dumps({"group": self.group, "topic": self.topic,
                           "member": self.member}).encode()
        _, (st, resp, _) = self.client._any_broker("/coordinator/join", body)
        if st != 200:
            raise MQError(f"join failed: {resp!r}")
        self.partitions = json.loads(resp)["partitions"]
        for pi in self.partitions:
            if pi not in self.positions:
                self.positions[pi] = self._committed(pi)
        return self.partitions

    def _committed(self, pi: int) -> int:
        path = "/offsets/get?" + urllib.parse.urlencode(
            {"group": self.group, "topic": self.topic, "partition": str(pi)})
        _, (st, body, _) = self.client._any_broker(path)
        return int(json.loads(body).get("offset", 0)) if st == 200 else 0

    def poll(self, max_messages: int = 1024,
             wait: float = 0.0) -> list[dict]:
        """Next batch across this member's partitions, advancing local
        positions (commit() makes them durable)."""
        if not self.partitions:
            self.join()
        out: list[dict] = []
        for pi in self.partitions:
            if len(out) >= max_messages:
                break
            msgs, nxt = self.client.fetch(
                self.topic, pi, self.positions.get(pi, 0),
                limit=max_messages - len(out), wait=wait)
            for m in msgs:
                m["partition"] = pi
            out.extend(msgs)
            self.positions[pi] = nxt
        return out

    def commit(self) -> None:
        for pi, offset in self.positions.items():
            body = json.dumps({"group": self.group, "topic": self.topic,
                               "partition": pi, "offset": offset}).encode()
            _, (st, resp, _) = self.client._any_broker("/offsets/commit",
                                                       body)
            if st != 200:
                raise MQError(f"commit failed: {resp!r}")
