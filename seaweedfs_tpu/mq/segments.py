"""Durable MQ storage: partition log segments + group offsets in the filer.

Reference: the broker persists topic data into the filer under /topics
(weed/mq/broker/broker_topic_conf_read_write.go writes topic.conf there,
weed/filer/filer_notify_append.go appends the log segments) and the segment
byte format lives in weed/mq/segment/message_serde.go (flatbuffers).  Here a
segment is a self-contained binary file of consecutive messages:

    "WMQ1" then repeated [offset u64][ts_ns u64][klen u32][vlen u32][key][value]
    (big-endian), named <base>-<end>.seg (end exclusive) under
    /topics/<namespace>/<topic>/<partition>/

plus a per-topic topic.json ({"partition_count": N}) and group offsets as
tiny JSON files under /topics/.offsets/<group>/<topic>.<partition> — so a
full-cluster broker restart recovers topics, data, and consumer progress
from the filer alone.
"""

from __future__ import annotations

import asyncio
import json
import struct

import aiohttp

from seaweedfs_tpu.mq.topic import Message, Topic
from seaweedfs_tpu.security.tls import scheme as _tls_scheme

SEG_MAGIC = b"WMQ1"
SEG_HEADER = struct.Struct(">QQII")  # offset, ts_ns, klen, vlen


def encode_segment(msgs: list[Message]) -> bytes:
    out = [SEG_MAGIC]
    for m in msgs:
        out.append(SEG_HEADER.pack(m.offset, m.ts_ns, len(m.key),
                                   len(m.value)))
        out.append(m.key)
        out.append(m.value)
    return b"".join(out)


def decode_segment(data: bytes) -> list[Message]:
    if data[:4] != SEG_MAGIC:
        raise ValueError("bad segment magic")
    msgs: list[Message] = []
    pos = 4
    n = len(data)
    while pos < n:
        if pos + SEG_HEADER.size > n:
            break  # segment cut inside a record header: same torn-tail drop
        off, ts, klen, vlen = SEG_HEADER.unpack_from(data, pos)
        pos += SEG_HEADER.size
        key = data[pos:pos + klen]
        pos += klen
        value = data[pos:pos + vlen]
        pos += vlen
        if len(key) != klen or len(value) != vlen:
            # segment cut mid-record (torn write): a silently shortened
            # message must not replay — drop the partial trailing record
            break
        msgs.append(Message(off, ts, key, value))
    return msgs


def seg_name(base: int, end: int) -> str:
    return f"{base:020d}-{end:020d}.seg"


def parse_seg_name(name: str) -> tuple[int, int] | None:
    if not name.endswith(".seg"):
        return None
    try:
        base, end = name[:-4].split("-")
        return int(base), int(end)
    except ValueError:
        return None


class FilerSegmentStore:
    """Async filer-backed storage for the broker (one per BrokerServer)."""

    def __init__(self, session: aiohttp.ClientSession, filer_url: str,
                 root: str = "/topics"):
        self.session = session
        self.filer_url = filer_url
        self.root = root.rstrip("/")

    def _u(self, path: str) -> str:
        return f"{_tls_scheme()}://{self.filer_url}{path}"

    def topic_dir(self, topic: str) -> str:
        t = Topic.parse(topic)
        return f"{self.root}/{t.namespace}/{t.name}"

    # -- topic conf ----------------------------------------------------

    async def write_conf(self, topic: str, partition_count: int) -> None:
        await self._put(f"{self.topic_dir(topic)}/topic.json",
                        json.dumps({"partition_count":
                                    partition_count}).encode())

    async def read_conf(self, topic: str) -> int | None:
        data = await self._get(f"{self.topic_dir(topic)}/topic.json")
        if data is None:
            return None
        try:
            return int(json.loads(data)["partition_count"])
        except (ValueError, KeyError):
            return None

    async def list_topics(self) -> list[str]:
        """Walk /topics/<ns>/<topic> two levels deep."""
        out: list[str] = []
        for ns in await self._list(self.root):
            if ns.startswith("."):
                continue
            for name in await self._list(f"{self.root}/{ns}"):
                if await self._get(
                        f"{self.root}/{ns}/{name}/topic.json") is not None:
                    out.append(f"{ns}.{name}")
        return out

    # -- segments ------------------------------------------------------

    async def write_segment(self, topic: str, pi: int,
                            msgs: list[Message]) -> None:
        if not msgs:
            return
        base, end = msgs[0].offset, msgs[-1].offset + 1
        path = f"{self.topic_dir(topic)}/{pi}/{seg_name(base, end)}"
        await self._put(path, encode_segment(msgs))

    async def list_segments(self, topic: str,
                            pi: int) -> list[tuple[int, int, str]]:
        """-> sorted [(base, end, name)]."""
        out = []
        for name in await self._list(f"{self.topic_dir(topic)}/{pi}"):
            parsed = parse_seg_name(name)
            if parsed:
                out.append((parsed[0], parsed[1], name))
        out.sort()
        return out

    async def read_segment(self, topic: str, pi: int,
                           name: str) -> list[Message]:
        data = await self._get(f"{self.topic_dir(topic)}/{pi}/{name}")
        if data is None:
            return []
        try:
            return decode_segment(data)
        except (ValueError, struct.error):
            # truncated/corrupt segment (e.g. broker killed mid-PUT) must
            # not wedge recovery or reads — skip it
            return []

    async def flushed_upto(self, topic: str, pi: int) -> int:
        segs = await self.list_segments(topic, pi)
        return segs[-1][1] if segs else 0

    # -- group offsets -------------------------------------------------

    def _offset_path(self, group: str, topic: str, pi: int) -> str:
        return f"{self.root}/.offsets/{group}/{topic}.{pi}"

    async def write_offset(self, group: str, topic: str, pi: int,
                           offset: int) -> None:
        await self._put(self._offset_path(group, topic, pi),
                        str(offset).encode())

    async def read_offset(self, group: str, topic: str,
                          pi: int) -> int | None:
        data = await self._get(self._offset_path(group, topic, pi))
        if data is None:
            return None
        try:
            return int(data)
        except ValueError:
            return None

    # -- filer http ----------------------------------------------------

    async def _put(self, path: str, data: bytes) -> None:
        async with self.session.put(
                self._u(path), data=data,
                timeout=aiohttp.ClientTimeout(total=30)) as r:
            if r.status >= 400:
                raise OSError(f"filer put {path}: {r.status}")

    async def _get(self, path: str) -> bytes | None:
        try:
            async with self.session.get(
                    self._u(path),
                    timeout=aiohttp.ClientTimeout(total=30)) as r:
                if r.status == 404:
                    return None
                if r.status >= 400:
                    raise OSError(f"filer get {path}: {r.status}")
                return await r.read()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return None

    async def _list(self, dir_path: str) -> list[str]:
        try:
            async with self.session.get(
                    self._u(dir_path.rstrip("/") + "/"),
                    params={"limit": "100000"},
                    timeout=aiohttp.ClientTimeout(total=30)) as r:
                if r.status != 200:
                    return []
                listing = await r.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return []
        return [e["FullPath"].rsplit("/", 1)[-1]
                for e in listing.get("Entries") or []]
