"""Topic + partition model.

Reference: weed/mq/topic/{topic.go,partition.go,local_partition.go}.  A
topic's key space is a ring of 4096 slots; each partition owns a
contiguous [range_start, range_stop) slice of the ring, and a message is
routed by hashing its key onto the ring — the same scheme the reference
uses so partition counts can change without rehashing everything.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field

PARTITION_COUNT_RING = 4096  # reference: mq/topic/partition.go PartitionCount


@dataclass(frozen=True)
class Topic:
    namespace: str
    name: str

    def __str__(self) -> str:
        return f"{self.namespace}.{self.name}"

    @classmethod
    def parse(cls, s: str) -> "Topic":
        ns, _, name = s.rpartition(".")
        return cls(ns or "default", name)


@dataclass(frozen=True)
class Partition:
    range_start: int
    range_stop: int
    ring_size: int = PARTITION_COUNT_RING

    def holds_key(self, key: bytes) -> bool:
        return self.range_start <= ring_slot(key) < self.range_stop


def ring_slot(key: bytes, ring_size: int = PARTITION_COUNT_RING) -> int:
    return zlib.crc32(key) % ring_size


def split_ring(partition_count: int,
               ring_size: int = PARTITION_COUNT_RING) -> list[Partition]:
    """Divide the ring into `partition_count` contiguous ranges
    (reference: pub_balancer/allocate.go allocateTopicPartitions)."""
    assert partition_count > 0
    step = ring_size // partition_count
    parts = []
    for i in range(partition_count):
        start = i * step
        stop = ring_size if i == partition_count - 1 else (i + 1) * step
        parts.append(Partition(start, stop, ring_size))
    return parts


@dataclass
class Message:
    offset: int
    ts_ns: int
    key: bytes
    value: bytes

    def to_dict(self) -> dict:
        return {"offset": self.offset, "ts_ns": self.ts_ns,
                "key": self.key.decode("utf-8", "replace"),
                "value": self.value.decode("utf-8", "replace")}


class LocalPartition:
    """In-memory append log for one partition with blocking follow reads
    (reference: mq/topic/local_partition.go + log_buffer)."""

    def __init__(self, partition: Partition, max_messages: int = 1 << 20):
        self.partition = partition
        self.max_messages = max_messages
        self.messages: list[Message] = []
        self.base_offset = 0  # offset of messages[0] after trimming
        self._lock = threading.Condition()

    def publish(self, key: bytes, value: bytes) -> int:
        with self._lock:
            offset = self.base_offset + len(self.messages)
            self.messages.append(Message(offset, time.time_ns(), key, value))
            if len(self.messages) > self.max_messages:
                drop = len(self.messages) - self.max_messages
                self.messages = self.messages[drop:]
                self.base_offset += drop
            self._lock.notify_all()
            return offset

    def read(self, offset: int, limit: int = 1024,
             wait: float = 0.0) -> list[Message]:
        """Messages from `offset` (clamped to retained range); blocks up to
        `wait` seconds when nothing new."""
        deadline = time.monotonic() + wait
        with self._lock:
            while True:
                start = max(offset, self.base_offset) - self.base_offset
                batch = self.messages[start:start + limit]
                if batch or wait <= 0:
                    return batch
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._lock.wait(remaining)

    @property
    def next_offset(self) -> int:
        with self._lock:
            return self.base_offset + len(self.messages)

    # -- replication (reference: partition followers, mq/broker) ---------

    def append_replica(self, offset: int, ts_ns: int, key: bytes,
                       value: bytes) -> bool:
        """Follower-side append at an explicit offset. Returns False on a
        gap (the leader then pushes a full snapshot); stale offsets are
        acknowledged as already-held."""
        with self._lock:
            nxt = self.base_offset + len(self.messages)
            if offset < nxt:
                return True
            if offset > nxt:
                return False
            self.messages.append(Message(offset, ts_ns, key, value))
            if len(self.messages) > self.max_messages:
                drop = len(self.messages) - self.max_messages
                self.messages = self.messages[drop:]
                self.base_offset += drop
            self._lock.notify_all()
            return True

    def snapshot(self) -> tuple[int, list[Message]]:
        with self._lock:
            return self.base_offset, list(self.messages)

    def load_snapshot(self, base_offset: int,
                      messages: list[Message]) -> None:
        """Replace local state when the incoming log extends further."""
        with self._lock:
            if base_offset + len(messages) <=                     self.base_offset + len(self.messages):
                return
            self.base_offset = base_offset
            self.messages = list(messages)
            self._lock.notify_all()
