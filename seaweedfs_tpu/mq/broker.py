"""MQ broker: HTTP pub/sub with partition balancing, follower replication,
broker failover, and subscriber-group coordination.

Reference: weed/mq/broker/{broker_grpc_pub.go:37 Publish,
broker_grpc_sub.go:13 Subscribe, broker_grpc_configure.go} plus the
coordination plane in weed/mq/pub_balancer/ (partition->broker assignment)
and weed/mq/sub_coordinator/ (consumer-group partition assignment +
progress). The reference streams over gRPC with an elected balancer
broker; here the same roles ride HTTP with a DETERMINISTIC balance rule —
partition i of a topic is owned by sorted(live_brokers)[i % n], its
follower is the next broker in that ring — so every broker (and client)
computes identical assignments from the shared live-broker view instead of
holding leader state:

  POST /topics/configure   {"topic": "ns.name", "partition_count": N}
  GET  /topics/list
  POST /pub?topic=ns.name  body=value, ?key= routes by ring slot;
                           forwarded to the owning broker, synchronously
                           replicated to the follower
  GET  /sub?topic=ns.name&partition=i&offset=K[&wait=seconds]
                           -> NDJSON batch (long-polls when caught up)
  POST /replicate          follower append (leader pushes a snapshot on gap)
  GET/POST /partition/state  full-partition snapshot pull / push
  POST /coordinator/join   {"group","topic","member"} -> partitions for
                           this member (round-robin over live members)
  POST /offsets/commit     {"group","topic","partition","offset"}
  GET  /offsets/get?group=&topic=&partition=
  GET  /status

Brokers register in the master's cluster registry (type=broker); each
broker's peer view = master's member list filtered by a direct liveness
probe, refreshed continuously. Killing a broker re-routes its partitions
to survivors, which already hold the data via follower replication —
publishes keep succeeding and subscribers lose nothing. Group offsets are
broadcast to every live broker on commit so they also survive failover.

Durability (with `filer_url`): partition logs flush into the filer as
binary segments under /topics/<ns>/<topic>/<partition>/ (mq/segments.py;
reference persists topic data into the filer the same way), topic confs as
topic.json, and committed group offsets write through to
/topics/.offsets/ — kill and restart EVERY broker and topics, messages,
and consumer progress all recover.  Reads below the RAM window fall back
to the segment files.

Fencing: partition ownership carries an epoch issued by the master
(/cluster/mq/epoch, monotonic per partition).  Replicas reject appends
with an older epoch, so two brokers with divergent ring views fail loudly
instead of silently interleaving/merging logs.  The unflushed RAM tail is
still lost if owner AND follower die inside one flush interval — the same
window the reference's in-memory log buffer has.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import time

import aiohttp
from aiohttp import web

from seaweedfs_tpu.mq.topic import (LocalPartition, Message, Topic,
                                    ring_slot, split_ring)
from seaweedfs_tpu.security.tls import scheme as _tls_scheme
from seaweedfs_tpu.security import tls as _tls

log = logging.getLogger("mq.broker")


class BrokerServer:
    def __init__(self, master_url: str, host: str = "127.0.0.1",
                 port: int = 17777, peer_refresh: float = 2.0,
                 member_ttl: float = 15.0, filer_url: str | None = None,
                 flush_interval: float = 2.0):
        self.master_url = master_url
        self.host, self.port = host, port
        self.peer_refresh = peer_refresh
        self.member_ttl = member_ttl
        self.filer_url = filer_url
        self.flush_interval = flush_interval
        # str(topic) -> list[LocalPartition]
        self.topics: dict[str, list[LocalPartition]] = {}
        self.peer_brokers: list[str] = [self.url]  # sorted, self included
        # (group, topic) -> {member: last_seen}
        self.group_members: dict[tuple[str, str], dict[str, float]] = {}
        # (group, topic, partition) -> committed offset
        self.group_offsets: dict[tuple[str, str, int], int] = {}
        # fencing (advisor finding: divergent ring views must not silently
        # merge): (topic, pi) -> epoch I publish under / highest seen
        self.own_epoch: dict[tuple[str, int], int] = {}
        self.seen_epoch: dict[tuple[str, int], int] = {}
        # (topic, pi) -> next offset already durable in filer segments
        self.flushed_upto: dict[tuple[str, int], int] = {}
        self._conf_persisted: set[str] = set()
        self._seg_cache: dict[tuple, list] = {}  # LRU of decoded segments
        self.store = None  # FilerSegmentStore when filer_url is set
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.add_routes([
            web.post("/topics/configure", self.handle_configure),
            web.get("/topics/list", self.handle_list),
            web.post("/pub", self.handle_pub),
            web.get("/sub", self.handle_sub),
            web.post("/replicate", self.handle_replicate),
            web.get("/partition/state", self.handle_partition_state_get),
            web.post("/partition/state", self.handle_partition_state_put),
            web.post("/coordinator/join", self.handle_coordinator_join),
            web.post("/offsets/commit", self.handle_offsets_commit),
            web.post("/offsets/sync", self.handle_offsets_sync),
            web.get("/offsets/get", self.handle_offsets_get),
            web.post("/flush", self.handle_flush),
            web.get("/status", self.handle_status),
        ])
        self._runner: web.AppRunner | None = None
        self._session: aiohttp.ClientSession | None = None
        self._register_task: asyncio.Task | None = None
        self._flush_task: asyncio.Task | None = None

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(ssl=_tls.client_ssl()),
            timeout=aiohttp.ClientTimeout(total=30))
        if self.filer_url:
            from seaweedfs_tpu.mq.segments import FilerSegmentStore
            self.store = FilerSegmentStore(self._session, self.filer_url)
            await self._recover()
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port,
                           ssl_context=_tls.server_ssl("broker"))
        await site.start()
        self._register_task = asyncio.create_task(self._register_loop())
        if self.store is not None:
            self._flush_task = asyncio.create_task(self._flush_loop())
        log.info("mq broker on %s", self.url)

    async def stop(self) -> None:
        if self._register_task:
            self._register_task.cancel()
        if self._flush_task:
            self._flush_task.cancel()
        if self.store is not None:
            try:
                await self._flush_all()  # graceful stop drains the tail
            except Exception:
                log.exception("final flush failed")
        if self._session:
            await self._session.close()
        if self._runner:
            await self._runner.cleanup()

    # -- durability (reference: topic data persisted into the filer under
    #    /topics; segment serde weed/mq/segment/message_serde.go) ---------

    async def _recover(self) -> None:
        """Rebuild topics + partition tails + flush cursors from the filer:
        a full-cluster restart loses nothing that was flushed."""
        for topic in await self.store.list_topics():
            n = await self.store.read_conf(topic)
            if not n:
                continue
            parts = self._get_topic(topic, auto_create=True, n=n)
            for pi, part in enumerate(parts):
                segs = await self.store.list_segments(topic, pi)
                if not segs:
                    continue
                # load the tail segments into the RAM window, newest
                # first; dedup by offset with the newest segment winning
                # (overlapping segments can exist after a ring-change
                # flush race) and corrupt files skipped
                by_off: dict[int, object] = {}
                for base, end, name in reversed(segs):
                    for m in await self.store.read_segment(topic, pi,
                                                           name):
                        by_off.setdefault(m.offset, m)
                    if len(by_off) >= part.max_messages:
                        break
                msgs = [by_off[o] for o in sorted(by_off)]
                msgs = msgs[-part.max_messages:]
                if msgs:
                    part.load_snapshot(msgs[0].offset, msgs)
                # cursor from the last GOOD message, not the segment file
                # names: a corrupt tail segment must not suppress
                # re-flushing (and thus silently lose) its offset range
                self.flushed_upto[(topic, pi)] = \
                    (msgs[-1].offset + 1) if msgs else 0
        if self.topics:
            log.info("recovered %d topics from filer", len(self.topics))

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.flush_interval)
            try:
                await self._flush_all()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("segment flush failed")

    async def _flush_all(self) -> None:
        """Write every owned partition's unflushed tail as one new segment.
        Only the owner flushes; after a failover the new owner derives its
        cursor from the filer listing.  During a ring-change window two
        brokers may briefly both believe they own a partition and write
        overlapping segments — readers (_recover, _read_segments) dedup by
        offset, newest segment first, so the race degrades to redundant
        bytes, not replayed duplicates."""
        if self.store is None:
            return
        for topic, parts in list(self.topics.items()):
            for pi, part in enumerate(parts):
                if self._owner_of(pi) != self.url:
                    continue
                key = (topic, pi)
                if key not in self.flushed_upto:
                    self.flushed_upto[key] = \
                        await self.store.flushed_upto(topic, pi)
                upto = self.flushed_upto[key]
                if part.next_offset <= upto:
                    continue
                # off-loop: read takes the partition lock and copies up to
                # the whole RAM window (read clamps to >= upto already)
                tail = await asyncio.to_thread(part.read, upto,
                                               1 << 20)
                if not tail:
                    continue
                if topic not in self._conf_persisted:
                    # auto-created topics (first pub) persist their conf
                    # with their first segment so recovery finds them
                    await self.store.write_conf(topic, len(parts))
                    self._conf_persisted.add(topic)
                await self.store.write_segment(topic, pi, tail)
                self.flushed_upto[key] = tail[-1].offset + 1

    async def handle_flush(self, req: web.Request) -> web.Response:
        """Force-drain the unflushed tails (deterministic tests; ops)."""
        if self.store is None:
            return web.json_response({"error": "no filer configured"},
                                     status=400)
        await self._flush_all()
        return web.json_response({"ok": True})

    # -- fencing epochs --------------------------------------------------

    async def _ensure_epoch(self, topic: str, pi: int) -> int:
        """Owner-side: fetch a fresh fencing epoch from the master the
        first time this broker publishes to a partition (and again after
        being fenced).  Monotonic per partition across the cluster."""
        key = (topic, pi)
        epoch = self.own_epoch.get(key)
        if epoch is not None:
            return epoch
        try:
            async with self._session.post(
                    f"{_tls_scheme()}://{self.master_url}/cluster/mq/epoch",
                    json={"key": f"{topic}/{pi}"},
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                epoch = int((await r.json())["epoch"])
        except (aiohttp.ClientError, asyncio.TimeoutError,
                ValueError, KeyError):
            # master unreachable: publish under the highest epoch this
            # broker has itself replicated for (passes the follower's
            # >= check in the common case) and do NOT cache, so the next
            # publish retries the master — a master outage must degrade
            # fencing, not turn into a publish outage
            return self.seen_epoch.get(key, 0)
        self.own_epoch[key] = epoch
        return epoch

    # -- membership / balance --------------------------------------------

    async def _register_loop(self) -> None:
        while True:
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{self.master_url}/cluster/register",
                        json={"type": "broker", "address": self.url},
                        timeout=aiohttp.ClientTimeout(total=10)):
                    pass
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # the loop must outlive any transient failure
            try:
                await self._refresh_peers()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("peer refresh failed")
            await asyncio.sleep(self.peer_refresh)

    async def _refresh_peers(self) -> None:
        """Live-broker view = master registry ∩ direct probe. The balance
        rule is pure arithmetic over this sorted list, so agreement on the
        list IS agreement on every partition assignment."""
        candidates = {self.url}
        try:
            async with self._session.get(
                    f"{_tls_scheme()}://{self.master_url}/cluster/status",
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                members = (await r.json()).get("Members", {})
                candidates.update(members.get("broker", []))
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            # ONE slow/failed registry fetch must not collapse the ring
            # to {self}: that splits the brain — this broker briefly
            # owns every partition, accepts appends under its solo
            # ring, and the divergence reconciles by DROPPING whichever
            # side's log is shorter.  Keep probing the known ring
            # instead; genuinely dead peers still drop via the direct
            # probe below, and the registry re-adds newcomers next
            # cycle.
            candidates.update(self.peer_brokers)

        async def probe(addr: str) -> str | None:
            if addr == self.url:
                return addr
            try:
                async with self._session.get(
                        f"{_tls_scheme()}://{addr}/status",
                        timeout=aiohttp.ClientTimeout(total=2)) as r:
                    return addr if r.status == 200 else None
            except (aiohttp.ClientError, asyncio.TimeoutError):
                return None

        alive = sorted(a for a in await asyncio.gather(
            *(probe(a) for a in sorted(candidates))) if a)
        if alive != self.peer_brokers:
            log.info("broker ring: %s -> %s", self.peer_brokers, alive)
            self.peer_brokers = alive
            # ownership may have moved: publish under fresh fencing epochs
            # so a peer still on the old ring cannot silently interleave
            self.own_epoch.clear()
        # anti-entropy every cycle (and the takeover path after a ring
        # change): a broker that accepted publishes under a stale ring view
        # holds data its settled owner lacks; comparing next_offsets and
        # pulling the longer log converges every such divergence
        await self._reconcile()

    async def _reconcile(self) -> None:
        for peer in self.peer_brokers:
            if peer == self.url:
                continue
            try:
                async with self._session.get(
                        f"{_tls_scheme()}://{peer}/topics/list",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    listing = await r.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                continue
            for t in listing.get("topics", []):
                name = t["name"]
                parts = self._get_topic(name, auto_create=True,
                                        n=t["partition_count"])
                if len(parts) != t["partition_count"]:
                    continue  # partition-count conflict; leave it alone
                for pi, peer_next in enumerate(t["next_offsets"]):
                    mine = self._owner_of(pi) == self.url or \
                        self._follower_of(pi) == self.url
                    if mine and peer_next > parts[pi].next_offset:
                        await self._pull_state(peer, name, pi, parts[pi])

    async def _catch_up(self, topic: str, pi: int,
                        part: LocalPartition) -> None:
        """Pull this partition's state from every live peer before the
        first append under fresh ownership; load_snapshot keeps only a
        log longer than ours, so this is an idempotent fast-forward to
        the fleet's high-water mark."""
        for peer in self.peer_brokers:
            if peer == self.url:
                continue
            await self._pull_state(peer, topic, pi, part)

    async def _pull_state(self, peer: str, topic: str, pi: int,
                          part: LocalPartition) -> None:
        try:
            async with self._session.get(
                    f"{_tls_scheme()}://{peer}/partition/state",
                    params={"topic": topic, "partition": str(pi)},
                    timeout=aiohttp.ClientTimeout(total=30)) as r:
                if r.status != 200:
                    return
                st = await r.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return
        part.load_snapshot(st["base_offset"],
                           _decode_messages(st["messages"]))

    def _owner_of(self, pi: int) -> str:
        b = self.peer_brokers
        return b[pi % len(b)] if b else self.url

    def _follower_of(self, pi: int) -> str | None:
        b = self.peer_brokers
        if len(b) < 2:
            return None
        return b[(pi + 1) % len(b)]

    # -- topic admin -----------------------------------------------------

    def _get_topic(self, name: str,
                   auto_create: bool = False,
                   n: int = 4) -> list[LocalPartition] | None:
        key = str(Topic.parse(name))
        parts = self.topics.get(key)
        if parts is None and auto_create:
            parts = [LocalPartition(p) for p in split_ring(n)]
            self.topics[key] = parts
        return parts

    async def handle_configure(self, req: web.Request) -> web.Response:
        body = await req.json()
        topic = str(Topic.parse(body["topic"]))
        n = int(body.get("partition_count", 4))
        if n <= 0 or n > 4096:
            return web.json_response({"error": "bad partition_count"},
                                     status=400)
        existing = self.topics.get(topic)
        if existing is not None and len(existing) != n:
            return web.json_response(
                {"error": "cannot repartition a live topic"}, status=409)
        if existing is None:
            self.topics[topic] = [LocalPartition(p) for p in split_ring(n)]
        if self.store is not None:
            try:
                await self.store.write_conf(topic, n)
            except OSError:
                log.exception("topic conf persist failed")
        if not req.query.get("propagated"):
            # every broker holds every partition object (leader for some,
            # follower for others) so configuration fans out
            for peer in self.peer_brokers:
                if peer == self.url:
                    continue
                try:
                    async with self._session.post(
                            f"{_tls_scheme()}://{peer}/topics/configure"
                            "?propagated=1", json=body,
                            timeout=aiohttp.ClientTimeout(total=5)):
                        pass
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    pass
        return web.json_response({"topic": topic, "partition_count": n})

    async def handle_list(self, req: web.Request) -> web.Response:
        return web.json_response({
            "topics": [
                {"name": name, "partition_count": len(parts),
                 "next_offsets": [p.next_offset for p in parts]}
                for name, parts in sorted(self.topics.items())],
            "brokers": self.peer_brokers,
        })

    # -- publish ---------------------------------------------------------

    async def handle_pub(self, req: web.Request) -> web.Response:
        topic = req.query.get("topic", "")
        if not topic:
            return web.json_response({"error": "topic required"}, status=400)
        parts = self._get_topic(topic, auto_create=True)
        if "key_b64" in req.query:  # arbitrary-bytes keys (mq/client.py)
            try:
                key = base64.b64decode(req.query["key_b64"])
            except ValueError:
                return web.json_response({"error": "bad key_b64"},
                                         status=400)
        else:
            key = req.query.get("key", "").encode()
        value = await req.read()
        slot = ring_slot(key)
        part = next((p for p in parts if p.partition.holds_key(key)),
                    parts[slot % len(parts)])
        pi = parts.index(part)

        owner = self._owner_of(pi)
        if owner != self.url and not req.query.get("forwarded"):
            resp = await self._forward_pub(owner, req.query, value)
            if resp is not None:
                return resp
            # owner unreachable: refresh the ring and serve it ourselves if
            # ownership moved here, else fail loudly
            await self._refresh_peers()
            if self._owner_of(pi) != self.url:
                return web.json_response(
                    {"error": f"partition {pi} owner unreachable"},
                    status=503)

        tkey = str(Topic.parse(topic))
        if (tkey, pi) not in self.own_epoch:
            # fresh ownership of this partition (the ring changed, or
            # first publish ever): catch up from peers BEFORE the first
            # append.  A takeover owner whose local log is short (it was
            # neither owner nor follower before) would otherwise assign
            # offsets from ITS next_offset, colliding with the log the
            # previous owner's follower still holds — and anti-entropy
            # resolves collisions by keeping the longer (old) log,
            # silently DROPPING the fresh appends (observed as failover
            # message loss under ring flap).
            await self._catch_up(tkey, pi, part)
        epoch = await self._ensure_epoch(tkey, pi)
        offset = await asyncio.to_thread(part.publish, key, value)
        fenced = await self._replicate_out(topic, pi, part, offset, key,
                                           value, epoch)
        if fenced:
            # the follower has seen a newer owner: this broker's ring view
            # is stale — refresh and route the NEXT publish correctly; the
            # message is already appended locally and anti-entropy will
            # reconcile, but tell the client the truth
            self.own_epoch.pop((str(Topic.parse(topic)), pi), None)
            await self._refresh_peers()
            return web.json_response(
                {"error": f"fenced: partition {pi} has a newer owner"},
                status=503)
        return web.json_response({"partition": pi, "offset": offset})

    async def _forward_pub(self, owner: str, query, value: bytes):
        try:
            params = dict(query)
            params["forwarded"] = "1"
            async with self._session.post(
                    f"{_tls_scheme()}://{owner}/pub", params=params,
                    data=value,
                    timeout=aiohttp.ClientTimeout(total=15)) as r:
                return web.json_response(await r.json(), status=r.status)
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return None

    async def _replicate_out(self, topic: str, pi: int,
                             part: LocalPartition, offset: int,
                             key: bytes, value: bytes,
                             epoch: int = 0) -> bool:
        """Synchronous replication to the partition's follower (reference:
        partition followers); a gap answer triggers a snapshot push so a
        rejoining follower converges.  Returns True when the follower
        FENCED this append (it has seen a newer ownership epoch)."""
        follower = self._follower_of(pi)
        if follower is None:
            return False
        msg = {
            "topic": topic, "partition": pi, "offset": offset,
            "partition_count": len(self.topics[str(Topic.parse(topic))]),
            "ts_ns": time.time_ns(), "epoch": epoch,
            "key": base64.b64encode(key).decode(),
            "value": base64.b64encode(value).decode(),
        }
        try:
            async with self._session.post(
                    f"{_tls_scheme()}://{follower}/replicate", json=msg,
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                if r.status == 403:
                    return True
                if r.status == 409:  # follower has a gap: push everything
                    await self._push_state(follower, topic, pi, part)
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass  # follower down; the ring refresh will re-route it
        return False

    async def _push_state(self, peer: str, topic: str, pi: int,
                          part: LocalPartition) -> None:
        base, msgs = part.snapshot()
        try:
            async with self._session.post(
                    f"{_tls_scheme()}://{peer}/partition/state",
                    params={"topic": topic, "partition": str(pi)},
                    json={"base_offset": base,
                          "partition_count": len(
                              self.topics[str(Topic.parse(topic))]),
                          "messages": _encode_messages(msgs)},
                    timeout=aiohttp.ClientTimeout(total=30)):
                pass
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass

    async def handle_replicate(self, req: web.Request) -> web.Response:
        body = await req.json()
        topic = body["topic"]
        pi = int(body["partition"])
        parts = self._get_topic(topic, auto_create=True,
                                n=int(body.get("partition_count", 4)))
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        # fencing: appends from an owner whose epoch is older than the
        # newest we've replicated for are rejected, not merged (a stale
        # ring view must fail loudly instead of silently discarding the
        # settled owner's interleaved messages)
        ekey = (str(Topic.parse(topic)), pi)
        epoch = int(body.get("epoch", 0))
        seen = self.seen_epoch.get(ekey, 0)
        if epoch < seen:
            return web.json_response(
                {"error": f"fenced: epoch {epoch} < {seen}"}, status=403)
        self.seen_epoch[ekey] = epoch
        ok = parts[pi].append_replica(
            int(body["offset"]), int(body["ts_ns"]),
            base64.b64decode(body["key"]), base64.b64decode(body["value"]))
        if not ok:
            return web.json_response({"error": "gap"}, status=409)
        return web.json_response({"ok": True})

    async def handle_partition_state_get(self,
                                         req: web.Request) -> web.Response:
        parts = self._get_topic(req.query.get("topic", ""))
        if parts is None:
            return web.json_response({"error": "no such topic"}, status=404)
        pi = int(req.query.get("partition", "0"))
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        base, msgs = parts[pi].snapshot()
        return web.json_response({"base_offset": base,
                                  "partition_count": len(parts),
                                  "messages": _encode_messages(msgs)})

    async def handle_partition_state_put(self,
                                         req: web.Request) -> web.Response:
        body = await req.json()
        parts = self._get_topic(req.query.get("topic", ""),
                                auto_create=True,
                                n=int(body.get("partition_count", 4)))
        pi = int(req.query.get("partition", "0"))
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        parts[pi].load_snapshot(body["base_offset"],
                                _decode_messages(body["messages"]))
        return web.json_response({"ok": True})

    # -- subscribe -------------------------------------------------------

    async def handle_sub(self, req: web.Request) -> web.Response:
        topic = req.query.get("topic", "")
        parts = self._get_topic(topic)
        if parts is None:
            return web.json_response({"error": "no such topic"}, status=404)
        try:
            pi = int(req.query.get("partition", "0"))
            offset = int(req.query.get("offset", "0"))
            wait = min(float(req.query.get("wait", "0")), 60.0)
            limit = min(int(req.query.get("limit", "1024")), 16384)
        except ValueError:
            return web.json_response({"error": "bad params"}, status=400)
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        owner = self._owner_of(pi)
        if owner != self.url and self._follower_of(pi) != self.url:
            # this broker holds no replica of pi: an empty 200 here would
            # read as "caught up" forever — send the subscriber to the owner
            raise web.HTTPTemporaryRedirect(
                f"{_tls_scheme()}://{owner}/sub?{req.query_string}")
        part = parts[pi]
        if offset < part.base_offset and self.store is not None:
            # below the RAM window: serve from the durable filer segments
            batch = await self._read_segments(topic, pi, offset, limit)
            if batch:
                lines = b"".join(
                    json.dumps(m.to_dict(),
                               separators=(",", ":")).encode() + b"\n"
                    for m in batch)
                return web.Response(
                    body=lines, content_type="application/x-ndjson",
                    headers={"X-Next-Offset":
                             str(batch[-1].offset + 1)})
        batch = await asyncio.to_thread(part.read, offset, limit, wait)
        lines = b"".join(
            json.dumps(m.to_dict(), separators=(",", ":")).encode() + b"\n"
            for m in batch)
        return web.Response(body=lines, content_type="application/x-ndjson",
                            headers={"X-Next-Offset": str(
                                batch[-1].offset + 1 if batch else offset)})

    async def _read_segments(self, topic: str, pi: int, offset: int,
                             limit: int):
        """Messages from `offset` out of the filer segment files (the
        reference reads aged topic data back out of /topics the same way).
        Only segments covering [offset, ...) are downloaded, the most
        recently decoded ones are kept in a small LRU (a replaying
        consumer advances through a segment across several fetches —
        re-downloading it each time would make replay O(segments^2)), and
        duplicate offsets from flush-race overlaps are dropped."""
        # A LATER segment's copy of an offset wins — the same newest-wins
        # rule _recover applies, so live subscribers and a restarted
        # cluster resolve flush-race overlaps identically.  Early exit only
        # once `limit` offsets are collected AND the next segment starts
        # beyond the limit-th one (no density assumption: torn-tail drops
        # and corrupt-segment skips can leave gaps a fixed offset+limit
        # window would silently jump over).
        by_off: dict[int, Message] = {}
        if limit <= 0:
            return []
        for base, end, name in await self.store.list_segments(topic, pi):
            if end <= offset:
                continue
            if len(by_off) >= limit and \
                    base > sorted(by_off)[limit - 1]:
                break
            ckey = (topic, pi, name)
            msgs = self._seg_cache.get(ckey)
            if msgs is None:
                msgs = await self.store.read_segment(topic, pi, name)
                self._seg_cache[ckey] = msgs
                while len(self._seg_cache) > 8:
                    self._seg_cache.pop(next(iter(self._seg_cache)))
            for m in msgs:
                if m.offset >= offset:
                    by_off[m.offset] = m
        return [by_off[o] for o in sorted(by_off)][:limit]

    # -- consumer-group coordination (reference: sub_coordinator/) -------

    def _coordinator_of(self, group: str) -> str:
        """One broker coordinates each group (reference: sub_coordinator is
        the balancer-leader's job); deterministic over the ring so every
        member lands on the same one."""
        b = self.peer_brokers
        return b[ring_slot(group.encode()) % len(b)] if b else self.url

    async def handle_coordinator_join(self, req: web.Request) -> web.Response:
        """Register/renew a group member and return its partitions: the
        round-robin split of the topic's partitions over the live members
        (ConsumerGroup.BalanceConsumerGroupInstances in the reference).
        Joins are forwarded to the group's coordinator broker — membership
        lives in one place, so members joining via different brokers can
        never get overlapping assignments."""
        body = await req.json()
        group = body["group"]
        topic = str(Topic.parse(body["topic"]))
        member = body["member"]
        coord = self._coordinator_of(group)
        if coord != self.url and not req.query.get("forwarded"):
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{coord}/coordinator/join"
                        "?forwarded=1", json=body,
                        timeout=aiohttp.ClientTimeout(total=10)) as r:
                    return web.json_response(await r.json(),
                                             status=r.status)
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                await self._refresh_peers()
                if self._coordinator_of(group) != self.url:
                    return web.json_response(
                        {"error": "group coordinator unreachable"},
                        status=503)
        parts = self._get_topic(topic)
        if parts is None:
            return web.json_response({"error": "no such topic"}, status=404)
        gm = self.group_members.setdefault((group, topic), {})
        now = time.monotonic()
        gm[member] = now
        for m, seen in list(gm.items()):
            if now - seen > self.member_ttl:
                del gm[m]
        members = sorted(gm)
        mine = [i for i in range(len(parts))
                if members[i % len(members)] == member]
        return web.json_response({"partitions": mine, "members": members,
                                  "generation": len(members)})

    async def handle_offsets_commit(self, req: web.Request) -> web.Response:
        body = await req.json()
        key = (body["group"], str(Topic.parse(body["topic"])),
               int(body["partition"]))
        self.group_offsets[key] = int(body["offset"])
        if self.store is not None:
            # write-through so progress survives a full-cluster restart
            try:
                await self.store.write_offset(key[0], key[1], key[2],
                                              self.group_offsets[key])
            except OSError:
                log.exception("offset persist failed")

        # fan the commit out (concurrently — a dead peer must not stall the
        # consumer) so any surviving broker can answer offsets/get later
        async def push(peer: str) -> None:
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{peer}/offsets/sync",
                        json={"entries": [[key[0], key[1], key[2],
                                           self.group_offsets[key]]]},
                        timeout=aiohttp.ClientTimeout(total=5)):
                    pass
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass
        await asyncio.gather(*(push(p) for p in self.peer_brokers
                               if p != self.url))
        return web.json_response({"ok": True})

    async def handle_offsets_sync(self, req: web.Request) -> web.Response:
        body = await req.json()
        for g, t, p, off in body.get("entries", []):
            # exact value, not max: a deliberate rewind commit must
            # propagate, or brokers diverge and a failover skips the replay
            self.group_offsets[(g, t, int(p))] = int(off)
        return web.json_response({"ok": True})

    async def handle_offsets_get(self, req: web.Request) -> web.Response:
        key = (req.query.get("group", ""),
               str(Topic.parse(req.query.get("topic", ""))),
               int(req.query.get("partition", "0")))
        offset = self.group_offsets.get(key)
        if offset is None and self.store is not None:
            offset = await self.store.read_offset(*key)
            if offset is not None:
                self.group_offsets[key] = offset
        return web.json_response({"offset": offset or 0})

    async def handle_status(self, req: web.Request) -> web.Response:
        return web.json_response({
            "topics": len(self.topics),
            "partitions": sum(len(p) for p in self.topics.values()),
            "brokers": self.peer_brokers,
            "groups": len(self.group_members),
        })


def _encode_messages(msgs: list[Message]) -> list[list]:
    return [[m.offset, m.ts_ns,
             base64.b64encode(m.key).decode(),
             base64.b64encode(m.value).decode()] for m in msgs]


def _decode_messages(rows: list[list]) -> list[Message]:
    return [Message(int(o), int(ts),
                    base64.b64decode(k), base64.b64decode(v))
            for o, ts, k, v in rows]
