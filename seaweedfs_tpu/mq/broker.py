"""MQ broker: HTTP pub/sub server over LocalPartition logs.

Reference: weed/mq/broker/{broker_grpc_pub.go:37 Publish,
broker_grpc_sub.go:13 Subscribe, broker_grpc_configure.go} — the
reference streams over gRPC; here the same operations ride HTTP:

  POST /topics/configure   {"topic": "ns.name", "partition_count": N}
  GET  /topics/list
  POST /pub?topic=ns.name  body=value, ?key= routes by ring slot
  GET  /sub?topic=ns.name&partition=i&offset=K[&wait=seconds]
                           -> NDJSON batch (long-polls when caught up)
  GET  /status

Brokers register in the master's cluster registry (type=broker) just like
filers, standing in for the reference's pub_balancer broker ring.
"""

from __future__ import annotations

import asyncio
import json
import logging

import aiohttp
from aiohttp import web

from seaweedfs_tpu.mq.topic import (LocalPartition, Topic, ring_slot,
                                    split_ring)
from seaweedfs_tpu.security.tls import scheme as _tls_scheme
from seaweedfs_tpu.security import tls as _tls

log = logging.getLogger("mq.broker")


class BrokerServer:
    def __init__(self, master_url: str, host: str = "127.0.0.1",
                 port: int = 17777):
        self.master_url = master_url
        self.host, self.port = host, port
        # str(topic) -> list[LocalPartition]
        self.topics: dict[str, list[LocalPartition]] = {}
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.add_routes([
            web.post("/topics/configure", self.handle_configure),
            web.get("/topics/list", self.handle_list),
            web.post("/pub", self.handle_pub),
            web.get("/sub", self.handle_sub),
            web.get("/status", self.handle_status),
        ])
        self._runner: web.AppRunner | None = None
        self._session: aiohttp.ClientSession | None = None
        self._register_task: asyncio.Task | None = None

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(ssl=_tls.client_ssl()),
            timeout=aiohttp.ClientTimeout(total=30))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port,
                           ssl_context=_tls.server_ssl("broker"))
        await site.start()
        self._register_task = asyncio.create_task(self._register_loop())
        log.info("mq broker on %s", self.url)

    async def stop(self) -> None:
        if self._register_task:
            self._register_task.cancel()
        if self._session:
            await self._session.close()
        if self._runner:
            await self._runner.cleanup()

    async def _register_loop(self) -> None:
        while True:
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{self.master_url}/cluster/register",
                        json={"type": "broker", "address": self.url}):
                    pass
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(10)

    # -- handlers -------------------------------------------------------

    def _get_topic(self, name: str,
                   auto_create: bool = False) -> list[LocalPartition] | None:
        key = str(Topic.parse(name))
        parts = self.topics.get(key)
        if parts is None and auto_create:
            parts = [LocalPartition(p) for p in split_ring(4)]
            self.topics[key] = parts
        return parts

    async def handle_configure(self, req: web.Request) -> web.Response:
        body = await req.json()
        topic = str(Topic.parse(body["topic"]))
        n = int(body.get("partition_count", 4))
        if n <= 0 or n > 4096:
            return web.json_response({"error": "bad partition_count"},
                                     status=400)
        existing = self.topics.get(topic)
        if existing is not None and len(existing) != n:
            return web.json_response(
                {"error": "cannot repartition a live topic"}, status=409)
        if existing is None:
            self.topics[topic] = [LocalPartition(p) for p in split_ring(n)]
        return web.json_response({"topic": topic, "partition_count": n})

    async def handle_list(self, req: web.Request) -> web.Response:
        return web.json_response({
            "topics": [
                {"name": name, "partition_count": len(parts),
                 "next_offsets": [p.next_offset for p in parts]}
                for name, parts in sorted(self.topics.items())],
        })

    async def handle_pub(self, req: web.Request) -> web.Response:
        topic = req.query.get("topic", "")
        if not topic:
            return web.json_response({"error": "topic required"}, status=400)
        parts = self._get_topic(topic, auto_create=True)
        key = req.query.get("key", "").encode()
        value = await req.read()
        slot = ring_slot(key)
        part = next((p for p in parts if p.partition.holds_key(key)),
                    parts[slot % len(parts)])
        idx = parts.index(part)
        offset = await asyncio.to_thread(part.publish, key, value)
        return web.json_response({"partition": idx, "offset": offset})

    async def handle_sub(self, req: web.Request) -> web.Response:
        topic = req.query.get("topic", "")
        parts = self._get_topic(topic)
        if parts is None:
            return web.json_response({"error": "no such topic"}, status=404)
        try:
            pi = int(req.query.get("partition", "0"))
            offset = int(req.query.get("offset", "0"))
            wait = min(float(req.query.get("wait", "0")), 60.0)
            limit = min(int(req.query.get("limit", "1024")), 16384)
        except ValueError:
            return web.json_response({"error": "bad params"}, status=400)
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        part = parts[pi]
        batch = await asyncio.to_thread(part.read, offset, limit, wait)
        lines = b"".join(
            json.dumps(m.to_dict(), separators=(",", ":")).encode() + b"\n"
            for m in batch)
        return web.Response(body=lines, content_type="application/x-ndjson",
                            headers={"X-Next-Offset": str(
                                batch[-1].offset + 1 if batch else offset)})

    async def handle_status(self, req: web.Request) -> web.Response:
        return web.json_response({
            "topics": len(self.topics),
            "partitions": sum(len(p) for p in self.topics.values()),
        })
