"""MQ broker: HTTP pub/sub with partition balancing, follower replication,
broker failover, and subscriber-group coordination.

Reference: weed/mq/broker/{broker_grpc_pub.go:37 Publish,
broker_grpc_sub.go:13 Subscribe, broker_grpc_configure.go} plus the
coordination plane in weed/mq/pub_balancer/ (partition->broker assignment)
and weed/mq/sub_coordinator/ (consumer-group partition assignment +
progress). The reference streams over gRPC with an elected balancer
broker; here the same roles ride HTTP with a DETERMINISTIC balance rule —
partition i of a topic is owned by sorted(live_brokers)[i % n], its
follower is the next broker in that ring — so every broker (and client)
computes identical assignments from the shared live-broker view instead of
holding leader state:

  POST /topics/configure   {"topic": "ns.name", "partition_count": N}
  GET  /topics/list
  POST /pub?topic=ns.name  body=value, ?key= routes by ring slot;
                           forwarded to the owning broker, synchronously
                           replicated to the follower
  GET  /sub?topic=ns.name&partition=i&offset=K[&wait=seconds]
                           -> NDJSON batch (long-polls when caught up)
  POST /replicate          follower append (leader pushes a snapshot on gap)
  GET/POST /partition/state  full-partition snapshot pull / push
  POST /coordinator/join   {"group","topic","member"} -> partitions for
                           this member (round-robin over live members)
  POST /offsets/commit     {"group","topic","partition","offset"}
  GET  /offsets/get?group=&topic=&partition=
  GET  /status

Brokers register in the master's cluster registry (type=broker); each
broker's peer view = master's member list filtered by a direct liveness
probe, refreshed continuously. Killing a broker re-routes its partitions
to survivors, which already hold the data via follower replication —
publishes keep succeeding and subscribers lose nothing. Group offsets are
broadcast to every live broker on commit so they also survive failover.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import time

import aiohttp
from aiohttp import web

from seaweedfs_tpu.mq.topic import (LocalPartition, Message, Topic,
                                    ring_slot, split_ring)
from seaweedfs_tpu.security.tls import scheme as _tls_scheme
from seaweedfs_tpu.security import tls as _tls

log = logging.getLogger("mq.broker")


class BrokerServer:
    def __init__(self, master_url: str, host: str = "127.0.0.1",
                 port: int = 17777, peer_refresh: float = 2.0,
                 member_ttl: float = 15.0):
        self.master_url = master_url
        self.host, self.port = host, port
        self.peer_refresh = peer_refresh
        self.member_ttl = member_ttl
        # str(topic) -> list[LocalPartition]
        self.topics: dict[str, list[LocalPartition]] = {}
        self.peer_brokers: list[str] = [self.url]  # sorted, self included
        # (group, topic) -> {member: last_seen}
        self.group_members: dict[tuple[str, str], dict[str, float]] = {}
        # (group, topic, partition) -> committed offset
        self.group_offsets: dict[tuple[str, str, int], int] = {}
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.add_routes([
            web.post("/topics/configure", self.handle_configure),
            web.get("/topics/list", self.handle_list),
            web.post("/pub", self.handle_pub),
            web.get("/sub", self.handle_sub),
            web.post("/replicate", self.handle_replicate),
            web.get("/partition/state", self.handle_partition_state_get),
            web.post("/partition/state", self.handle_partition_state_put),
            web.post("/coordinator/join", self.handle_coordinator_join),
            web.post("/offsets/commit", self.handle_offsets_commit),
            web.post("/offsets/sync", self.handle_offsets_sync),
            web.get("/offsets/get", self.handle_offsets_get),
            web.get("/status", self.handle_status),
        ])
        self._runner: web.AppRunner | None = None
        self._session: aiohttp.ClientSession | None = None
        self._register_task: asyncio.Task | None = None

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(ssl=_tls.client_ssl()),
            timeout=aiohttp.ClientTimeout(total=30))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port,
                           ssl_context=_tls.server_ssl("broker"))
        await site.start()
        self._register_task = asyncio.create_task(self._register_loop())
        log.info("mq broker on %s", self.url)

    async def stop(self) -> None:
        if self._register_task:
            self._register_task.cancel()
        if self._session:
            await self._session.close()
        if self._runner:
            await self._runner.cleanup()

    # -- membership / balance --------------------------------------------

    async def _register_loop(self) -> None:
        while True:
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{self.master_url}/cluster/register",
                        json={"type": "broker", "address": self.url},
                        timeout=aiohttp.ClientTimeout(total=10)):
                    pass
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # the loop must outlive any transient failure
            try:
                await self._refresh_peers()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("peer refresh failed")
            await asyncio.sleep(self.peer_refresh)

    async def _refresh_peers(self) -> None:
        """Live-broker view = master registry ∩ direct probe. The balance
        rule is pure arithmetic over this sorted list, so agreement on the
        list IS agreement on every partition assignment."""
        candidates = {self.url}
        try:
            async with self._session.get(
                    f"{_tls_scheme()}://{self.master_url}/cluster/status",
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                members = (await r.json()).get("Members", {})
                candidates.update(members.get("broker", []))
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            pass

        async def probe(addr: str) -> str | None:
            if addr == self.url:
                return addr
            try:
                async with self._session.get(
                        f"{_tls_scheme()}://{addr}/status",
                        timeout=aiohttp.ClientTimeout(total=2)) as r:
                    return addr if r.status == 200 else None
            except (aiohttp.ClientError, asyncio.TimeoutError):
                return None

        alive = sorted(a for a in await asyncio.gather(
            *(probe(a) for a in sorted(candidates))) if a)
        if alive != self.peer_brokers:
            log.info("broker ring: %s -> %s", self.peer_brokers, alive)
            self.peer_brokers = alive
        # anti-entropy every cycle (and the takeover path after a ring
        # change): a broker that accepted publishes under a stale ring view
        # holds data its settled owner lacks; comparing next_offsets and
        # pulling the longer log converges every such divergence
        await self._reconcile()

    async def _reconcile(self) -> None:
        for peer in self.peer_brokers:
            if peer == self.url:
                continue
            try:
                async with self._session.get(
                        f"{_tls_scheme()}://{peer}/topics/list",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    listing = await r.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                continue
            for t in listing.get("topics", []):
                name = t["name"]
                parts = self._get_topic(name, auto_create=True,
                                        n=t["partition_count"])
                if len(parts) != t["partition_count"]:
                    continue  # partition-count conflict; leave it alone
                for pi, peer_next in enumerate(t["next_offsets"]):
                    mine = self._owner_of(pi) == self.url or \
                        self._follower_of(pi) == self.url
                    if mine and peer_next > parts[pi].next_offset:
                        await self._pull_state(peer, name, pi, parts[pi])

    async def _pull_state(self, peer: str, topic: str, pi: int,
                          part: LocalPartition) -> None:
        try:
            async with self._session.get(
                    f"{_tls_scheme()}://{peer}/partition/state",
                    params={"topic": topic, "partition": str(pi)},
                    timeout=aiohttp.ClientTimeout(total=30)) as r:
                if r.status != 200:
                    return
                st = await r.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return
        part.load_snapshot(st["base_offset"],
                           _decode_messages(st["messages"]))

    def _owner_of(self, pi: int) -> str:
        b = self.peer_brokers
        return b[pi % len(b)] if b else self.url

    def _follower_of(self, pi: int) -> str | None:
        b = self.peer_brokers
        if len(b) < 2:
            return None
        return b[(pi + 1) % len(b)]

    # -- topic admin -----------------------------------------------------

    def _get_topic(self, name: str,
                   auto_create: bool = False,
                   n: int = 4) -> list[LocalPartition] | None:
        key = str(Topic.parse(name))
        parts = self.topics.get(key)
        if parts is None and auto_create:
            parts = [LocalPartition(p) for p in split_ring(n)]
            self.topics[key] = parts
        return parts

    async def handle_configure(self, req: web.Request) -> web.Response:
        body = await req.json()
        topic = str(Topic.parse(body["topic"]))
        n = int(body.get("partition_count", 4))
        if n <= 0 or n > 4096:
            return web.json_response({"error": "bad partition_count"},
                                     status=400)
        existing = self.topics.get(topic)
        if existing is not None and len(existing) != n:
            return web.json_response(
                {"error": "cannot repartition a live topic"}, status=409)
        if existing is None:
            self.topics[topic] = [LocalPartition(p) for p in split_ring(n)]
        if not req.query.get("propagated"):
            # every broker holds every partition object (leader for some,
            # follower for others) so configuration fans out
            for peer in self.peer_brokers:
                if peer == self.url:
                    continue
                try:
                    async with self._session.post(
                            f"{_tls_scheme()}://{peer}/topics/configure"
                            "?propagated=1", json=body,
                            timeout=aiohttp.ClientTimeout(total=5)):
                        pass
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    pass
        return web.json_response({"topic": topic, "partition_count": n})

    async def handle_list(self, req: web.Request) -> web.Response:
        return web.json_response({
            "topics": [
                {"name": name, "partition_count": len(parts),
                 "next_offsets": [p.next_offset for p in parts]}
                for name, parts in sorted(self.topics.items())],
            "brokers": self.peer_brokers,
        })

    # -- publish ---------------------------------------------------------

    async def handle_pub(self, req: web.Request) -> web.Response:
        topic = req.query.get("topic", "")
        if not topic:
            return web.json_response({"error": "topic required"}, status=400)
        parts = self._get_topic(topic, auto_create=True)
        key = req.query.get("key", "").encode()
        value = await req.read()
        slot = ring_slot(key)
        part = next((p for p in parts if p.partition.holds_key(key)),
                    parts[slot % len(parts)])
        pi = parts.index(part)

        owner = self._owner_of(pi)
        if owner != self.url and not req.query.get("forwarded"):
            resp = await self._forward_pub(owner, req.query, value)
            if resp is not None:
                return resp
            # owner unreachable: refresh the ring and serve it ourselves if
            # ownership moved here, else fail loudly
            await self._refresh_peers()
            if self._owner_of(pi) != self.url:
                return web.json_response(
                    {"error": f"partition {pi} owner unreachable"},
                    status=503)

        offset = await asyncio.to_thread(part.publish, key, value)
        await self._replicate_out(topic, pi, part, offset, key, value)
        return web.json_response({"partition": pi, "offset": offset})

    async def _forward_pub(self, owner: str, query, value: bytes):
        try:
            params = dict(query)
            params["forwarded"] = "1"
            async with self._session.post(
                    f"{_tls_scheme()}://{owner}/pub", params=params,
                    data=value,
                    timeout=aiohttp.ClientTimeout(total=15)) as r:
                return web.json_response(await r.json(), status=r.status)
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return None

    async def _replicate_out(self, topic: str, pi: int,
                             part: LocalPartition, offset: int,
                             key: bytes, value: bytes) -> None:
        """Synchronous replication to the partition's follower (reference:
        partition followers); a gap answer triggers a snapshot push so a
        rejoining follower converges."""
        follower = self._follower_of(pi)
        if follower is None:
            return
        msg = {
            "topic": topic, "partition": pi, "offset": offset,
            "partition_count": len(self.topics[str(Topic.parse(topic))]),
            "ts_ns": time.time_ns(),
            "key": base64.b64encode(key).decode(),
            "value": base64.b64encode(value).decode(),
        }
        try:
            async with self._session.post(
                    f"{_tls_scheme()}://{follower}/replicate", json=msg,
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                if r.status == 409:  # follower has a gap: push everything
                    await self._push_state(follower, topic, pi, part)
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass  # follower down; the ring refresh will re-route it

    async def _push_state(self, peer: str, topic: str, pi: int,
                          part: LocalPartition) -> None:
        base, msgs = part.snapshot()
        try:
            async with self._session.post(
                    f"{_tls_scheme()}://{peer}/partition/state",
                    params={"topic": topic, "partition": str(pi)},
                    json={"base_offset": base,
                          "partition_count": len(
                              self.topics[str(Topic.parse(topic))]),
                          "messages": _encode_messages(msgs)},
                    timeout=aiohttp.ClientTimeout(total=30)):
                pass
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass

    async def handle_replicate(self, req: web.Request) -> web.Response:
        body = await req.json()
        topic = body["topic"]
        pi = int(body["partition"])
        parts = self._get_topic(topic, auto_create=True,
                                n=int(body.get("partition_count", 4)))
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        ok = parts[pi].append_replica(
            int(body["offset"]), int(body["ts_ns"]),
            base64.b64decode(body["key"]), base64.b64decode(body["value"]))
        if not ok:
            return web.json_response({"error": "gap"}, status=409)
        return web.json_response({"ok": True})

    async def handle_partition_state_get(self,
                                         req: web.Request) -> web.Response:
        parts = self._get_topic(req.query.get("topic", ""))
        if parts is None:
            return web.json_response({"error": "no such topic"}, status=404)
        pi = int(req.query.get("partition", "0"))
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        base, msgs = parts[pi].snapshot()
        return web.json_response({"base_offset": base,
                                  "partition_count": len(parts),
                                  "messages": _encode_messages(msgs)})

    async def handle_partition_state_put(self,
                                         req: web.Request) -> web.Response:
        body = await req.json()
        parts = self._get_topic(req.query.get("topic", ""),
                                auto_create=True,
                                n=int(body.get("partition_count", 4)))
        pi = int(req.query.get("partition", "0"))
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        parts[pi].load_snapshot(body["base_offset"],
                                _decode_messages(body["messages"]))
        return web.json_response({"ok": True})

    # -- subscribe -------------------------------------------------------

    async def handle_sub(self, req: web.Request) -> web.Response:
        topic = req.query.get("topic", "")
        parts = self._get_topic(topic)
        if parts is None:
            return web.json_response({"error": "no such topic"}, status=404)
        try:
            pi = int(req.query.get("partition", "0"))
            offset = int(req.query.get("offset", "0"))
            wait = min(float(req.query.get("wait", "0")), 60.0)
            limit = min(int(req.query.get("limit", "1024")), 16384)
        except ValueError:
            return web.json_response({"error": "bad params"}, status=400)
        if not 0 <= pi < len(parts):
            return web.json_response({"error": "bad partition"}, status=400)
        owner = self._owner_of(pi)
        if owner != self.url and self._follower_of(pi) != self.url:
            # this broker holds no replica of pi: an empty 200 here would
            # read as "caught up" forever — send the subscriber to the owner
            raise web.HTTPTemporaryRedirect(
                f"{_tls_scheme()}://{owner}/sub?{req.query_string}")
        part = parts[pi]
        batch = await asyncio.to_thread(part.read, offset, limit, wait)
        lines = b"".join(
            json.dumps(m.to_dict(), separators=(",", ":")).encode() + b"\n"
            for m in batch)
        return web.Response(body=lines, content_type="application/x-ndjson",
                            headers={"X-Next-Offset": str(
                                batch[-1].offset + 1 if batch else offset)})

    # -- consumer-group coordination (reference: sub_coordinator/) -------

    def _coordinator_of(self, group: str) -> str:
        """One broker coordinates each group (reference: sub_coordinator is
        the balancer-leader's job); deterministic over the ring so every
        member lands on the same one."""
        b = self.peer_brokers
        return b[ring_slot(group.encode()) % len(b)] if b else self.url

    async def handle_coordinator_join(self, req: web.Request) -> web.Response:
        """Register/renew a group member and return its partitions: the
        round-robin split of the topic's partitions over the live members
        (ConsumerGroup.BalanceConsumerGroupInstances in the reference).
        Joins are forwarded to the group's coordinator broker — membership
        lives in one place, so members joining via different brokers can
        never get overlapping assignments."""
        body = await req.json()
        group = body["group"]
        topic = str(Topic.parse(body["topic"]))
        member = body["member"]
        coord = self._coordinator_of(group)
        if coord != self.url and not req.query.get("forwarded"):
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{coord}/coordinator/join"
                        "?forwarded=1", json=body,
                        timeout=aiohttp.ClientTimeout(total=10)) as r:
                    return web.json_response(await r.json(),
                                             status=r.status)
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                await self._refresh_peers()
                if self._coordinator_of(group) != self.url:
                    return web.json_response(
                        {"error": "group coordinator unreachable"},
                        status=503)
        parts = self._get_topic(topic)
        if parts is None:
            return web.json_response({"error": "no such topic"}, status=404)
        gm = self.group_members.setdefault((group, topic), {})
        now = time.monotonic()
        gm[member] = now
        for m, seen in list(gm.items()):
            if now - seen > self.member_ttl:
                del gm[m]
        members = sorted(gm)
        mine = [i for i in range(len(parts))
                if members[i % len(members)] == member]
        return web.json_response({"partitions": mine, "members": members,
                                  "generation": len(members)})

    async def handle_offsets_commit(self, req: web.Request) -> web.Response:
        body = await req.json()
        key = (body["group"], str(Topic.parse(body["topic"])),
               int(body["partition"]))
        self.group_offsets[key] = int(body["offset"])

        # fan the commit out (concurrently — a dead peer must not stall the
        # consumer) so any surviving broker can answer offsets/get later
        async def push(peer: str) -> None:
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{peer}/offsets/sync",
                        json={"entries": [[key[0], key[1], key[2],
                                           self.group_offsets[key]]]},
                        timeout=aiohttp.ClientTimeout(total=5)):
                    pass
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass
        await asyncio.gather(*(push(p) for p in self.peer_brokers
                               if p != self.url))
        return web.json_response({"ok": True})

    async def handle_offsets_sync(self, req: web.Request) -> web.Response:
        body = await req.json()
        for g, t, p, off in body.get("entries", []):
            # exact value, not max: a deliberate rewind commit must
            # propagate, or brokers diverge and a failover skips the replay
            self.group_offsets[(g, t, int(p))] = int(off)
        return web.json_response({"ok": True})

    async def handle_offsets_get(self, req: web.Request) -> web.Response:
        key = (req.query.get("group", ""),
               str(Topic.parse(req.query.get("topic", ""))),
               int(req.query.get("partition", "0")))
        return web.json_response({"offset": self.group_offsets.get(key, 0)})

    async def handle_status(self, req: web.Request) -> web.Response:
        return web.json_response({
            "topics": len(self.topics),
            "partitions": sum(len(p) for p in self.topics.values()),
            "brokers": self.peer_brokers,
            "groups": len(self.group_members),
        })


def _encode_messages(msgs: list[Message]) -> list[list]:
    return [[m.offset, m.ts_ns,
             base64.b64encode(m.key).decode(),
             base64.b64encode(m.value).decode()] for m in msgs]


def _decode_messages(rows: list[list]) -> list[Message]:
    return [Message(int(o), int(ts),
                    base64.b64decode(k), base64.b64decode(v))
            for o, ts, k, v in rows]
