"""Async replication: meta-event-driven sinks + filer.sync
(reference: weed/replication/, weed/command/filer_sync.go)."""
