"""filer.sync — continuous (optionally bidirectional) filer→filer
replication over the meta-event subscribe stream, with persisted resume
offsets, signature-based loop prevention, and the geo-replication
observatory's lag plane.

Reference: weed/command/filer_sync.go (doSubscribeFilerMetaChanges),
weed/replication/track_sync_offset.go.  Loop prevention follows the
reference's signature scheme: the direction src→dst stamps every write
with sig(src) and skips any event already stamped sig(dst) — an event on
src that was itself written by the dst→src direction carries sig(dst) and
must not echo back.

Observatory (``WEEDTPU_GEO_OBS=0`` disables all of it, read per event so
the bench can price it):

- **lag**: now minus the last applied/confirmed source event timestamp.
  Live-stream keepalives count as confirmation — an idle healthy pipe
  reads ~0, a partitioned one freezes its progress clock and lag climbs;
- **backlog**: source meta-log events newer than the resume offset,
  polled from the source's ``/__meta__/digest`` endpoint (cheap head
  read, no tree walk) on connect, on stream errors, and at most every
  ``WEEDTPU_SYNC_BACKLOG_INTERVAL`` seconds while streaming;
- **stalled**: the pump itself publishes
  ``weedtpu_replication_stalled{direction}=1`` once no progress has been
  made for ``WEEDTPU_SYNC_STALL_AFTER`` seconds AND the stream is
  erroring — the alert engine can't express that conjunction, so the
  default ``replication_stalled`` rule just thresholds this gauge;
- **traces**: every applied event runs under a fresh sampled root span
  (``sync.apply``) that the source read and the sink write inherit, so
  ``/cluster/trace/<tid>`` shows one write's waterfall across both
  regions; the last root id is kept on ``SyncDirection.last_trace_id``;
- **WAN ledger**: sink writes run inside ``netflow.wan(remote_region)``
  so every cross-region byte is double-booked into
  ``weedtpu_wan_bytes_total`` beside the class=replication ledger.

Resilience (PR 8 layer, replacing the old fixed ``stop.wait(2.0)``
reconnect sleep and hand-rolled ``2**attempt`` apply retries):
reconnects pace on a decorrelated-jitter ``Backoff``
(``WEEDTPU_SYNC_BACKOFF_BASE``/``_CAP``) and spend class=replication
retry-budget tokens — an exhausted budget parks the pump at the cap so a
dead region can't turn N pumps into a retry storm.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.parse
import urllib.request
import zlib

from seaweedfs_tpu.replication.sink import FilerSink, Replicator
from seaweedfs_tpu.security.tls import scheme as _tls_scheme
from seaweedfs_tpu.stats import netflow as _netflow
from seaweedfs_tpu.stats import trace as _trace
from seaweedfs_tpu.utils import resilience as _res
from seaweedfs_tpu.utils.http import PooledHTTP

MAX_APPLY_RETRIES = 5

log = logging.getLogger("filer.sync")


def geo_obs_enabled() -> bool:
    """Observatory switch, read per event (the bench flips it between
    interleaved reps to price the lag plane itself)."""
    return os.environ.get("WEEDTPU_GEO_OBS", "1") != "0"


def _sync_backoff() -> "_res.Backoff":
    return _res.Backoff(
        base=float(os.environ.get("WEEDTPU_SYNC_BACKOFF_BASE", "0.5")),
        cap=float(os.environ.get("WEEDTPU_SYNC_BACKOFF_CAP", "15")))


def stall_after_s() -> float:
    return float(os.environ.get("WEEDTPU_SYNC_STALL_AFTER", "30"))


def backlog_interval_s() -> float:
    return float(os.environ.get("WEEDTPU_SYNC_BACKLOG_INTERVAL", "5"))


def filer_signature(filer_url: str) -> int:
    return zlib.crc32(filer_url.encode()) & 0x7FFFFFFF or 1


class SyncOffsetStore:
    """Resume offsets persisted to a local JSON file
    (reference: replication/track_sync_offset.go persists in the filer)."""

    def __init__(self, path: str | None):
        self.path = path
        self._data: dict[str, int] = {}
        self._lock = threading.Lock()  # both sync directions share one store
        self._last_flush = 0.0
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._data = {k: int(v) for k, v in json.load(f).items()}
            except (OSError, ValueError):
                self._data = {}

    FLUSH_INTERVAL = 2.0  # seconds between on-disk offset snapshots

    def get(self, key: str) -> int:
        with self._lock:
            return self._data.get(key, 0)

    def put(self, key: str, ts_ns: int) -> None:
        """Update in memory; snapshot to disk at most every FLUSH_INTERVAL
        (events are idempotent, so a crash replays at most a couple of
        seconds — the reference also persists offsets periodically)."""
        import time as _time
        with self._lock:
            self._data[key] = ts_ns
            now = _time.monotonic()
            if self.path and now - self._last_flush >= self.FLUSH_INTERVAL:
                self._flush_locked()
                self._last_flush = now

    def flush(self) -> None:
        with self._lock:
            if self.path:
                self._flush_locked()

    def _flush_locked(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._data, f)
        os.replace(tmp, self.path)


class SyncDirection:
    """One src→dst pump."""

    def __init__(self, src: str, dst: str, prefix: str = "/",
                 offsets: SyncOffsetStore | None = None,
                 timeout: float = 60.0, sink=None,
                 region: str = "", remote_region: str = ""):
        """`sink` defaults to a FilerSink on `dst`; pass any
        ReplicationSink (e.g. LocalSink for filer.backup) to replicate
        into something other than a peer filer.  `region` names the
        pump's home region (it runs beside its SOURCE filer) and
        `remote_region` the sink's, for the WAN ledger and region
        faults; both default to "" — single-region pumps pay nothing."""
        self.src, self.dst = src, dst
        self.prefix = prefix
        self.offsets = offsets or SyncOffsetStore(None)
        self.key = f"{src}=>{dst}"
        # metric/trace label: region pair when known ("a->b"), else the
        # netloc pair — region names keep the label space bounded
        self.direction = (f"{region}->{remote_region}"
                          if region and remote_region else self.key)
        self.src_sig = filer_signature(src)
        self.dst_sig = filer_signature(dst)
        self.timeout = timeout
        self.region = region
        self.remote_region = remote_region
        # one pool for source reads, backlog polls, AND sink writes:
        # replication bytes ride the netflow ledger, breakers, and
        # deadline clamps like every other caller's
        self.http = PooledHTTP(timeout=timeout, role="replicator",
                               region=region)
        if sink is None:
            # retries=1: _apply owns the (budgeted, offset-replaying)
            # retry loop — a second layer inside the sink would multiply
            # worst-case stall detection into minutes
            sink = FilerSink(dst, signature=self.src_sig, timeout=timeout,
                             http=self.http, region=remote_region,
                             retries=1)
        self.replicator = Replicator(sink, self._read_source_file, prefix)
        self.applied = 0
        self.skipped = 0
        self.errors = 0
        self.backlog = 0
        self.stalled = False
        # progress clock: the timestamp replication is known caught up
        # to (applied event ts, or "now" on a live keepalive).  Lag is
        # now minus this.
        self.last_progress = time.time()
        self.last_trace_id = ""
        self._backoff = _sync_backoff()
        self._last_backlog_poll = 0.0
        self._stop: threading.Event | None = None

    # -- observatory ------------------------------------------------------

    def _gauges(self):
        from seaweedfs_tpu.stats import metrics as _metrics
        return _metrics

    def lag_s(self, now: float | None = None) -> float:
        return max(0.0, (now or time.time()) - self.last_progress)

    def _note_progress(self, event_ts_ns: int | None = None) -> None:
        """An event applied/skipped (confirmed up to its ts), or a live
        keepalive (confirmed up to now)."""
        now = time.time()
        self.last_progress = now if event_ts_ns is None \
            else min(now, event_ts_ns / 1e9)
        self._backoff.reset()
        if not geo_obs_enabled():
            return
        m = self._gauges()
        m.REPLICATION_LAG.labels(self.direction).set(self.lag_s(now))
        if self.stalled:
            self.stalled = False
            m.REPLICATION_STALLED.labels(self.direction).set(0)

    def _note_error(self) -> None:
        """A stream/apply error: refresh the lag gauge from the frozen
        progress clock and raise the stalled flag once the stall window
        has passed with no progress."""
        self.errors += 1
        if not geo_obs_enabled():
            return
        m = self._gauges()
        m.REPLICATION_ERRORS.labels(self.direction).inc()
        lag = self.lag_s()
        m.REPLICATION_LAG.labels(self.direction).set(lag)
        if lag > stall_after_s():
            self.stalled = True
            m.REPLICATION_STALLED.labels(self.direction).set(1)

    def _poll_backlog(self, force: bool = False) -> None:
        """Refresh backlog depth (source meta-log head minus our resume
        offset) from the source's digest endpoint — cheap head read, no
        tree walk.  Best effort: a dead source keeps the last value."""
        if not geo_obs_enabled():
            return
        now = time.monotonic()
        if not force and now - self._last_backlog_poll < \
                backlog_interval_s():
            return
        self._last_backlog_poll = now
        url = (f"{_tls_scheme()}://{self.src}/__meta__/digest?"
               + urllib.parse.urlencode({
                   "prefix": self.prefix, "digest": "0",
                   "since": str(self.offsets.get(self.key))}))
        try:
            status, _, body = self.http.request(url, timeout=self.timeout)
            if status != 200:
                return
            self.backlog = int(json.loads(body).get("backlog_events", 0))
            self._gauges().REPLICATION_BACKLOG.labels(self.direction).set(
                self.backlog)
        except (OSError, ValueError):
            pass

    def status(self) -> dict:
        return {"src": self.src, "dst": self.dst, "prefix": self.prefix,
                "region": self.region, "remote_region": self.remote_region,
                "applied": self.applied, "skipped": self.skipped,
                "errors": self.errors, "backlog": self.backlog,
                "direction": self.direction,
                "lag_s": round(self.lag_s(), 3), "stalled": self.stalled,
                "offset_ts_ns": self.offsets.get(self.key),
                "last_trace_id": self.last_trace_id}

    # -- pump -------------------------------------------------------------

    def _read_source_file(self, path: str) -> bytes:
        from seaweedfs_tpu.replication.sink import HTTPStatusError
        url = f"{_tls_scheme()}://{self.src}{urllib.parse.quote(path)}"
        status, _, body = self.http.request(url, timeout=self.timeout)
        if status == 404:
            # the file was deleted/renamed after this event was logged;
            # a later event supersedes it — skip, don't stall the stream
            raise FileNotFoundError(path)
        if status >= 400:
            raise HTTPStatusError(status, url)
        return body

    def run(self, stop: threading.Event, live: bool = True) -> None:
        """Pump events until `stop` is set (or the replay drains when
        live=False)."""
        self._stop = stop
        while not stop.is_set():
            since = self.offsets.get(self.key)
            url = (f"{_tls_scheme()}://{self.src}/__meta__/subscribe?"
                   + urllib.parse.urlencode({
                       "since": str(since),
                       "prefix": self.prefix,
                       "live": "true" if live else "false"}))
            try:
                self._poll_backlog(force=True)
                with urllib.request.urlopen(url, timeout=self.timeout) as r:
                    for raw in r:
                        if stop.is_set():
                            return
                        line = raw.strip()
                        if not line:
                            # keepalive: the stream is live and drained —
                            # replication is caught up as of now
                            self._note_progress()
                            self._poll_backlog()
                            continue
                        ev = json.loads(line)
                        if not self._apply(ev, stop):
                            # event still failing after retries: reconnect
                            # from the last good offset rather than skip it
                            raise ConnectionError("replicate failed; "
                                                  "will retry from offset")
                if not live:
                    return
            except (urllib.error.URLError, ConnectionError, OSError,
                    json.JSONDecodeError, TimeoutError) as e:
                if not live:
                    raise
                self._note_error()
                self._poll_backlog(force=True)
                delay = self._backoff.next()
                if not _res.spend_retry("replication"):
                    # budget exhausted: park at the cap — the damper
                    # working, not a bug (see utils/resilience.py)
                    delay = max(delay, self._backoff.cap)
                log.warning("%s: stream error, reconnecting in %.1fs: %s",
                            self.key, delay, e)
                stop.wait(delay)

    def _replicate_observed(self, ev: dict) -> bool:
        """One replicate pass under the observatory: class=replication
        netflow, a fresh sampled root span both regions' servers will
        parent to, and WAN booking on the sink side (the sink enters
        ``wan(remote_region)`` itself — the source read is local)."""
        if not geo_obs_enabled():
            with _netflow.flow("replication"):
                return self.replicator.replicate(ev)
        t = _trace.new_root(sampled=True)
        tok = _trace._current.set(t)
        try:
            path = (ev.get("new_entry") or ev.get("old_entry")
                    or {}).get("full_path", "")
            with _netflow.flow("replication"), \
                    _trace.span("sync.apply", server="replicator",
                                direction=self.direction, path=path,
                                region=self.region):
                return self.replicator.replicate(ev)
        finally:
            _trace._current.reset(tok)
            self.last_trace_id = t.trace_id

    def _apply(self, ev: dict, stop: threading.Event | None = None) -> bool:
        """Apply one event; the offset advances ONLY on success so a
        transient sink failure re-replays instead of silently dropping
        (events are idempotent overwrites)."""
        stop = stop or self._stop
        if self.dst_sig in (ev.get("signatures") or []):
            self.skipped += 1  # originated on dst; don't echo back
            if geo_obs_enabled():
                self._gauges().REPLICATION_SKIPPED.labels(self.direction).inc()
            self._note_progress(ev["ts_ns"])
            self.offsets.put(self.key, ev["ts_ns"])
            return True
        path = (ev.get("new_entry") or ev.get("old_entry")
                or {}).get("full_path")

        def giveup(e: BaseException) -> bool:
            # deleted-at-source is handled by the caller, and client
            # errors (HTTP < 500) won't heal by retrying
            return isinstance(e, FileNotFoundError) or \
                getattr(e, "code", 500) < 500

        try:
            did = _res.retry_call(
                lambda: self._replicate_observed(ev),
                attempts=MAX_APPLY_RETRIES, base=self._backoff.base,
                cap=10.0, cls="replication", retry_on=(Exception,),
                giveup=giveup,
                sleep=(stop.wait if stop is not None else time.sleep))
        except FileNotFoundError:
            # source content gone; a later event will converge the sink
            self.skipped += 1
            if geo_obs_enabled():
                self._gauges().REPLICATION_SKIPPED.labels(self.direction).inc()
            self._note_progress(ev["ts_ns"])
            self.offsets.put(self.key, ev["ts_ns"])
            return True
        except Exception as e:
            log.warning("%s: replicate %s failed after %d tries: %s",
                        self.key, path, MAX_APPLY_RETRIES, e)
            self._note_error()
            return False
        if did:
            self.applied += 1
            if geo_obs_enabled():
                self._gauges().REPLICATION_APPLIED.labels(self.direction).inc()
        self._note_progress(ev["ts_ns"])
        self.offsets.put(self.key, ev["ts_ns"])
        return True


class FilerSync:
    """Bidirectional filer.sync (reference: weed filer.sync -a -b).

    With region names attached (the GeoCluster harness does), each
    direction labels its WAN bytes and the divergence auditor
    (stats/canary.DivergenceAuditor) rides along, proving both filers'
    subtree digests converge."""

    def __init__(self, filer_a: str, filer_b: str, prefix: str = "/",
                 offset_path: str | None = None, one_way: bool = False,
                 region_a: str = "", region_b: str = ""):
        offsets = SyncOffsetStore(offset_path)
        self.a2b = SyncDirection(filer_a, filer_b, prefix, offsets,
                                 region=region_a, remote_region=region_b)
        self.b2a = None if one_way else SyncDirection(
            filer_b, filer_a, prefix, offsets,
            region=region_b, remote_region=region_a)
        self.stop_event = threading.Event()
        self._threads: list[threading.Thread] = []
        self.auditor = None
        if not one_way:
            from seaweedfs_tpu.stats.canary import DivergenceAuditor
            self.auditor = DivergenceAuditor(filer_a, filer_b, prefix,
                                             region_a=region_a,
                                             region_b=region_b)

    def start(self) -> None:
        for d in self.directions():
            th = threading.Thread(target=d.run, args=(self.stop_event,),
                                  daemon=True, name=f"sync-{d.key}")
            th.start()
            self._threads.append(th)
        if self.auditor is not None:
            self.auditor.start()

    def directions(self) -> list[SyncDirection]:
        return [d for d in (self.a2b, self.b2a) if d is not None]

    def status(self) -> dict:
        out = {"directions": {d.key: d.status()
                              for d in self.directions()}}
        if self.auditor is not None:
            out["audit"] = self.auditor.status()
        return out

    def stop(self) -> None:
        self.stop_event.set()
        if self.auditor is not None:
            self.auditor.stop()
        for th in self._threads:
            th.join(5)
        self.a2b.offsets.flush()  # both directions share the store

    def run_forever(self) -> None:
        self.start()
        try:
            while True:
                self.stop_event.wait(3600)
        except KeyboardInterrupt:
            self.stop()
