"""filer.sync — continuous (optionally bidirectional) filer→filer
replication over the meta-event subscribe stream, with persisted resume
offsets and signature-based loop prevention.

Reference: weed/command/filer_sync.go (doSubscribeFilerMetaChanges),
weed/replication/track_sync_offset.go.  Loop prevention follows the
reference's signature scheme: the direction src→dst stamps every write
with sig(src) and skips any event already stamped sig(dst) — an event on
src that was itself written by the dst→src direction carries sig(dst) and
must not echo back.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.parse
import urllib.request
import zlib

from seaweedfs_tpu.replication.sink import FilerSink, Replicator
from seaweedfs_tpu.security.tls import scheme as _tls_scheme

MAX_APPLY_RETRIES = 5

log = logging.getLogger("filer.sync")


def filer_signature(filer_url: str) -> int:
    return zlib.crc32(filer_url.encode()) & 0x7FFFFFFF or 1


class SyncOffsetStore:
    """Resume offsets persisted to a local JSON file
    (reference: replication/track_sync_offset.go persists in the filer)."""

    def __init__(self, path: str | None):
        self.path = path
        self._data: dict[str, int] = {}
        self._lock = threading.Lock()  # both sync directions share one store
        self._last_flush = 0.0
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._data = {k: int(v) for k, v in json.load(f).items()}
            except (OSError, ValueError):
                self._data = {}

    FLUSH_INTERVAL = 2.0  # seconds between on-disk offset snapshots

    def get(self, key: str) -> int:
        with self._lock:
            return self._data.get(key, 0)

    def put(self, key: str, ts_ns: int) -> None:
        """Update in memory; snapshot to disk at most every FLUSH_INTERVAL
        (events are idempotent, so a crash replays at most a couple of
        seconds — the reference also persists offsets periodically)."""
        import time as _time
        with self._lock:
            self._data[key] = ts_ns
            now = _time.monotonic()
            if self.path and now - self._last_flush >= self.FLUSH_INTERVAL:
                self._flush_locked()
                self._last_flush = now

    def flush(self) -> None:
        with self._lock:
            if self.path:
                self._flush_locked()

    def _flush_locked(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._data, f)
        os.replace(tmp, self.path)


class SyncDirection:
    """One src→dst pump."""

    def __init__(self, src: str, dst: str, prefix: str = "/",
                 offsets: SyncOffsetStore | None = None,
                 timeout: float = 60.0, sink=None):
        """`sink` defaults to a FilerSink on `dst`; pass any
        ReplicationSink (e.g. LocalSink for filer.backup) to replicate
        into something other than a peer filer."""
        self.src, self.dst = src, dst
        self.prefix = prefix
        self.offsets = offsets or SyncOffsetStore(None)
        self.key = f"{src}=>{dst}"
        self.src_sig = filer_signature(src)
        self.dst_sig = filer_signature(dst)
        self.timeout = timeout
        if sink is None:
            sink = FilerSink(dst, signature=self.src_sig, timeout=timeout)
        self.replicator = Replicator(sink, self._read_source_file, prefix)
        self.applied = 0
        self.skipped = 0

    def _read_source_file(self, path: str) -> bytes:
        url = f"{_tls_scheme()}://{self.src}{urllib.parse.quote(path)}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # the file was deleted/renamed after this event was logged;
                # a later event supersedes it — skip, don't stall the stream
                raise FileNotFoundError(path) from e
            raise

    def run(self, stop: threading.Event, live: bool = True) -> None:
        """Pump events until `stop` is set (or the replay drains when
        live=False)."""
        while not stop.is_set():
            since = self.offsets.get(self.key)
            url = (f"{_tls_scheme()}://{self.src}/__meta__/subscribe?"
                   + urllib.parse.urlencode({
                       "since": str(since),
                       "prefix": self.prefix,
                       "live": "true" if live else "false"}))
            try:
                with urllib.request.urlopen(url, timeout=self.timeout) as r:
                    for raw in r:
                        if stop.is_set():
                            return
                        line = raw.strip()
                        if not line:
                            continue  # keepalive
                        ev = json.loads(line)
                        if not self._apply(ev):
                            # event still failing after retries: reconnect
                            # from the last good offset rather than skip it
                            raise ConnectionError("replicate failed; "
                                                  "will retry from offset")
                if not live:
                    return
            except (urllib.error.URLError, ConnectionError, OSError,
                    json.JSONDecodeError, TimeoutError) as e:
                if not live:
                    raise
                log.warning("%s: stream error, reconnecting: %s",
                            self.key, e)
                stop.wait(2.0)

    def _apply(self, ev: dict) -> bool:
        """Apply one event; the offset advances ONLY on success so a
        transient sink failure re-replays instead of silently dropping
        (events are idempotent overwrites)."""
        if self.dst_sig in (ev.get("signatures") or []):
            self.skipped += 1  # originated on dst; don't echo back
            self.offsets.put(self.key, ev["ts_ns"])
            return True
        path = (ev.get("new_entry") or ev.get("old_entry")
                or {}).get("full_path")
        for attempt in range(MAX_APPLY_RETRIES):
            try:
                if self.replicator.replicate(ev):
                    self.applied += 1
                self.offsets.put(self.key, ev["ts_ns"])
                return True
            except FileNotFoundError:
                # source content gone; a later event will converge the sink
                self.skipped += 1
                self.offsets.put(self.key, ev["ts_ns"])
                return True
            except Exception as e:
                log.warning("%s: replicate %s failed (try %d/%d): %s",
                            self.key, path, attempt + 1, MAX_APPLY_RETRIES, e)
                if attempt + 1 < MAX_APPLY_RETRIES:
                    import time
                    time.sleep(min(2 ** attempt, 10))
        return False


class FilerSync:
    """Bidirectional filer.sync (reference: weed filer.sync -a -b)."""

    def __init__(self, filer_a: str, filer_b: str, prefix: str = "/",
                 offset_path: str | None = None, one_way: bool = False):
        offsets = SyncOffsetStore(offset_path)
        self.a2b = SyncDirection(filer_a, filer_b, prefix, offsets)
        self.b2a = None if one_way else SyncDirection(filer_b, filer_a,
                                                      prefix, offsets)
        self.stop_event = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> None:
        for d in filter(None, (self.a2b, self.b2a)):
            th = threading.Thread(target=d.run, args=(self.stop_event,),
                                  daemon=True, name=f"sync-{d.key}")
            th.start()
            self._threads.append(th)

    def stop(self) -> None:
        self.stop_event.set()
        for th in self._threads:
            th.join(5)
        self.a2b.offsets.flush()  # both directions share the store

    def run_forever(self) -> None:
        self.start()
        try:
            while True:
                self.stop_event.wait(3600)
        except KeyboardInterrupt:
            self.stop()
