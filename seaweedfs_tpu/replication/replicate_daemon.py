"""Queue-driven replicate daemon.

Reference: `weed filer.replicate` (weed/command/filer_replicate.go:23-80) —
consume filer meta events from the configured notification queue and apply
each to a ReplicationSink, resuming from a persisted offset after restart
(the reference delegates resume to the broker's consumer offset; file/memory
queues carry the offset here, in the same SyncOffsetStore the filer.sync
daemon uses).

Sources mirror weed/replication/sub/notifications.go's input registry: the
JSONL log-file queue (the `log` notification backend's counterpart) and an
in-memory queue for tests; kafka-style brokers would slot in behind the
same two-method SPI.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from seaweedfs_tpu.replication.filer_sync import SyncOffsetStore
from seaweedfs_tpu.replication.sink import Replicator, ReplicationSink

log = logging.getLogger("replication.replicate")


class NotificationSource:
    """Input side of the replicate daemon: yields (next_offset, event)."""

    name = "abstract"

    def receive(self, since: int, stop: threading.Event):
        raise NotImplementedError


class LogFileSource(NotificationSource):
    """Tail the notification LogQueue's JSONL file; the resume offset is
    the byte position after the last applied line, so a restarted daemon
    re-reads nothing and skips nothing (partial trailing lines — a writer
    mid-append — are left for the next poll)."""

    name = "log"

    def __init__(self, path: str, poll_interval: float = 0.2):
        self.path = path
        self.poll_interval = poll_interval

    def receive(self, since: int, stop: threading.Event):
        pos = since
        while not stop.is_set():
            try:
                f = open(self.path, "rb")
            except FileNotFoundError:
                if stop.wait(self.poll_interval):
                    return
                continue
            with f:
                f.seek(pos)
                while not stop.is_set():
                    line = f.readline()
                    if not line:
                        break
                    if not line.endswith(b"\n"):
                        break  # torn tail: re-read after the writer flushes
                    pos = f.tell()
                    s = line.strip()
                    if not s:
                        continue
                    try:
                        yield pos, json.loads(s)
                    except ValueError:
                        log.warning("skipping malformed event line at %d",
                                    pos)
            if stop.wait(self.poll_interval):
                return


class MemorySource(NotificationSource):
    """Consume a notification.MemoryQueue; the offset is the count of
    messages consumed from the queue since process start.  The queue's
    deque is bounded, so eviction is tracked via the queue's total send
    count — consuming resumes at (total - len(deque)) at worst, and a
    gap (evicted-before-read messages) is logged rather than silently
    skipped."""

    name = "memory"

    def __init__(self, queue, poll_interval: float = 0.05):
        self.queue = queue
        self.poll_interval = poll_interval

    def receive(self, since: int, stop: threading.Event):
        seen = since
        import contextlib
        # snapshot (sent, messages) under the queue's lock when it has
        # one: this used to snapshot the deque BEFORE reading sent, so a
        # send() racing between the two reads inflated `first` and an
        # event was skipped (or yielded under the wrong offset) without
        # any eviction having occurred.  The sent-before-snapshot order
        # alone is not enough either — append and the sent increment are
        # two bytecodes, and catching the gap after an eviction
        # mis-offsets msgs[0].
        lock = getattr(self.queue, "lock", None) or contextlib.nullcontext()
        while not stop.is_set():
            with lock:
                total = getattr(self.queue, "sent", None)
                msgs = list(self.queue.messages)
            if total is None:
                total = len(msgs)
            first = max(0, total - len(msgs))  # absolute index of msgs[0]
            if seen < first:
                log.warning("memory queue evicted %d unread events",
                            first - seen)
                seen = first
            while seen < total:
                _, message = msgs[seen - first]
                seen += 1
                yield seen, message
            if stop.wait(self.poll_interval):
                return


class ReplicateDaemon:
    """Pump source -> sink with offset persistence and per-event retry
    already inside the sink layer (sink.retry)."""

    def __init__(self, source: NotificationSource, sink: ReplicationSink,
                 read_file, prefix: str = "/",
                 offset_path: str | None = None,
                 offset_key: str | None = None):
        self.source = source
        self.replicator = Replicator(sink, read_file, prefix=prefix)
        self.offsets = SyncOffsetStore(offset_path)
        self.key = offset_key or f"replicate:{source.name}:{sink.name}"
        self.stop_event = threading.Event()
        self.applied = 0

    def run(self) -> None:
        since = self.offsets.get(self.key)
        for offset, event in self.source.receive(since, self.stop_event):
            try:
                if self.replicator.replicate(event):
                    self.applied += 1
            except Exception:
                # the sink layer already retried with backoff; a still-
                # failing event must not wedge the stream forever — log
                # loudly and move the offset past it (the reference's
                # processEventFn error likewise skips after logging)
                log.exception("replicate failed for event at offset %s",
                              offset)
            self.offsets.put(self.key, offset)
        self.offsets.flush()

    def run_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.run, name="filer-replicate",
                             daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self.stop_event.set()


def read_file_via_filer(filer_url: str, timeout: float = 60.0):
    """File-content reader for sinks: fetch the path from the filer HTTP
    API (same shape SyncDirection._read_source_file uses)."""
    import urllib.parse
    import urllib.request
    from seaweedfs_tpu.security.tls import scheme as _tls_scheme

    def read(path: str) -> bytes:
        url = f"{_tls_scheme()}://{filer_url}{urllib.parse.quote(path)}"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read()
    return read
