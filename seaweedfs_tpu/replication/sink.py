"""Replication sinks: apply a stream of filer meta events to a target.

Reference: weed/replication/sink/replication_sink.go (interface:
CreateEntry / UpdateEntry / DeleteEntry + IsIncremental) and the concrete
sinks under weed/replication/sink/{filersink,localsink,s3sink,...}.  Here:
FilerSink (another weedtpu filer over HTTP) and LocalSink (a local
directory tree), registered by name like the reference's sink registry.
"""

from __future__ import annotations

import json
import logging
import os
import time
import urllib.parse
import urllib.request
from seaweedfs_tpu.security.tls import scheme as _tls_scheme

log = logging.getLogger("replication.sink")


class HTTPStatusError(OSError):
    """An HTTP error status from a PooledHTTP call (which never raises
    on statuses itself).  Carries ``.code`` like urllib's HTTPError so
    the shared retry giveup can treat both the same."""

    def __init__(self, code: int, url: str):
        super().__init__(f"HTTP {code} from {url}")
        self.code = code
        self.url = url


def retry(fn, attempts: int = 4, base_delay: float = 0.5,
          retriable=(urllib.error.URLError, ConnectionError, OSError)):
    """Budgeted jittered retry for sink IO (reference: util.Retry wraps
    every sink write) — without it one transient 500 during filer.sync
    drops the event permanently.  Rides the unified resilience layer:
    decorrelated-jitter delays, and every retry spends a token from the
    process-wide budget so a down replication target can't storm.
    Client errors (HTTP < 500 — urllib HTTPError or our own
    HTTPStatusError) won't heal by retrying and raise immediately."""
    from seaweedfs_tpu.utils import resilience

    def giveup(e: BaseException) -> bool:
        return getattr(e, "code", 500) < 500

    def wrapped():
        try:
            return fn()
        except retriable as e:
            log.warning("sink call failed (%s); may retry", e)
            raise

    return resilience.retry_call(
        wrapped, attempts=attempts, base=base_delay, cap=30.0,
        cls="replication",
        retry_on=(retriable if isinstance(retriable, tuple)
                  else (retriable,)),
        giveup=giveup)


def entry_is_directory(entry: dict) -> bool:
    """Entry dicts carry directoriness in attr.mode (S_IFDIR), matching
    Entry.to_dict / Attr.is_directory."""
    import stat
    if "is_directory" in entry:
        return bool(entry["is_directory"])
    return stat.S_ISDIR((entry.get("attr") or {}).get("mode", 0))


class ReplicationSink:
    """create/update/delete against a replication target."""

    name = "abstract"

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        raise NotImplementedError

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        self.create_entry(path, entry, data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        raise NotImplementedError

    def is_incremental(self) -> bool:
        """Incremental sinks only append dated copies, never delete
        (reference: IsIncremental + -filer.backup)."""
        return False


class FilerSink(ReplicationSink):
    """Replicate into another filer over its HTTP API, stamping the
    configured signature for sync-loop prevention.  Writes ride a
    PooledHTTP (deadline clamps, breakers, netflow/trace headers) —
    raw urllib kept replication bytes invisible to the byte ledger —
    and, when a remote region is named, run inside ``netflow.wan()``
    so the WAN ledger books every cross-region byte."""

    name = "filer"

    def __init__(self, filer_url: str, path_prefix: str = "/",
                 signature: int = 0, timeout: float = 60.0,
                 http=None, region: str = "", retries: int = 4):
        self.filer_url = filer_url
        self.prefix = path_prefix.rstrip("/")
        self.signature = signature
        self.timeout = timeout
        # sink-level retry attempts.  The sync pump passes 1: its _apply
        # loop already does budgeted retries AND re-replays from the
        # offset, and stacking the two layers multiplies worst-case
        # stall detection from seconds into minutes.  Standalone users
        # (filer.backup, cloud sinks) keep the default — this is their
        # only retry layer.
        self.retries = retries
        # the REMOTE region this sink writes toward ("" = same region)
        self.region = region
        if http is None:
            from seaweedfs_tpu.utils.http import PooledHTTP
            http = PooledHTTP(timeout=timeout, role="replicator")
        self.http = http
        # transient, set per-event by the Replicator: the event's existing
        # signature chain, forwarded so ring topologies terminate
        self.event_signatures: list[int] = []

    def _headers(self) -> dict:
        sigs = [s for s in self.event_signatures if s]
        if self.signature:
            sigs.append(self.signature)
        return {"X-Weed-Signatures": ",".join(map(str, sigs))} if sigs else {}

    def _url(self, path: str) -> str:
        return f"{_tls_scheme()}://{self.filer_url}{urllib.parse.quote(self.prefix + path)}"

    def _request(self, url: str, method: str, body: bytes | None,
                 headers: dict, ok_statuses=()) -> None:
        from seaweedfs_tpu.stats import netflow as _netflow
        if self.region:
            with _netflow.wan(self.region):
                status, _, _ = self.http.request(
                    url, method, body, headers, timeout=self.timeout)
        else:
            status, _, _ = self.http.request(
                url, method, body, headers, timeout=self.timeout)
        if status >= 400 and status not in ok_statuses:
            raise HTTPStatusError(status, url)

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        if entry_is_directory(entry):
            url = self._url(path.rstrip("/") + "/")
            headers = self._headers()
            body: bytes = b""
        else:
            url = self._url(path)
            headers = self._headers()
            attr = entry.get("attr") or {}
            if attr.get("mime"):
                headers["Content-Type"] = attr["mime"]
            for k, v in (entry.get("extended") or {}).items():
                headers[f"Seaweed-{k}"] = v
            body = data or b""
        retry(lambda: self._request(url, "POST", body, headers),
              attempts=self.retries)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        url = self._url(path) + "?recursive=true"
        # 404 tolerated: the entry may never have replicated
        retry(lambda: self._request(url, "DELETE", None, self._headers(),
                                    ok_statuses=(404,)),
              attempts=self.retries)


class LocalSink(ReplicationSink):
    """Replicate into a local directory (reference:
    weed/replication/sink/localsink)."""

    name = "local"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _p(self, path: str) -> str:
        return os.path.join(self.dir, path.lstrip("/"))

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        p = self._p(path)
        if entry_is_directory(entry):
            os.makedirs(p, exist_ok=True)
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data or b"")

    def delete_entry(self, path: str, is_directory: bool) -> None:
        p = self._p(path)
        try:
            if is_directory:
                import shutil
                shutil.rmtree(p, ignore_errors=True)
            else:
                os.remove(p)
        except FileNotFoundError:
            pass


class CloudSink(ReplicationSink):
    """Replicate into an object store through a RemoteStorageClient wire
    client (reference: weed/replication/sink/{s3sink/s3_sink.go:30-70,
    gcssink,azuresink,b2sink}).  Object stores have no directories, so
    directory events are no-ops; incremental mode prefixes keys with the
    event date and never deletes (the reference's IsIncremental backup
    behavior)."""

    name = "cloud"

    def __init__(self, remote, key_prefix: str = "",
                 incremental: bool = False):
        self.remote = remote
        self.key_prefix = key_prefix.strip("/")
        self.incremental = incremental

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        if self.incremental:
            key = time.strftime("%Y-%m-%d") + "/" + key
        if self.key_prefix:
            key = self.key_prefix + "/" + key
        return key

    def is_incremental(self) -> bool:
        return self.incremental

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        if entry_is_directory(entry):
            return
        retry(lambda: self.remote.write_file(self._key(path), data or b""))

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            # delete every object under the prefix (S3 has no rmdir);
            # skip directory placeholder entries some remotes yield —
            # delete_file on them would error and abort the fan-out
            prefix = self._key(path).rstrip("/") + "/"
            for ent in list(self.remote.traverse(prefix)):
                if ent.is_directory:
                    continue
                retry(lambda k=ent.key: self.remote.delete_file(k))
            return
        retry(lambda: self.remote.delete_file(self._key(path)))


def _cloud_sink_factory(kind: str):
    """Sink kinds s3/gcs/azure/b2 construct the matching wire client from
    seaweedfs_tpu.remote_storage (b2 rides B2's S3-compatible endpoint, so
    it shares the SigV4 client the way the reference's b2sink shares the
    blazer API shape)."""
    def make(key_prefix: str = "", incremental=False, **remote_opts):
        from seaweedfs_tpu import remote_storage
        remote_kind = "s3" if kind == "b2" else kind
        remote = remote_storage.make_remote(remote_kind, **remote_opts)
        # sink specs arrive as strings from the CLI ("incremental=false")
        if isinstance(incremental, str):
            incremental = incremental.lower() in ("true", "1", "yes")
        sink = CloudSink(remote, key_prefix=key_prefix,
                         incremental=incremental)
        sink.name = kind
        return sink
    return make


SINKS = {"filer": FilerSink, "local": LocalSink,
         "s3": _cloud_sink_factory("s3"), "gcs": _cloud_sink_factory("gcs"),
         "azure": _cloud_sink_factory("azure"),
         "b2": _cloud_sink_factory("b2")}


def make_sink(kind: str, **options) -> ReplicationSink:
    try:
        return SINKS[kind](**options)
    except KeyError:
        raise ValueError(f"unknown sink {kind!r} (have {sorted(SINKS)})")


class Replicator:
    """Routes one meta event to a sink (reference:
    weed/replication/replicator.go Replicate)."""

    def __init__(self, sink: ReplicationSink,
                 read_file: "callable[[str], bytes]",
                 prefix: str = "/"):
        self.sink = sink
        self.read_file = read_file
        self.prefix = prefix if prefix.endswith("/") else prefix + "/"

    def _in_scope(self, path: str) -> bool:
        return path.startswith(self.prefix) or path == self.prefix.rstrip("/")

    def replicate(self, event: dict) -> bool:
        """Apply one subscribe-stream event dict.  Returns True if the
        event resulted in a sink action."""
        # forward the event's signature chain (loop prevention must be
        # transitive across multi-filer rings)
        if hasattr(self.sink, "event_signatures"):
            self.sink.event_signatures = list(event.get("signatures") or [])
        old, new = event.get("old_entry"), event.get("new_entry")
        old_path = old.get("full_path") if old else None
        new_path = new.get("full_path") if new else None
        if new is not None:
            if not self._in_scope(new_path):
                # rename OUT of the synced subtree: drop the sink's copy of
                # the old path, or it diverges forever
                if old is not None and self._in_scope(old_path) and \
                        not self.sink.is_incremental():
                    self.sink.delete_entry(old_path, entry_is_directory(old))
                    return True
                return False
            data = None
            if not entry_is_directory(new):
                data = self.read_file(new_path)
            if old is not None and old_path != new_path and \
                    self._in_scope(old_path) and not self.sink.is_incremental():
                self.sink.delete_entry(old_path, entry_is_directory(old))
            if old is None:
                self.sink.create_entry(new_path, new, data)
            else:
                self.sink.update_entry(new_path, new, data)
            return True
        if old is not None and self._in_scope(old_path) and \
                not self.sink.is_incremental():
            self.sink.delete_entry(old_path, entry_is_directory(old))
            return True
        return False
