"""Fleet simulator: hundreds of virtual volume servers in one process.

The master's control loops (aggregator, history/alerts, repair planner,
autopilot, interference observatory) had only ever seen single-digit
node counts; their superlinear walls are invisible at that scale.  This
module registers hundreds of *virtual* volume servers against a REAL
master: each vnode is an `asyncio.start_server` socket (no threads, no
aiohttp app — ~one open listener per node) that serves a synthesized
Prometheus `/metrics` exposition and a mergeable `/heat` sketch, plus a
real `/heartbeat` POST loop so the topology, repair planner, and
aggregator treat it exactly like a live fleet.

Workload model (deterministic per WEEDTPU_FLEETSIM_SEED):
  - read traffic per volume follows a Zipf(a) popularity curve,
  - fleet rate swings on a diurnal sine (period compressed to minutes),
  - `flash_crowd()` multiplies one node set's rate and fattens its
    latency tail — the interference observatory sees p99 inflation,
  - counters accumulate lazily at scrape time (rate × elapsed), so an
    idle simulator costs nothing between scrapes.

Failure injection: `fail_rack(rack)` silences heartbeats AND scrape
responses for every vnode in the rack (correlated failure, the arxiv
1309.0186 pattern); `recover_rack` lifts it.  `stop_nodes`/`add_nodes`
provide join/leave churn for eviction/retirement audits.

Knobs: WEEDTPU_FLEETSIM_NODES (500), WEEDTPU_FLEETSIM_RACKS (10),
WEEDTPU_FLEETSIM_VOLUMES per node (8), WEEDTPU_FLEETSIM_HEARTBEAT
seconds (5), WEEDTPU_FLEETSIM_RPS base reads/s per node (120),
WEEDTPU_FLEETSIM_ZIPF_A (1.1), WEEDTPU_FLEETSIM_SEED (42),
WEEDTPU_FLEETSIM_DELAY_MS per-response service delay (0).  CLI:

    python -m seaweedfs_tpu.maintenance.fleetsim <master host:port>

runs a fleet against an already-running master until interrupted.
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import random
import threading
import time
import uuid

from seaweedfs_tpu.security.tls import scheme as _tls_scheme
from seaweedfs_tpu.utils import weedlog

# latency buckets the synthesized read histogram exposes — a subset of
# metrics._DEFAULT_BUCKETS is enough for p99 math in the observatory
_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)

# fraction of reads completing under each bucket bound: calm tail vs
# the fattened tail a flash crowd (or rack failure recovery) causes
_CALM_FRACS = (0.30, 0.60, 0.82, 0.93, 0.985, 0.997, 0.9995, 1.0, 1.0)
_BUSY_FRACS = (0.10, 0.25, 0.45, 0.65, 0.83, 0.93, 0.97, 0.995, 1.0)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class _VNode:
    """One virtual volume server: listener + lazily-advanced counters."""

    def __init__(self, sim: "FleetSim", idx: int, rack: str,
                 volumes: list[int]):
        self.sim = sim
        self.idx = idx
        self.rack = rack
        self.volumes = volumes  # global volume ids hosted here
        self.url = ""           # "127.0.0.1:port" once the listener is up
        self.tracker_id = uuid.uuid4().hex
        self.server: asyncio.base_events.Server | None = None
        self.hb_task: asyncio.Task | None = None
        self.failed = False     # rack failure: drop scrapes + heartbeats
        # lazily-advanced workload counters
        self._last = sim.t0
        self.reads = 0.0
        self.read_sum = 0.0                       # seconds
        self.buckets = [0.0] * len(_BUCKETS)      # cumulative counts
        self.net = {"scrub": 0.0, "repair": 0.0}  # background bytes
        self.used = 10e9 + (idx % 7) * 1e9        # of 100 GB total
        self.vol_sizes = {v: 1e8 + (v % 13) * 1e7 for v in volumes}
        self.scrub_scale = 1.0  # governor /admin/scrub_rate pushes land

    # -- workload model ---------------------------------------------------

    def _rate(self, t: float) -> float:
        """Reads/s now: base × diurnal sine × flash-crowd multiplier."""
        sim = self.sim
        diurnal = 1.0 + 0.5 * math.sin(
            2 * math.pi * (t - sim.t0) / sim.diurnal_period)
        flash = sim.flash_mult if self.idx in sim.flash_nodes and \
            t < sim.flash_until else 1.0
        return sim.base_rps * diurnal * flash

    def advance(self, now: float) -> None:
        """Integrate counters since the last advance (scrape-triggered)."""
        dt = now - self._last
        if dt <= 0:
            return
        self._last = now
        busy = self.idx in self.sim.flash_nodes and \
            now < self.sim.flash_until
        fracs = _BUSY_FRACS if busy else _CALM_FRACS
        n = self._rate(now) * dt
        self.reads += n
        self.read_sum += n * (0.05 if busy else 0.004)
        for i, frac in enumerate(fracs):
            self.buckets[i] += n * frac
        # background byte flows: scrub paced by the governor's pushed
        # scale, a trickle of repair traffic on a few nodes
        self.net["scrub"] += 20e6 * self.scrub_scale * dt
        if self.idx % 17 == 0:
            self.net["repair"] += 5e6 * dt
        self.used += self.sim.fill_bps * dt
        for v in self.vol_sizes:
            self.vol_sizes[v] += self.sim.fill_bps * dt / \
                max(len(self.vol_sizes), 1)

    # -- synthesized surfaces ---------------------------------------------

    def render_metrics(self) -> str:
        now = time.time()
        self.advance(now)
        L = [
            "# TYPE weedtpu_volume_request_seconds histogram",
        ]
        for le, c in zip(_BUCKETS, self.buckets):
            L.append('weedtpu_volume_request_seconds_bucket'
                     f'{{type="read",le="{le}"}} {c:.3f}')
        L.append('weedtpu_volume_request_seconds_bucket'
                 f'{{type="read",le="+Inf"}} {self.reads:.3f}')
        L.append('weedtpu_volume_request_seconds_count'
                 f'{{type="read"}} {self.reads:.3f}')
        L.append('weedtpu_volume_request_seconds_sum'
                 f'{{type="read"}} {self.read_sum:.3f}')
        L.append("# TYPE weedtpu_net_bytes_total counter")
        for cls, b in self.net.items():
            L.append(f'weedtpu_net_bytes_total{{class="{cls}",'
                     f'direction="sent"}} {b:.0f}')
        L.append("# TYPE weedtpu_disk_bytes gauge")
        L.append(f'weedtpu_disk_bytes{{vs="{self.url}",dir="/sim",'
                 f'kind="total"}} {100e9:.0f}')
        L.append(f'weedtpu_disk_bytes{{vs="{self.url}",dir="/sim",'
                 f'kind="used"}} {self.used:.0f}')
        L.append("# TYPE weedtpu_volume_size_bytes gauge")
        for v, s in self.vol_sizes.items():
            L.append(f'weedtpu_volume_size_bytes{{vid="{v}",'
                     f'vs="{self.url}"}} {s:.0f}')
        return "\n".join(L) + "\n"

    def render_heat(self) -> str:
        """A mergeable HeatTracker serialization: volume-dim Space-Saving
        entries weighted by this node's Zipf curve (distinct tracker id,
        so the master's fleet merge counts every vnode)."""
        now = time.time()
        self.advance(now)
        weights = self.sim.zipf_weights(len(self.volumes))
        entries = []
        for v, w in zip(self.volumes, weights):
            est = self.reads * w
            entries.append([str(v), round(est, 3), 0.0,
                            {"read": round(est, 3),
                             "bytes": round(est * 4096, 1)},
                            self.sim.t0])
        top = {"ts": now, "k": max(len(entries), 1),
               "halflife": 300.0, "total": round(self.reads, 3),
               "min": 0.0, "entries": entries}
        return json.dumps({
            "ts": now, "id": self.tracker_id, "k": top["k"],
            "halflife": 300.0,
            "dims": {"chunk": {}, "volume": top, "tenant": {}},
            "cms": {}})

    def heartbeat_body(self) -> dict:
        return {
            "id": self.url, "url": self.url, "public_url": self.url,
            "data_center": "simdc", "rack": self.rack,
            "max_volume_count": len(self.volumes) + 2,
            "volumes": [{
                "id": v, "collection": "", "size": int(self.vol_sizes[v]),
                "file_count": 100, "delete_count": 0, "deleted_bytes": 0,
                "read_only": False, "replica_placement": "000", "ttl": "",
                "modified_at": int(time.time()),
            } for v in self.volumes],
            "ec_shards": [],
        }

    # -- the listener -----------------------------------------------------

    async def handle(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), 5.0)
            parts = line.decode("latin-1").split()
            path = parts[1] if len(parts) > 1 else "/"
            clen = 0
            while True:
                h = await asyncio.wait_for(reader.readline(), 5.0)
                if h in (b"\r\n", b"\n", b""):
                    break
                if h.lower().startswith(b"content-length:"):
                    clen = int(h.split(b":", 1)[1])
            body = await reader.readexactly(clen) if clen else b""
            if self.failed:
                writer.close()
                return
            if self.sim.response_delay > 0:
                await asyncio.sleep(self.sim.response_delay)
            path = path.split("?", 1)[0]
            if path == "/metrics":
                payload = self.render_metrics().encode()
                ctype = "text/plain; version=0.0.4"
            elif path == "/heat":
                payload = self.render_heat().encode()
                ctype = "application/json"
            elif path == "/admin/scrub_rate":
                try:
                    self.scrub_scale = float(
                        json.loads(body or b"{}").get("scale", 1.0))
                except (ValueError, TypeError):
                    pass
                payload, ctype = b"{}", "application/json"
            else:
                payload, ctype = b"{}", "application/json"
            writer.write(b"HTTP/1.0 200 OK\r\nContent-Type: " +
                         ctype.encode() + b"\r\nContent-Length: " +
                         str(len(payload)).encode() + b"\r\n\r\n" + payload)
            await writer.drain()
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionError, ValueError, IndexError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass


class FleetSim:
    """Drive a real master with N virtual volume servers.

    Runs its own asyncio loop in a daemon thread; every public method is
    thread-safe.  `start()` brings the listeners up and begins heartbeats;
    `wait_registered()` blocks until the master's topology holds every
    live vnode."""

    def __init__(self, master_url: str, nodes: int | None = None,
                 racks: int | None = None,
                 volumes_per_node: int | None = None,
                 heartbeat_s: float | None = None,
                 base_rps: float | None = None,
                 zipf_a: float | None = None,
                 seed: int | None = None,
                 response_delay: float | None = None):
        self.master_url = master_url
        self.n_nodes = nodes if nodes is not None else \
            _env_int("WEEDTPU_FLEETSIM_NODES", 500)
        self.n_racks = racks if racks is not None else \
            _env_int("WEEDTPU_FLEETSIM_RACKS", 10)
        self.vols_per_node = volumes_per_node if volumes_per_node \
            is not None else _env_int("WEEDTPU_FLEETSIM_VOLUMES", 8)
        self.heartbeat_s = heartbeat_s if heartbeat_s is not None else \
            _env_float("WEEDTPU_FLEETSIM_HEARTBEAT", 5.0)
        self.base_rps = base_rps if base_rps is not None else \
            _env_float("WEEDTPU_FLEETSIM_RPS", 120.0)
        self.zipf_a = zipf_a if zipf_a is not None else \
            _env_float("WEEDTPU_FLEETSIM_ZIPF_A", 1.1)
        seed = seed if seed is not None else \
            _env_int("WEEDTPU_FLEETSIM_SEED", 42)
        # per-response artificial service delay: models real scrape RTT
        # so fan-out pool sizing shows up in aggregator tick wall time
        self.response_delay = response_delay if response_delay \
            is not None else _env_float("WEEDTPU_FLEETSIM_DELAY_MS",
                                        0.0) / 1000.0
        self.rng = random.Random(seed)
        self.t0 = time.time()
        self.diurnal_period = 600.0  # a "day" compressed to 10 minutes
        self.fill_bps = 2e6
        self.flash_nodes: set[int] = set()
        self.flash_until = 0.0
        self.flash_mult = 8.0
        self.nodes: dict[int, _VNode] = {}
        self._next_idx = 0
        self._next_vid = 1
        self._zipf_cache: dict[int, tuple[float, ...]] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._session = None  # aiohttp session, created on the sim loop
        self._hb_sem: asyncio.Semaphore | None = None
        self._lock = threading.Lock()

    # -- workload helpers -------------------------------------------------

    def zipf_weights(self, n: int) -> tuple[float, ...]:
        w = self._zipf_cache.get(n)
        if w is None:
            raw = [1.0 / (r ** self.zipf_a) for r in range(1, n + 1)]
            s = sum(raw) or 1.0
            w = self._zipf_cache[n] = tuple(v / s for v in raw)
        return w

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "FleetSim":
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        name="fleetsim", daemon=True)
        self._thread.start()
        self._call(self._start_all(self.n_nodes))
        return self

    def stop(self) -> None:
        if self._loop is None:
            return
        try:
            self._call(self._stop_all())
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(5.0)
            self._loop.close()
            self._loop = None

    def _call(self, coro, timeout: float = 60.0):
        return asyncio.run_coroutine_threadsafe(
            coro, self._loop).result(timeout)

    async def _start_all(self, n: int) -> None:
        import aiohttp
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10),
                connector=aiohttp.TCPConnector(limit=64))
            self._hb_sem = asyncio.Semaphore(32)
        await asyncio.gather(*[self._spawn_node() for _ in range(n)])

    async def _spawn_node(self) -> _VNode:
        with self._lock:
            idx = self._next_idx
            self._next_idx += 1
            vids = list(range(self._next_vid,
                              self._next_vid + self.vols_per_node))
            self._next_vid += self.vols_per_node
        node = _VNode(self, idx, f"rack{idx % self.n_racks}", vids)
        node.server = await asyncio.start_server(
            node.handle, "127.0.0.1", 0)
        port = node.server.sockets[0].getsockname()[1]
        node.url = f"127.0.0.1:{port}"
        with self._lock:
            self.nodes[idx] = node
        node.hb_task = asyncio.get_running_loop().create_task(
            self._heartbeat_loop(node))
        return node

    async def _stop_node(self, node: _VNode) -> None:
        if node.hb_task is not None:
            node.hb_task.cancel()
        if node.server is not None:
            node.server.close()
            try:
                await node.server.wait_closed()
            except Exception:
                pass

    async def _stop_all(self) -> None:
        with self._lock:
            nodes = list(self.nodes.values())
            self.nodes.clear()
        await asyncio.gather(*[self._stop_node(n) for n in nodes],
                             return_exceptions=True)
        if self._session is not None:
            await self._session.close()
            self._session = None

    # -- heartbeats -------------------------------------------------------

    async def _beat_once(self, node: _VNode) -> bool:
        async with self._hb_sem:
            try:
                async with self._session.post(
                        f"{_tls_scheme()}://{self.master_url}/heartbeat",
                        json=node.heartbeat_body()) as r:
                    return r.status == 200
            except Exception as e:
                weedlog.V(1, "fleetsim").infof(
                    "heartbeat from %s failed: %s", node.url, e)
                return False

    async def _heartbeat_loop(self, node: _VNode) -> None:
        # stagger the fleet across the interval so the master sees a
        # steady arrival rate, not a thundering herd each period
        await asyncio.sleep(self.rng.random() * self.heartbeat_s)
        while True:
            if not node.failed:
                await self._beat_once(node)
            await asyncio.sleep(self.heartbeat_s)

    def beat_all(self) -> int:
        """One immediate heartbeat from every live node (deterministic
        registration for tests/bench).  Returns the success count."""
        async def _all():
            with self._lock:
                nodes = [n for n in self.nodes.values() if not n.failed]
            oks = await asyncio.gather(*[self._beat_once(n)
                                         for n in nodes])
            return sum(oks)
        return self._call(_all())

    # -- churn + failure injection ---------------------------------------

    def add_nodes(self, n: int) -> list[str]:
        """Join n new vnodes (listener + heartbeats); returns their urls."""
        async def _add():
            nodes = await asyncio.gather(*[self._spawn_node()
                                           for _ in range(n)])
            return [nd.url for nd in nodes]
        return self._call(_add())

    def stop_nodes(self, n: int) -> list[str]:
        """Leave churn: permanently stop the n most recently joined."""
        with self._lock:
            idxs = sorted(self.nodes)[-n:]
            victims = [self.nodes.pop(i) for i in idxs]
        async def _stop():
            await asyncio.gather(*[self._stop_node(v) for v in victims],
                                 return_exceptions=True)
        self._call(_stop())
        return [v.url for v in victims]

    def fail_rack(self, rack: str) -> list[str]:
        """Correlated failure: every vnode in the rack stops answering
        scrapes and heartbeating (connection drops, like a dead ToR)."""
        with self._lock:
            hit = [n for n in self.nodes.values() if n.rack == rack]
            for n in hit:
                n.failed = True
        return [n.url for n in hit]

    def recover_rack(self, rack: str) -> None:
        with self._lock:
            for n in self.nodes.values():
                if n.rack == rack:
                    n.failed = False

    def flash_crowd(self, frac: float = 0.05,
                    duration_s: float = 60.0) -> set[int]:
        """Make `frac` of the fleet suddenly hot with a fat latency tail."""
        with self._lock:
            idxs = sorted(self.nodes)
        k = max(1, int(len(idxs) * frac))
        self.flash_nodes = set(self.rng.sample(idxs, k))
        self.flash_until = time.time() + duration_s
        return set(self.flash_nodes)

    # -- views ------------------------------------------------------------

    def urls(self) -> list[str]:
        with self._lock:
            return [n.url for n in self.nodes.values()]

    def __len__(self) -> int:
        with self._lock:
            return len(self.nodes)


def main(argv: list[str] | None = None) -> int:
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m seaweedfs_tpu.maintenance.fleetsim "
              "<master host:port>", file=sys.stderr)
        return 2
    sim = FleetSim(argv[0]).start()
    print(f"fleetsim: {len(sim)} vnodes heartbeating to {argv[0]} "
          f"(Ctrl-C to stop)")
    try:
        while True:
            time.sleep(5)
    except KeyboardInterrupt:
        pass
    finally:
        sim.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
