"""Background scrubber: detect silent corruption before a client does.

One Scrubber runs on each volume server.  A full pass streams every store
volume and every EC volume at a bounded rate (WEEDTPU_SCRUB_MBPS):

- store volumes: each live needle is re-read and its CRC32C recomputed
  against the stored checksum (storage/needle.py crc32c), and the record's
  id is cross-checked against the index entry that routed us there — a
  bit flip in either the data or the header surfaces here instead of on a
  client read.

- EC volumes: RS(10,4) parity verification IS a batched GF(2^8) matmul,
  so each scrub window stacks the k data-shard stripes into one [k, W]
  matrix, recomputes parity through the SAME ops/dispatch backend seam
  the encoder uses (tpu / native / numpy all work), and compares against
  the stored parity shards — one codec dispatch per window.  A mismatch
  is localized to the single corrupt shard by a per-candidate consistency
  test on the mismatching byte columns (RS decodes column by column, so
  only those columns are re-derived, with the slow numpy reference code).

Corrupt EC ranges are quarantined on the owning EcVolume — reads of the
range reconstruct from the other shards instead of serving the bad bytes —
and every pass's verdicts are reported upstream to the master's repair
planner (maintenance/repair.py), which deletes the corrupt shard and
rebuilds it through the normal EC machinery.

The rate limit exists because scrub I/O competes with foreground reads on
the same spindles: bench.py gates foreground blob_read_rps at >= 0.95x
with the scrubber running.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

from seaweedfs_tpu.stats import metrics, netflow, trace
from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import layout

log = logging.getLogger("scrub")

DEFAULT_MBPS = 8.0          # WEEDTPU_SCRUB_MBPS: sustained scrub rate
DEFAULT_INTERVAL = 300.0    # WEEDTPU_SCRUB_INTERVAL: seconds between passes
DEFAULT_WINDOW = 1024 * 1024  # WEEDTPU_SCRUB_WINDOW: syndrome window bytes
# columns fed to the corrupt-shard localizer: RS is column-independent, so
# a handful of mismatching columns identify the shard as well as all of them
LOCALIZE_COLS = 1024


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class RateLimiter:
    """Byte-budget throttle: sustained `bytes_per_s` with a small burst
    allowance so per-needle accounting doesn't turn into thousands of
    sub-millisecond sleeps."""

    def __init__(self, bytes_per_s: float, burst_s: float = 0.25):
        self.rate = float(bytes_per_s)
        self.burst = burst_s
        self._next = time.monotonic()

    def set_rate(self, bytes_per_s: float) -> None:
        """Retarget the sustained rate live (a float store — atomic
        under the GIL; the scrub thread reads it per chunk, so a
        governor push takes effect mid-pass, not next pass)."""
        self.rate = float(bytes_per_s)

    def throttle(self, nbytes: int) -> None:
        # read the rate ONCE: set_rate() flips it from another thread,
        # and the zero-check must guard the same value we divide by
        rate = self.rate
        if rate <= 0 or nbytes <= 0:
            return
        now = time.monotonic()
        # credit at most `burst` seconds of idle time, then advance the
        # schedule by this chunk's transmit time at the target rate
        self._next = max(self._next, now - self.burst) + nbytes / rate
        delay = self._next - now
        if delay > 0:
            time.sleep(delay)


def localize_corrupt_shard(cols: np.ndarray, code=None) -> int | None:
    """Identify the single corrupt shard from the stored bytes at the
    mismatching byte columns.

    `cols` is [n, C] for the volume's code (RS by default; any alpha=1
    code with reconstruct_numpy + parity_matrix works — LRC does).  For
    each candidate shard, reconstruct it from the other n-1 and test
    whether the stripe becomes fully consistent (all m parity rows
    match a recompute from the data rows).  With one corrupt shard
    exactly one candidate passes: excluding the corrupt shard from the
    survivors yields a consistent stripe, while any other candidate
    either reconstructs from (or is checked against) the bad bytes.
    Returns None when zero or several candidates pass — more than one
    shard is corrupt in this window, or the stripe is degenerate."""
    from seaweedfs_tpu.models import rs
    from seaweedfs_tpu.ops import gf
    if code is None:
        code = rs.get_code(layout.DATA_SHARDS, layout.PARITY_SHARDS)
    passing: list[int] = []
    for cand in range(code.n):
        others = {i: cols[i] for i in range(code.n)
                  if i != cand}
        rec = code.reconstruct_numpy(others, wanted=[cand])[cand]
        rows = dict(others)
        rows[cand] = rec
        data = np.stack([rows[i] for i in range(code.k)])
        parity = gf.gf_matmul(code.parity_matrix, data)
        if all(np.array_equal(parity[r], rows[code.k + r])
               for r in range(code.m)):
            passing.append(cand)
            if len(passing) > 1:
                return None
    return passing[0] if len(passing) == 1 else None


def syndrome_scan(ev, codec=None, window: int | None = None,
                  limiter: RateLimiter | None = None,
                  shard_reader=None, stop: threading.Event | None = None,
                  stats: dict | None = None) -> list[dict]:
    """Walk an EcVolume's shard files window by window and verify parity.

    Each window reads the same [off, off+W) slice of every readable shard,
    recomputes parity from the k data rows in ONE dispatch through the
    ops/dispatch seam, and compares against the stored parity rows.
    Windows where any data shard (or every parity shard) is unreadable are
    skipped and counted — on a spread cluster each server only verifies
    what it can assemble locally unless a `shard_reader` is provided.

    Returns corrupt-range dicts {shard, offset, size, columns}; shard is
    -1 when the corruption could not be localized to one shard."""
    from seaweedfs_tpu.ops import codecs as _codecs
    from seaweedfs_tpu.ops import dispatch
    from seaweedfs_tpu.storage.ec import ec_files
    if codec is None:
        codec = ec_files._get_codec(tag=getattr(ev, "codec_tag", None))
    spec = getattr(ev, "spec", None) or _codecs.spec_of(codec)
    window = window or DEFAULT_WINDOW
    if spec.alpha > 1:
        # sub-packetized codewords are positionally blocked per alpha
        # bytes: parity only recomputes over alpha-aligned windows
        window = max(spec.alpha, window - window % spec.alpha)
    k, m = spec.k, spec.m
    out: list[dict] = []
    for off in range(0, ev.shard_size, window):
        if stop is not None and stop.is_set():
            break
        n = min(window, ev.shard_size - off)
        rows: dict[int, np.ndarray] = {}
        for sid in range(spec.n):
            data = ev._read_local(sid, off, n)
            if (data is None or len(data) != n) and shard_reader is not None:
                data = shard_reader(sid, off, n)
            if data is not None and len(data) == n:
                rows[sid] = np.frombuffer(data, dtype=np.uint8)
        got = sum(r.nbytes for r in rows.values())
        if stats is not None:
            stats["bytes"] = stats.get("bytes", 0) + got
        metrics.SCRUB_BYTES.labels("ec").inc(got)
        parity_have = {s - k: rows[s] for s in range(k, k + m) if s in rows}
        if any(i not in rows for i in range(k)) or not parity_have:
            if stats is not None:
                stats["windows_skipped"] = stats.get("windows_skipped", 0) + 1
            if limiter is not None:
                limiter.throttle(got)
            continue
        batch = np.stack([rows[i] for i in range(k)])
        with trace.span("scrub.syndrome", offset=off, bytes=batch.nbytes):
            masks = dispatch.parity_mismatch(codec, batch, parity_have)
        if stats is not None:
            stats["windows"] = stats.get("windows", 0) + 1
        if limiter is not None:
            limiter.throttle(got)
        mism = np.zeros(n, dtype=bool)
        for mask in masks.values():
            mism |= mask
        bad_cols = np.nonzero(mism)[0]
        if bad_cols.size == 0:
            continue
        shard = -1
        # single-byte-column localization needs columns to be
        # independent codewords: true for alpha=1 families only
        if len(rows) == spec.n and spec.alpha == 1:
            sel = bad_cols[:LOCALIZE_COLS]
            cols = np.stack([rows[i][sel]
                             for i in range(spec.n)])
            loc = localize_corrupt_shard(
                cols, code=getattr(codec, "code", None))
            if loc is not None:
                shard = loc
        out.append({"shard": shard, "offset": off, "size": n,
                    "columns": int(bad_cols.size)})
    return out


class Scrubber:
    """Rate-limited background scrub loop over one Store.

    `report(summary)` is invoked (on the scrub thread) after each full
    pass — the volume server wires it to POST /maintenance/scrub_report on
    the master.  `shard_reader_factory(vid)` optionally supplies a remote
    shard reader so syndrome windows missing local shards can still be
    verified (WEEDTPU_SCRUB_REMOTE=1); by default only locally-assembled
    windows are checked."""

    def __init__(self, store, *, mbps: float | None = None,
                 interval: float | None = None, window: int | None = None,
                 report=None, shard_reader_factory=None):
        self.store = store
        self.mbps = mbps if mbps is not None else \
            _env_float("WEEDTPU_SCRUB_MBPS", DEFAULT_MBPS)
        self.interval = interval if interval is not None else \
            _env_float("WEEDTPU_SCRUB_INTERVAL", DEFAULT_INTERVAL)
        self.window = window or int(_env_float("WEEDTPU_SCRUB_WINDOW",
                                               DEFAULT_WINDOW))
        self.report = report
        self.shard_reader_factory = shard_reader_factory
        # this node's CONFIGURED rate: governor pushes arrive as a
        # fraction of it (apply_governed_scale), so a node deliberately
        # configured slower than the fleet default is scaled, never
        # overridden upward to someone else's ceiling
        self.configured_mbps = self.mbps
        self.last_scrub = 0.0
        self.last_summary: dict = {}
        self._stop = threading.Event()
        self._mu = threading.Lock()  # serializes concurrent scrub_once
        self._thread: threading.Thread | None = None
        # the pass currently in flight keeps its limiter here so a
        # governor retune (set_mbps) lands mid-pass, not next pass
        self._limiter: RateLimiter | None = None
        # operator pause latch: an explicit operator {"mbps": 0} sticks
        # until an explicit operator resume — the governor's periodic
        # governed=True re-pushes must never silently un-pause a node
        # someone stopped mid-incident
        self.operator_paused = False

    def set_mbps(self, mbps: float, governed: bool = False) -> float:
        """Retune the sustained scrub rate (pushed via
        /admin/scrub_rate).  Applies to the active pass immediately and
        to every later pass.  ``0`` PAUSES scrubbing (the
        construction-time semantic): future passes skip and the active
        pass stops at its next volume boundary — the live limiter keeps
        its previous rate rather than taking 0, because a zero-rate
        RateLimiter means *unthrottled*, the exact opposite of an
        operator posting {"mbps": 0} mid-incident.  ``governed`` marks
        the interference governor's pushes: they respect an operator
        pause (no-op while latched) and never flip the latch; operator
        calls (governed=False) set it — 0 latches, >0 releases.
        Returns the rate in effect."""
        mbps = max(0.0, float(mbps))
        if governed:
            if self.operator_paused:
                return self.mbps  # the operator's stop wins
        else:
            self.operator_paused = mbps <= 0
            self.configured_mbps = mbps  # new operator baseline
        self.mbps = mbps
        lim = self._limiter
        if lim is not None and self.mbps > 0:
            lim.set_rate(self.mbps * 1e6)
        return self.mbps

    def apply_governed_scale(self, scale: float) -> float:
        """Governor seam: scale THIS node's configured rate by the
        fleet backoff fraction (0..1].  A node started with
        WEEDTPU_SCRUB_MBPS=2 in an 8-default fleet governs to 2 x scale
        — its deliberate config is scaled, never raised to the master's
        ceiling.  Respects the operator pause latch like any governed
        push."""
        scale = max(0.0, min(1.0, float(scale)))
        return self.set_mbps(self.configured_mbps * scale, governed=True)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Scrubber":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="scrubber", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                summary = self.scrub_once()
            except Exception:
                log.warning("scrub pass failed", exc_info=True)
                continue
            if self.report is not None:
                try:
                    self.report(summary)
                except Exception:
                    log.warning("scrub report failed", exc_info=True)

    # -- one pass ------------------------------------------------------

    def scrub_once(self) -> dict:
        """One full pass over every mounted volume; returns the summary
        that also goes upstream: {ts, bytes, volumes: {vid: verdict}}."""
        # every remote byte this pass pulls (peer shard reads for the
        # syndrome checks) books as class=scrub — the shard_reader
        # factory captures the ambient class right here on this thread
        if self.mbps <= 0:
            # paused (set_mbps(0) or WEEDTPU_SCRUB_MBPS=0): no pass
            return {"ts": time.time(), "bytes": 0, "volumes": {},
                    "paused": True}
        with self._mu, netflow.flow("scrub"), \
                trace.span("scrub.pass", parent=trace.new_root()) \
                as pass_span:
            limiter = RateLimiter(self.mbps * 1e6)
            self._limiter = limiter
            vols: dict[str, dict] = {}
            total = 0
            for loc in self.store.locations:
                for vid, v in list(loc.volumes.items()):
                    if self._stop.is_set() or self.mbps <= 0:
                        break
                    if getattr(v, "backend_kind", "") == "remote" or \
                            getattr(v, "staging", False):
                        continue  # remote-tier reads cost money; staged
                    try:
                        res = self._scrub_volume(vid, v, limiter)
                    except Exception as e:
                        res = {"kind": "normal", "error": str(e)}
                    vols[str(vid)] = res
                    total += res.get("bytes", 0)
                for vid, ev in list(loc.ec_volumes.items()):
                    if self._stop.is_set() or self.mbps <= 0:
                        break
                    try:
                        res = self._scrub_ec(vid, ev, limiter)
                    except Exception as e:
                        res = {"kind": "ec", "error": str(e)}
                    vols[str(vid)] = res
                    total += res.get("bytes", 0)
            pass_span.set(volumes=len(vols), bytes=total)
            self._limiter = None
            summary = {"ts": time.time(), "bytes": total, "volumes": vols}
            self.last_scrub = summary["ts"]
            self.last_summary = summary
            return summary

    def _scrub_volume(self, vid: int, v, limiter: RateLimiter) -> dict:
        res: dict = {"kind": "normal", "needles": 0, "bytes": 0,
                     "crc_mismatches": 0, "corrupt": []}
        for nid, (off, size) in list(v.nm.items()):
            if self._stop.is_set():
                break
            if not t.size_is_valid(size):
                continue
            ok = True
            try:
                n = v._read_at(off, size, verify_checksum=False)
                c = ndl.crc32c(n.data)
                ok = n.id == nid and \
                    n.checksum in (c, ndl.crc_legacy_value(c))
            except (ValueError, EOFError, OSError):
                ok = False
            nbytes = t.actual_size(size, v.version)
            res["needles"] += 1
            res["bytes"] += nbytes
            metrics.SCRUB_BYTES.labels("volume").inc(nbytes)
            if not ok:
                res["crc_mismatches"] += 1
                res["corrupt"].append({"needle": f"{nid:x}"})
                metrics.SCRUB_CORRUPTIONS.labels("needle").inc()
                log.warning("scrub: volume %d needle %x failed CRC "
                            "verification", vid, nid)
            limiter.throttle(nbytes)
        res["last_scrub"] = time.time()
        return res

    def _scrub_ec(self, vid: int, ev, limiter: RateLimiter) -> dict:
        res: dict = {"kind": "ec", "windows": 0, "windows_skipped": 0,
                     "bytes": 0}
        reader = None
        if self.shard_reader_factory is not None and \
                os.environ.get("WEEDTPU_SCRUB_REMOTE") == "1":
            reader = self.shard_reader_factory(vid)
        corrupt = syndrome_scan(ev, window=self.window, limiter=limiter,
                                shard_reader=reader, stop=self._stop,
                                stats=res)
        for c in corrupt:
            metrics.SCRUB_CORRUPTIONS.labels("ec_shard").inc()
            if c["shard"] >= 0:
                # never serve the bad bytes again: reads of this range
                # reconstruct from the other shards until the repair
                # planner rebuilds the shard (remount clears it)
                ev.quarantine_range(c["shard"], c["offset"], c["size"])
            log.warning("scrub: ec volume %d parity mismatch at "
                        "[%d, +%d) -> shard %s", vid, c["offset"],
                        c["size"], c["shard"] if c["shard"] >= 0
                        else "unlocalized")
        res["corrupt"] = corrupt
        res["quarantined"] = ev.quarantine_snapshot()
        res["last_scrub"] = time.time()
        return res
