"""Self-healing maintenance plane.

Three cooperating parts (see README "Self-healing"):

  scrub.py   rate-limited background walker on each volume server: verifies
             needle CRC32C on store volumes and runs batched GF(2^8)
             parity-syndrome checks on EC shards through the same
             ops/dispatch backend seam the encoder uses; corrupt ranges are
             quarantined locally and reported to the master.
  repair.py  the master folds heartbeat shard maps and scrub verdicts into
             a per-volume health ledger and drives the existing rebuild
             machinery automatically (token-bucket limited, per-node
             concurrency caps, exponential backoff, trace spans).
  faults.py  test-only fault injection (WEEDTPU_FAULTS / /admin/faults):
             flip bits, delete shards, delay peers — the heal loop is
             provable end-to-end in tests and bench.py.
"""
