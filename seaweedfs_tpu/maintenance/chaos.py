"""Chaos harness: mixed workloads under compound failures, provably.

The pieces met one at a time in single-scenario tests — faults.py
injection, the scrub/repair loop, tracing, SLO burn rates, the
resilience layer — but nothing proved the cluster survives *mixed
workloads under compound failures*.  This module is the shared driver
behind ``tests/test_chaos.py`` and the ``bench.py`` chaos section:

- :class:`ChaosCluster` — an in-process cluster (master(s) + volume
  servers + optional filer/s3/MQ brokers on one background asyncio
  loop) whose servers can be killed and restarted mid-flight on the
  same ports and directories, and whose raft leader can be failed over;
- :data:`WORKLOADS` — s3 multipart, filer streaming, degraded blob
  reads, MQ produce/consume; each writes real data, remembers digests,
  and verifies byte-identical readback through its own gateway path;
- :data:`FAULTS` — shard loss, bit rot (healed through scrub → repair),
  slow peer (hedged reads carry the day), node restart mid-repair,
  network partition, master failover;
- :func:`run_scenario` — prepare → EC-encode the data volumes → inject
  the fault (and drive the heal machinery it requires) → verify every
  byte → assert ``volume.fsck -json`` reports ``ok``.

Every scenario ends in the same two assertions — fsck-clean state and
byte-identical reads — because that is the only definition of
"survived" that matters.
"""

from __future__ import annotations

import asyncio
import hashlib
import io
import json
import re
import socket
import threading
import time
import os
import urllib.error
import urllib.parse
import urllib.request

from seaweedfs_tpu.maintenance import faults
from seaweedfs_tpu.storage.ec import layout as _eclayout

__all__ = ["ChaosCluster", "GeoCluster", "WORKLOADS", "FAULTS", "MATRIX",
           "run_scenario", "fsck_report", "encode_all_volumes"]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _req(url: str, method: str = "GET", data: bytes | None = None,
         headers: dict | None = None, timeout: float = 30.0):
    """-> (status, body, headers) without raising on HTTP errors."""
    r = urllib.request.Request(url, data=data, method=method,
                               headers=headers or {})
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


class ChaosCluster:
    """Master(s) + N volume servers (+ filer, s3, MQ brokers) on one
    asyncio loop in a daemon thread, with mid-flight restart support:
    every server can be stopped and a replacement started on the SAME
    port and directories, which is what "the node came back" means."""

    def __init__(self, tmp_path, n_volume_servers: int = 2,
                 n_masters: int = 1, with_filer: bool = True,
                 with_s3: bool = False, with_mq: bool = False,
                 replication: str = "000",
                 volume_size_limit: int = 64 * 1024 * 1024,
                 heartbeat_interval: float = 0.3,
                 racks: list[str] | None = None):
        self.tmp = tmp_path
        self.n = n_volume_servers
        # rack label per volume server (None = all on the default rack):
        # the rack-scoped chaos cells and the locality-aware repair
        # planner key off these
        self.racks = racks
        self.n_masters = n_masters
        self.with_filer = with_filer
        self.with_s3 = with_s3
        self.with_mq = with_mq
        self.replication = replication
        self.volume_size_limit = volume_size_limit
        self.heartbeat_interval = heartbeat_interval
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever,
                                       daemon=True)
        self.masters: list = []
        self.volume_servers: list = []
        self.vs_ports: list[int] = []
        self.filer = None
        self.s3 = None
        self.brokers: list = []

    # -- lifecycle -------------------------------------------------------

    def submit(self, coro, timeout: float = 120.0):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(
            timeout)

    @property
    def master_urls(self) -> str:
        return ",".join(m.url for m in self.masters if m is not None)

    def leader(self):
        live = [m for m in self.masters if m is not None]
        leaders = [m for m in live if m.is_leader]
        return leaders[0] if leaders else live[0]

    def start(self) -> "ChaosCluster":
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer
        self.thread.start()
        if self.n_masters > 1:
            ports = [free_port() for _ in range(self.n_masters)]
            peers = [f"127.0.0.1:{p}" for p in ports]
            self.masters = [
                MasterServer("127.0.0.1", p, peers=peers,
                             volume_size_limit=self.volume_size_limit,
                             default_replication=self.replication,
                             raft_state_dir=str(self.tmp / "raft"))
                for p in ports]
            for m in self.masters:
                self.submit(m.start())
            self._wait_leader()
        else:
            m = MasterServer("127.0.0.1", free_port(),
                             volume_size_limit=self.volume_size_limit,
                             default_replication=self.replication)
            self.masters = [m]
            self.submit(m.start())
        for i in range(self.n):
            d = self.tmp / f"vs{i}"
            d.mkdir(exist_ok=True)
            self.vs_ports.append(free_port())
            self.volume_servers.append(None)
            self._start_volume_server(i)
        if self.with_filer:
            from seaweedfs_tpu.server.filer_server import FilerServer
            self.filer = FilerServer(
                self.leader().url, port=free_port(),
                data_dir=str(self.tmp / "filer"))
            self.submit(self.filer.start())
        if self.with_s3:
            from seaweedfs_tpu.s3.s3api_server import S3ApiServer
            self.s3 = S3ApiServer(self.filer.url, port=free_port(),
                                  master_url=self.leader().url)
            self.submit(self.s3.start())
        if self.with_mq:
            from seaweedfs_tpu.mq.broker import BrokerServer
            self.brokers = [BrokerServer(self.leader().url,
                                         port=free_port(),
                                         filer_url=self.filer.url,
                                         peer_refresh=0.5)
                            for _ in range(2)]
            for b in self.brokers:
                self.submit(b.start())
            time.sleep(1.0)  # brokers discover each other
        return self

    def stop(self) -> None:
        for b in self.brokers:
            try:
                self.submit(b.stop())
            except Exception:
                pass
        for srv in (self.s3, self.filer):
            if srv is not None:
                try:
                    self.submit(srv.stop())
                except Exception:
                    pass
        for vs in self.volume_servers:
            if vs is not None:
                try:
                    self.submit(vs.stop())
                except Exception:
                    pass
        for m in self.masters:
            if m is not None:
                try:
                    self.submit(m.stop())
                except Exception:
                    pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(5)
        faults.clear_net()

    def _wait_leader(self, timeout: float = 20.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            live = [m for m in self.masters if m is not None]
            leaders = [m for m in live if m.is_leader]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.05)
        raise TimeoutError("no single raft leader elected")

    def wait_heartbeats(self, timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        want = sum(1 for vs in self.volume_servers if vs is not None)
        while time.time() < deadline:
            if len(self.leader().topo.nodes) >= want:
                return
            time.sleep(0.05)
        raise TimeoutError("volume servers did not register")

    # -- process faults --------------------------------------------------

    def _start_volume_server(self, i: int) -> None:
        from seaweedfs_tpu.server.volume_server import VolumeServer
        rack = self.racks[i] if self.racks else ""
        vs = VolumeServer([str(self.tmp / f"vs{i}")], self.master_urls,
                          "127.0.0.1", self.vs_ports[i], max_volumes=20,
                          heartbeat_interval=self.heartbeat_interval,
                          rack=rack)
        self.submit(vs.start())
        self.volume_servers[i] = vs

    def stop_volume_server(self, i: int) -> None:
        vs = self.volume_servers[i]
        if vs is not None:
            self.submit(vs.stop())
            self.volume_servers[i] = None

    def restart_volume_server(self, i: int, downtime: float = 0.0) -> None:
        """Kill volume server `i` mid-flight and boot a replacement on
        the same port and directories after `downtime` seconds."""
        self.stop_volume_server(i)
        if downtime > 0:
            time.sleep(downtime)
        # the port may linger in TIME_WAIT for a beat after the runner
        # closes; retry the bind briefly rather than flaking
        deadline = time.time() + 10.0
        while True:
            try:
                self._start_volume_server(i)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        # the node is demonstrably back: close its (process-global)
        # circuit breaker instead of waiting out the half-open cooldown
        from seaweedfs_tpu.utils import resilience
        resilience.breaker_for(self.volume_servers[i].url).record(True)

    def fail_over_master(self) -> None:
        """Kill the raft leader; wait for a follower to take over; point
        the in-process gateways (filer/s3/brokers hold one static master
        URL, as a statically-configured deployment would until its
        config management catches up) at the new leader."""
        assert self.n_masters > 1, "failover needs a raft master group"
        old = self.leader()
        idx = self.masters.index(old)
        self.submit(old.stop())
        self.masters[idx] = None
        new = self._wait_leader()
        for srv in [self.filer, self.s3] + self.brokers:
            if srv is not None:
                srv.master_url = new.url
        # volume servers rotate on their own via the heartbeat loop's
        # master-list fallback; give them a beat to find the new leader
        self.wait_heartbeats(timeout=15.0)
        # the gateways re-register on their own cadence; the new
        # leader's member registry starts empty, and shell helpers
        # (find_filer) need it populated
        if self.filer is not None:
            deadline = time.time() + 20.0
            while time.time() < deadline:
                if new.cluster_members.get("filer"):
                    break
                time.sleep(0.2)

    # -- helpers ---------------------------------------------------------

    def client(self):
        from seaweedfs_tpu.client import WeedClient
        return WeedClient(self.master_urls)

    def shell_env(self):
        from seaweedfs_tpu.shell.commands import CommandEnv
        return CommandEnv(self.leader().url)

    def drive_repair(self, wait: bool = True, timeout: float = 120.0):
        """One deterministic repair-planner tick on the leader."""
        body = json.dumps({"wait": wait}).encode()
        st, out, _ = _req(
            f"http://{self.leader().url}/maintenance/tick",
            method="POST", data=body,
            headers={"Content-Type": "application/json"},
            timeout=timeout)
        assert st == 200, out
        return json.loads(out)

    def scrub_all(self) -> None:
        """One scrub pass on every live volume server (reports verdicts
        to the master's ledger).  Remote-shard verification is forced on
        for the pass: chaos clusters spread shards across nodes, and a
        local-only syndrome scan would skip every window."""
        import os
        prev = os.environ.get("WEEDTPU_SCRUB_REMOTE")
        os.environ["WEEDTPU_SCRUB_REMOTE"] = "1"
        try:
            for vs in self.volume_servers:
                if vs is None:
                    continue
                st, out, _ = _req(
                    f"http://{vs.url}/admin/scrub", method="POST",
                    data=b"{}",
                    headers={"Content-Type": "application/json"},
                    timeout=120.0)
                assert st == 200, out
        finally:
            if prev is None:
                os.environ.pop("WEEDTPU_SCRUB_REMOTE", None)
            else:
                os.environ["WEEDTPU_SCRUB_REMOTE"] = prev


class GeoCluster:
    """Two independent regions — each a full master + volume server +
    filer cluster — linked by a bidirectional FilerSync, all on one
    asyncio loop in a daemon thread.  The geo-observatory test/chaos
    harness: every node carries its region tag (trace spans, fault
    identities), the masters are cross-registered as ``peer_master`` so
    /cluster/trace federates across the WAN, and region-scoped faults
    (:func:`partition`, :func:`wan_latency`) cut or slow exactly the
    cross-region links while intra-region traffic runs clean."""

    def __init__(self, tmp_path, region_a: str = "a", region_b: str = "b",
                 sync_prefix: str = "/",
                 volume_size_limit: int = 64 * 1024 * 1024,
                 heartbeat_interval: float = 0.3):
        self.tmp = tmp_path
        self.region_names = (region_a, region_b)
        self.sync_prefix = sync_prefix
        self.volume_size_limit = volume_size_limit
        self.heartbeat_interval = heartbeat_interval
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever,
                                       daemon=True)
        # region name -> {"master": ..., "vs": ..., "filer": ...}
        self.regions: dict[str, dict] = {}
        self.sync = None

    def submit(self, coro, timeout: float = 120.0):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(
            timeout)

    def master(self, region: str):
        return self.regions[region]["master"]

    def filer(self, region: str):
        return self.regions[region]["filer"]

    def start(self) -> "GeoCluster":
        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer
        self.thread.start()
        for name in self.region_names:
            master = MasterServer(
                "127.0.0.1", free_port(),
                volume_size_limit=self.volume_size_limit, region=name)
            self.submit(master.start())
            d = self.tmp / f"geo_{name}_vs"
            d.mkdir(exist_ok=True)
            vs = VolumeServer([str(d)], master.url, "127.0.0.1",
                              free_port(), max_volumes=20,
                              heartbeat_interval=self.heartbeat_interval)
            self.submit(vs.start())
            # the VS has no region ctor knob; tag it for fault matching
            faults.register_region(vs.url, name)
            filer = FilerServer(master.url, port=free_port(),
                                data_dir=str(self.tmp / f"geo_{name}_f"),
                                region=name)
            self.submit(filer.start())
            self.regions[name] = {"master": master, "vs": vs,
                                  "filer": filer}
        # cross-register the masters so trace federation can hop regions
        a, b = self.region_names
        for me, other in ((a, b), (b, a)):
            st, out, _ = _req(
                f"http://{self.master(other).url}/cluster/register",
                method="POST",
                data=json.dumps({"type": "peer_master",
                                 "address": self.master(me).url}).encode(),
                headers={"Content-Type": "application/json"})
            assert st == 200, out
        from seaweedfs_tpu.replication.filer_sync import FilerSync
        self.sync = FilerSync(
            self.filer(a).url, self.filer(b).url, prefix=self.sync_prefix,
            offset_path=str(self.tmp / "geo_offsets.json"),
            region_a=a, region_b=b)
        self.sync.start()
        return self

    def stop(self) -> None:
        if self.sync is not None:
            try:
                self.sync.stop()
            except Exception:
                pass
        for reg in self.regions.values():
            for key in ("filer", "vs", "master"):
                try:
                    self.submit(reg[key].stop())
                except Exception:
                    pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(5)
        faults.clear_net()

    # -- WAN faults ------------------------------------------------------

    def partition(self) -> None:
        """Cut every cross-region link (both directions)."""
        a, b = self.region_names
        faults.add_partition(f"region:{a}", f"region:{b}")
        faults.add_partition(f"region:{b}", f"region:{a}")

    def heal(self) -> None:
        a, b = self.region_names
        faults.remove_partition(f"region:{a}", f"region:{b}")
        # the WAN is demonstrably back: close the (process-global)
        # breakers on every node instead of waiting out half-open
        from seaweedfs_tpu.utils import resilience
        for reg in self.regions.values():
            for key in ("filer", "vs", "master"):
                resilience.breaker_for(reg[key].url).record(True)

    def wan_latency(self, ms: float, jitter_ms: float = 0.0) -> None:
        """Charge every boundary-crossing dial `ms` (±jitter) extra."""
        a, b = self.region_names
        faults.set_wan_latency(a, b, ms, jitter_ms)

    # -- data helpers ----------------------------------------------------

    def write(self, region: str, path: str, data: bytes) -> None:
        st, out, _ = _req(f"http://{self.filer(region).url}{path}",
                          method="PUT", data=data)
        assert st in (200, 201), (region, path, out)

    def read(self, region: str, path: str) -> tuple[int, bytes]:
        st, body, _ = _req(f"http://{self.filer(region).url}{path}")
        return st, body

    def digests(self, prefix: str | None = None) -> tuple[str, str]:
        """(digest_a, digest_b) straight off the filers' meta endpoint."""
        out = []
        for name in self.region_names:
            st, body, _ = _req(
                f"http://{self.filer(name).url}/__meta__/digest?"
                + urllib.parse.urlencode(
                    {"prefix": prefix or self.sync_prefix}))
            assert st == 200, body
            out.append(json.loads(body)["digest"])
        return tuple(out)


def encode_all_volumes(c: ChaosCluster) -> list[int]:
    """EC-encode every data volume through the shell (lock, encode,
    unlock) so shard/scrub/repair faults apply to the workload's bytes
    — collection-scoped volumes (s3 buckets) included.  Returns the
    encoded vids."""
    from seaweedfs_tpu.shell.commands import run_command
    with c.leader().topo._lock:
        vols = sorted({(vid, v.collection)
                       for node in c.leader().topo.nodes.values()
                       for vid, v in node.volumes.items()})
    env = c.shell_env()
    out = io.StringIO()
    run_command(env, "lock", out)
    try:
        for vid, collection in vols:
            cmd = f"ec.encode -volumeId {vid}"
            if collection:
                cmd += f" -collection {collection}"
            run_command(env, cmd, out)
    finally:
        run_command(env, "unlock", out)
    time.sleep(2 * c.heartbeat_interval + 0.2)  # shard heartbeats land
    return [vid for vid, _ in vols]


def hedge_ratio_arms(c: ChaosCluster, blobs: dict, vid: int,
                     delay_s: float = 0.35) -> tuple[float, float]:
    """Deterministic slow-peer hedging measurement.

    Topology: all 14 shards of `vid` generated on node 0, then shards
    0+1 moved to node 1 (which answers shard reads `delay_s` late) and
    the normal volume unmounted — every GET against node 0 is a
    degraded read whose missing interval lives behind the slow peer,
    while 12 local survivors make reconstruction cheap.  Returns
    (p99_hedge_off_s, p99_hedge_on_s): without hedging each read waits
    out the slow peer; with it, reconstruction wins after the hedge
    delay.  `blobs` maps fid -> expected bytes (every read is
    byte-verified)."""
    import os
    vs0, vs1 = c.volume_servers[0], c.volume_servers[1]
    hdrs = {"Content-Type": "application/json"}

    def post(url, path, body, timeout=300.0):
        st, out, _ = _req(f"http://{url}{path}", method="POST",
                          data=json.dumps(body).encode(), headers=hdrs,
                          timeout=timeout)
        assert st == 200, (path, out)

    post(vs0.url, "/admin/ec/generate", {"volume": vid})
    post(vs0.url, "/admin/ec/mount", {"volume": vid})
    post(vs1.url, "/admin/ec/copy", {"volume": vid, "source": vs0.url,
                                     "shards": [0, 1]})
    post(vs1.url, "/admin/ec/mount", {"volume": vid})
    post(vs0.url, "/admin/ec/delete_shards", {"volume": vid,
                                              "shards": [0, 1]})
    post(vs0.url, "/admin/volume/unmount", {"volume": vid})
    time.sleep(2 * c.heartbeat_interval + 0.2)
    vs1._fault_delay_shard_read = delay_s

    def p99(samples):
        s = sorted(samples)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    def measure() -> float:
        # flush the reconstruction LRU so the previous arm's decodes
        # can't serve this one
        ev = vs0.store.get_ec_volume(vid)
        with ev._recon_lock:
            ev._recon_cache.clear()
            ev._recon_cache_bytes = 0
        lat = []
        for fid, want in blobs.items():
            t0 = time.monotonic()
            st, got, _ = _req(f"http://{vs0.url}/{fid}", timeout=60.0)
            lat.append(time.monotonic() - t0)
            assert st == 200 and got == want, fid
        return p99(lat)

    saved = {k: os.environ.get(k)
             for k in ("WEEDTPU_HEDGE_PCT", "WEEDTPU_HEDGE_MAX_MS")}
    try:
        os.environ["WEEDTPU_HEDGE_PCT"] = "0"
        p_off = measure()
        os.environ["WEEDTPU_HEDGE_PCT"] = "99"
        os.environ["WEEDTPU_HEDGE_MAX_MS"] = "100"
        p_on = measure()
    finally:
        vs1._fault_delay_shard_read = 0.0
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return p_off, p_on


def fsck_report(c: ChaosCluster) -> dict:
    """volume.fsck -json via the shell; returns the parsed report."""
    from seaweedfs_tpu.shell.commands import run_command
    env = c.shell_env()
    out = io.StringIO()
    run_command(env, "lock", out)
    out = io.StringIO()
    try:
        rc = run_command(env, "volume.fsck -json", out)
    finally:
        run_command(env, "unlock", io.StringIO())
    rep = json.loads(out.getvalue())
    rep["rc"] = rc
    return rep


# -- workloads -----------------------------------------------------------
#
# Each workload is (prepare, verify): prepare writes real data through
# its gateway path and returns opaque state with content digests;
# verify reads everything back through the same path and asserts
# byte-identity.  Workloads keep payloads small (hundreds of KB) so a
# 24-cell matrix stays runnable, but always span multiple blocks /
# chunks / parts so the interesting code paths engage.

def _digest(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _wl_blob_prepare(c: ChaosCluster) -> dict:
    import numpy as np
    client = c.client()
    rng = np.random.default_rng(0xC0FFEE)
    blobs = {}
    for i in range(40):
        data = rng.integers(0, 256, int(rng.integers(2_000, 60_000)),
                            dtype=np.uint8).tobytes()
        fid = client.upload(data, name=f"chaos{i}.bin")
        blobs[fid] = _digest(data)
    return {"blobs": blobs}


def _wl_blob_verify(c: ChaosCluster, state: dict) -> None:
    client = c.client()
    for fid, want in state["blobs"].items():
        got = client.download(fid)
        assert _digest(got) == want, f"blob {fid} bytes changed"


def _wl_filer_prepare(c: ChaosCluster) -> dict:
    import numpy as np
    rng = np.random.default_rng(0xF11E)
    files = {}
    for i in range(3):
        data = rng.integers(0, 256, 600_000 + i * 100_000,
                            dtype=np.uint8).tobytes()
        st, out, _ = _req(f"http://{c.filer.url}/chaos/f{i}.bin",
                          method="PUT", data=data)
        assert st in (200, 201), out
        files[f"/chaos/f{i}.bin"] = data
    return {"files": files}


def _wl_filer_verify(c: ChaosCluster, state: dict) -> None:
    for path, want in state["files"].items():
        st, body, _ = _req(f"http://{c.filer.url}{path}")
        assert st == 200, f"filer GET {path}: HTTP {st}"
        assert body == want, f"filer {path} bytes changed"
        # a mid-file range must slice out of the same bytes (streamed
        # range reads exercise the chunk-fetch path differently)
        st, part, _ = _req(f"http://{c.filer.url}{path}",
                           headers={"Range": "bytes=100000-100999"})
        assert st == 206 and part == want[100000:101000], \
            f"filer {path} range bytes changed"


def _wl_s3_prepare(c: ChaosCluster) -> dict:
    import numpy as np
    rng = np.random.default_rng(0x53)
    base = f"http://{c.s3.url}"
    st, out, _ = _req(f"{base}/chaos-bucket", method="PUT")
    assert st in (200, 409), out
    # multipart upload: two parts crossing the chunk boundary
    st, body, _ = _req(f"{base}/chaos-bucket/big.bin?uploads",
                       method="POST")
    assert st == 200, body
    m = re.search(rb"<UploadId>([^<]+)</UploadId>", body)
    assert m, body
    upload_id = m.group(1).decode()
    parts = [rng.integers(0, 256, 400_000, dtype=np.uint8).tobytes(),
             rng.integers(0, 256, 300_000, dtype=np.uint8).tobytes()]
    etags = []
    for n, part in enumerate(parts, start=1):
        st, out, hdrs = _req(
            f"{base}/chaos-bucket/big.bin?partNumber={n}"
            f"&uploadId={urllib.parse.quote(upload_id)}",
            method="PUT", data=part)
        assert st == 200, out
        etags.append(hdrs.get("ETag", ""))
    complete = "".join(
        f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
        for n, e in enumerate(etags, start=1))
    st, out, _ = _req(
        f"{base}/chaos-bucket/big.bin"
        f"?uploadId={urllib.parse.quote(upload_id)}",
        method="POST",
        data=f"<CompleteMultipartUpload>{complete}"
             "</CompleteMultipartUpload>".encode())
    assert st == 200, out
    whole = b"".join(parts)
    return {"key": "/chaos-bucket/big.bin", "content": whole}


def _wl_s3_verify(c: ChaosCluster, state: dict) -> None:
    base = f"http://{c.s3.url}"
    st, body, _ = _req(f"{base}{state['key']}")
    assert st == 200, f"s3 GET: HTTP {st}"
    assert body == state["content"], "s3 object bytes changed"
    # range across the part boundary
    lo = 399_995
    st, part, _ = _req(f"{base}{state['key']}",
                       headers={"Range": f"bytes={lo}-{lo + 9}"})
    assert st == 206 and part == state["content"][lo:lo + 10], \
        "s3 range bytes changed"


def _wl_mq_prepare(c: ChaosCluster) -> dict:
    from seaweedfs_tpu.mq.client import MQClient
    client = MQClient([b.url for b in c.brokers])
    client.configure("chaos.events", partition_count=2)
    sent = []
    for i in range(30):
        payload = f"chaos-payload-{i:04d}".encode() * 20
        client.publish("chaos.events", payload, key=f"k{i}".encode())
        sent.append(payload)
    # drain RAM tails to filer-backed segments so the messages live on
    # the storage the faults attack
    for b in c.brokers:
        st, out, _ = _req(f"http://{b.url}/flush", method="POST",
                          data=b"{}")
        assert st == 200, out
    return {"sent": sorted(_digest(p) for p in sent)}


def _wl_mq_verify(c: ChaosCluster, state: dict) -> None:
    from seaweedfs_tpu.mq.client import MQClient
    client = MQClient([b.url for b in c.brokers])
    client.refresh()
    got = []
    for pi in range(2):
        offset = 0
        while True:
            msgs, nxt = client.fetch("chaos.events", pi, offset)
            if not msgs:
                break
            # fetch returns decoded str values for text payloads
            got.extend(m["value"].encode()
                       if isinstance(m["value"], str) else m["value"]
                       for m in msgs)
            offset = nxt
    assert sorted(_digest(v) for v in got) == state["sent"], \
        f"MQ lost/changed messages ({len(got)} read)"


WORKLOADS = {
    "s3_multipart": (_wl_s3_prepare, _wl_s3_verify),
    "filer_stream": (_wl_filer_prepare, _wl_filer_verify),
    "degraded_read": (_wl_blob_prepare, _wl_blob_verify),
    "mq": (_wl_mq_prepare, _wl_mq_verify),
}


# -- faults --------------------------------------------------------------
#
# Each fault takes the running cluster, injects its failure against the
# (now EC-encoded) data volumes, drives whatever heal machinery the
# failure requires, and returns with the cluster in the state verify()
# must survive.  "Survive" sometimes means "heal completed" (bit rot,
# shard loss) and sometimes "degraded but correct" (slow peer,
# partition) — both end fsck-clean.

def _ec_vids_on(vs) -> list[int]:
    return sorted({vid for loc in vs.store.locations
                   for vid in loc.ec_volumes})


def heal_until_clean(c: ChaosCluster, timeout: float = 120.0) -> None:
    """Drive repair-planner ticks until every volume's ledger state is
    healthy (repairs are token-bucketed, so one tick may not cover all
    damaged volumes)."""
    deadline = time.monotonic() + timeout
    led = {}
    while time.monotonic() < deadline:
        c.drive_repair(wait=True)
        led = c.leader().maintenance.ledger()
        if led and all(i["state"] == "healthy" for i in led.values()):
            return
        time.sleep(0.5)
    states = {str(v): i["state"] for v, i in led.items()
              if i["state"] != "healthy"}
    raise AssertionError(f"cluster did not heal in {timeout}s: {states}")


def _fault_shard_loss(c: ChaosCluster, ctx: dict) -> None:
    """Delete two shards of every EC volume on one node, then repair."""
    vs = c.volume_servers[0]
    for vid in _ec_vids_on(vs):
        ev = vs.store.get_ec_volume(vid)
        drop = ev.shard_ids()[:2]
        for sid in drop:
            faults.delete_shard(vs.store, vid, sid)
    c.submit(vs._heartbeat_once())
    time.sleep(2 * c.heartbeat_interval)
    heal_until_clean(c)


def _fault_bit_rot(c: ChaosCluster, ctx: dict) -> None:
    """Flip one bit in one shard per EC volume; scrub localizes it,
    repair purges + rebuilds — the full silent-corruption heal path."""
    vs = c.volume_servers[0]
    for vid in _ec_vids_on(vs):
        ev = vs.store.get_ec_volume(vid)
        sid = ev.shard_ids()[0]
        faults.flip_bit(vs.store, vid, sid, offset=4096)
    c.scrub_all()
    heal_until_clean(c)
    # the rebuild remounted shards; re-scrub to confirm clean + refresh
    # the ledger verdicts
    c.scrub_all()


def _fault_slow_peer(c: ChaosCluster, ctx: dict) -> None:
    """One node serves shard reads 400ms late while shards are missing
    locally on its peer — degraded reads must stay correct (and the
    hedged-read path keeps them fast; timing asserted in bench/tests).
    The delay is lifted afterwards; nothing to heal."""
    slow = c.volume_servers[1]
    victim = c.volume_servers[0]
    for vid in _ec_vids_on(victim):
        ev = victim.store.get_ec_volume(vid)
        for sid in ev.shard_ids()[:2]:
            faults.delete_shard(victim.store, vid, sid)
    c.submit(victim._heartbeat_once())
    slow._fault_delay_shard_read = 0.4
    ctx["undo"] = lambda: setattr(slow, "_fault_delay_shard_read", 0.0)
    ctx["verify_during_fault"] = True


def _fault_restart_mid_repair(c: ChaosCluster, ctx: dict) -> None:
    """Lose shards on node 0, start the repair, and bounce node 1 while
    the repair is in flight; repair must converge once it returns."""
    vs = c.volume_servers[0]
    for vid in _ec_vids_on(vs):
        ev = vs.store.get_ec_volume(vid)
        for sid in ev.shard_ids()[:2]:
            faults.delete_shard(vs.store, vid, sid)
    c.submit(vs._heartbeat_once())
    time.sleep(2 * c.heartbeat_interval)
    c.drive_repair(wait=False)  # launch, don't wait
    c.restart_volume_server(1, downtime=0.3)
    # let the in-flight repairs finish; some failed against the
    # restarting node and went to backoff — further ticks pick them up
    heal_until_clean(c, timeout=90.0)


def repair_recv_bytes() -> float:
    """Process-wide class=repair received bytes (stats/netflow): the
    fleet-scale repair-traffic number the reduced-read path minimizes."""
    from seaweedfs_tpu.stats import netflow
    return netflow.class_total("recv", "repair")


def shards_on_rack(c: ChaosCluster, vid: int, rack: str) -> list[tuple]:
    """(server, shard_id) pairs of `vid`'s shards living on `rack`."""
    out = []
    for i, vs in enumerate(c.volume_servers):
        if vs is None or (c.racks[i] if c.racks else "") != rack:
            continue
        ev = vs.store.get_ec_volume(vid)
        if ev is not None:
            out.extend((vs, sid) for sid in ev.shard_ids())
    return out


def _fault_rack_loss(c: ChaosCluster, ctx: dict) -> None:
    """Correlated rack-scoped loss: two shards of every EC volume die
    TOGETHER on one rack (the mass-restart / rack-power shape of the
    1309.0186 study), then the planner heals.  On a rack-labeled
    cluster the survivor selection must route repair pulls same-rack
    first and keep cross-rack bytes inside the budget; on a label-less
    cluster this degrades to correlated two-shard loss."""
    victim_rack = (c.racks[-1] if c.racks else "")
    vids = sorted({vid for vs in c.volume_servers if vs is not None
                   for vid in _ec_vids_on(vs)})
    for vid in vids:
        for svr, sid in shards_on_rack(c, vid, victim_rack)[:2]:
            faults.delete_shard(svr.store, vid, sid)
    for vs in c.volume_servers:
        if vs is not None:
            c.submit(vs._heartbeat_once())
    time.sleep(2 * c.heartbeat_interval)
    heal_until_clean(c)


def _fault_helper_death_mid_rebuild(c: ChaosCluster, ctx: dict) -> None:
    """Lose shards on node 0, launch the repair, and kill the node most
    likely serving partial-sum fetches while the rebuild is in flight.
    The reduced path must re-plan around the dead helper (or back off
    and converge on a later tick), and no partial `.ecXX.tmp` may
    survive anywhere."""
    vs = c.volume_servers[0]
    for vid in _ec_vids_on(vs):
        ev = vs.store.get_ec_volume(vid)
        for sid in ev.shard_ids()[:2]:
            faults.delete_shard(vs.store, vid, sid)
    c.submit(vs._heartbeat_once())
    time.sleep(2 * c.heartbeat_interval)
    c.drive_repair(wait=False)  # launch, don't wait
    c.restart_volume_server(1, downtime=0.4)
    heal_until_clean(c, timeout=90.0)
    # a helper death mid-transfer must never leave a partial shard
    leftovers = [str(p) for i in range(c.n)
                 for p in (c.tmp / f"vs{i}").glob("*.ec??.tmp")]
    assert not leftovers, f"partial shards left behind: {leftovers}"


def _fault_convert_mid_failure(c: ChaosCluster, ctx: dict) -> None:
    """Kill a volume server mid-fleet-conversion: the scheduler's node
    call dies, its volumes are RE-QUEUED (never dropped), and once the
    node returns the conversion converges.  Clean-abort contract: the
    tmp+rename commit means a killed conversion can never leave a
    partial `.ecXX` set visible — after convergence every converted
    volume has all 14 shards, and run_scenario's byte-identical
    readback + fsck close the loop."""
    import asyncio as _aio
    vs = c.volume_servers[0]
    vids = sorted({vid for loc in vs.store.locations
                   for vid in loc.volumes})
    assert vids, "workload left no plain volumes to convert"
    for vid in vids:
        v = vs.store.get_volume(vid)
        if v is not None:
            v.nm.flush()
    leader = c.leader()
    sched = leader.convert
    sched.enqueue(vids)
    # fire the paced tick and kill the node while the batch is in flight
    fut = _aio.run_coroutine_threadsafe(sched.tick(), c.loop)
    c.restart_volume_server(0, downtime=0.5)
    try:
        fut.result(120)
    except Exception:
        pass  # the tick itself survives; failures land in the history
    st = sched.status()
    requeued = set(st["queued"]) | {int(v) for v in st["backoffs"]}
    converted_early = sched.converted
    if not converted_early:
        # the kill landed mid-conversion: every volume must be re-queued
        assert requeued.issuperset(vids), (requeued, vids)
    c.wait_heartbeats()
    # node is back: expire the backoffs and tick until the queue drains
    deadline = time.monotonic() + 90.0
    while time.monotonic() < deadline:
        sched._backoff = {v: (f, 0.0)
                          for v, (f, _) in sched._backoff.items()}
        _aio.run_coroutine_threadsafe(sched.tick(), c.loop).result(120)
        if not sched.queued and not sched.active:
            break
        time.sleep(0.3)
    assert not sched.queued, sched.status()
    vs = c.volume_servers[0]  # the restarted instance
    for vid in vids:
        v = vs.store.get_volume(vid)
        assert v is not None, vid
        shards = [i for i in range(_eclayout.TOTAL_SHARDS)
                  if os.path.exists(v._base + _eclayout.to_ext(i))]
        # all-or-nothing: a partial committed set would mean the
        # tmp+rename contract broke
        assert len(shards) == _eclayout.TOTAL_SHARDS, \
            f"volume {vid}: partial/absent shard set {shards}"
    time.sleep(2 * c.heartbeat_interval + 0.2)  # shard heartbeats land


def _fault_move_mid_failure(c: ChaosCluster, ctx: dict) -> None:
    """Kill the TARGET volume server mid-/admin/volume/move: the move
    must abort cleanly — 500 to the caller, no partial or staged state
    mounted on either side, the source thawed back to writable and
    still serving every byte — and the restarted target must boot with
    NO orphan files (its DiskLocation cleanup deletes crash leftovers).
    A re-run of the same move must then succeed: the abort left a
    re-runnable state, which is the whole contract."""
    import glob as _glob
    import threading as _threading
    src = c.volume_servers[0]
    dst = c.volume_servers[1]
    vids = sorted({vid for loc in src.store.locations
                   for vid in loc.volumes})
    assert vids, "workload left no plain volumes on node 0"
    vid = vids[0]
    body = json.dumps({"volume": vid, "target": dst.url}).encode()
    # stall the source's peer file pulls so the target is reliably
    # mid-transfer when it dies
    src._fault_delay_file_pull = 0.6
    result: dict = {}

    def mover():
        try:
            result["status"], result["body"], _ = _req(
                f"http://{src.url}/admin/volume/move", method="POST",
                data=body, headers={"Content-Type": "application/json"},
                timeout=120)
        except OSError as e:  # the source itself must not die
            result["error"] = str(e)

    t = _threading.Thread(target=mover, daemon=True)
    t.start()
    time.sleep(0.3)  # the target is now inside the staged pull
    c.restart_volume_server(1, downtime=0.5)
    t.join(120)
    src._fault_delay_file_pull = 0.0
    assert result.get("status") == 500, result  # clean abort, reported
    v = src.store.get_volume(vid)
    assert v is not None and not v.read_only  # source thawed + serving
    dst = c.volume_servers[1]  # the restarted instance
    assert dst.store.get_volume(vid) is None  # no half-copy mounted
    for vs in (src, dst):
        leftovers = [p for loc in vs.store.locations
                     for pat in ("*.cpd", "*.cpx", "*.staging",
                                 "*.cptail")
                     for p in _glob.glob(os.path.join(loc.directory,
                                                      pat))]
        assert not leftovers, f"orphan files after abort: {leftovers}"
    c.wait_heartbeats()
    # abort left a re-runnable state: the same move now completes
    status, out, _ = _req(
        f"http://{src.url}/admin/volume/move", method="POST",
        data=body, headers={"Content-Type": "application/json"},
        timeout=120)
    assert status == 200, out
    assert src.store.get_volume(vid) is None
    assert dst.store.get_volume(vid) is not None
    time.sleep(2 * c.heartbeat_interval + 0.2)  # topology settles


def _fault_partition(c: ChaosCluster, ctx: dict) -> None:
    """Partition every GATEWAY (client/shell/filer — and thereby s3 and
    MQ, which read through the filer) from node 1: reads must fail over
    to node 0, which reconstructs node 1's shards over the still-intact
    volume↔volume links.  Lifted before the final fsck (a partition
    heals; data never changed)."""
    target = c.volume_servers[1].url
    for src in ("client", "shell", "filer"):
        faults.add_partition(src, target)
    ctx["undo"] = lambda: faults.clear_net()
    ctx["verify_during_fault"] = True


def _fault_master_failover(c: ChaosCluster, ctx: dict) -> None:
    """Kill the raft leader; the cluster re-elects and serves on."""
    c.fail_over_master()


def _fault_noisy_neighbor(c: ChaosCluster, ctx: dict) -> None:
    """One abusive tenant hammers the s3 edge open-loop while a victim
    tenant keeps reading its object: per-tenant QoS admission must shed
    the abuser with 429s AND keep the victim error-free inside its
    latency bound — one tenant's abuse degrades into its own rejects,
    never into another tenant's SLO (429s are 4xx, so they cannot flip
    the 5xx-based availability SLO either).  The workload's own verify
    runs during the noise too (verify_during_fault), proving the
    scenario tenant is a second un-harmed victim.  Clusters without an
    s3 gateway get a temporary one for the fault's duration."""
    s3 = c.s3
    started = False
    if s3 is None:
        from seaweedfs_tpu.s3.s3api_server import S3ApiServer
        s3 = S3ApiServer(c.filer.url, port=free_port(),
                         master_url=c.leader().url)
        c.submit(s3.start())
        started = True
    prev = (s3.qos.total_rate, s3.qos.burst_s, dict(s3.qos.weights))
    # weighted admission: the victim (and the scenario workload's
    # bucket) carry heat-earned weight, the abuser rides the default —
    # unauthenticated tenants resolve to their bucket name
    s3.qos.configure(rate=200.0, burst_s=1.0,
                     weights={"victim-bucket": 4.0, "chaos-bucket": 4.0,
                              "default": 1.0})
    base = f"http://{s3.url}"
    for bucket in ("victim-bucket", "noisy-bucket"):
        st, out, _ = _req(f"{base}/{bucket}", method="PUT")
        assert st in (200, 409), out
    payload = os.urandom(64 * 1024)
    st, out, _ = _req(f"{base}/victim-bucket/slo.bin", method="PUT",
                      data=payload)
    assert st == 200, out
    st, out, _ = _req(f"{base}/noisy-bucket/spam.bin", method="PUT",
                      data=b"x" * 1024)
    assert st == 200, out

    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                _req(f"{base}/noisy-bucket/spam.bin", timeout=5)
            except OSError:
                pass

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(6)]
    for t in threads:
        t.start()

    def undo():
        stop.set()
        for t in threads:
            t.join(10)
        s3.qos.configure(rate=prev[0], burst_s=prev[1], weights=prev[2])
        if started:
            c.submit(s3.stop())

    ctx["undo"] = undo
    ctx["verify_during_fault"] = True

    # the edge is throttling the abuser: its shed count must be GROWING
    time.sleep(1.0)
    shed0 = s3.qos.shed_by_tenant.get("noisy-bucket", 0)
    time.sleep(1.5)
    abuser_shed = s3.qos.shed_by_tenant.get("noisy-bucket", 0)
    assert abuser_shed > shed0 and abuser_shed > 10, \
        f"abuser not throttled at the edge: shed {shed0}->{abuser_shed}"
    # the victim's SLO class under the noise: every read succeeds,
    # paced inside its admitted share, p99 bounded
    lat = []
    for _ in range(40):
        t0 = time.monotonic()
        st, body, _ = _req(f"{base}/victim-bucket/slo.bin", timeout=10)
        lat.append(time.monotonic() - t0)
        assert st == 200, f"victim read failed: HTTP {st}"
        assert body == payload, "victim bytes changed under noise"
        time.sleep(0.03)
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
    assert p99 < 2.0, f"victim p99 {p99:.3f}s out of SLO under noise"
    assert s3.qos.shed_by_tenant.get("victim-bucket", 0) == 0, \
        "victim tenant was shed — admission is not isolating tenants"


# faults that must see PLAIN volumes (their own conversion, or a
# volume move — both operate on .dat volumes): run_scenario must not
# pre-encode the workload's volumes for these
SELF_ENCODING_FAULTS = frozenset({"convert_mid_failure",
                                  "move_mid_failure"})

FAULTS = {
    "shard_loss": _fault_shard_loss,
    "convert_mid_failure": _fault_convert_mid_failure,
    "move_mid_failure": _fault_move_mid_failure,
    "bit_rot": _fault_bit_rot,
    "slow_peer": _fault_slow_peer,
    "restart_mid_repair": _fault_restart_mid_repair,
    "partition": _fault_partition,
    "master_failover": _fault_master_failover,
    "rack_loss": _fault_rack_loss,
    "helper_death_mid_rebuild": _fault_helper_death_mid_rebuild,
    "noisy_neighbor": _fault_noisy_neighbor,
}

MATRIX = [(w, f) for w in WORKLOADS for f in FAULTS]


def run_scenario(c: ChaosCluster, workload: str, fault: str,
                 encode: bool = True) -> dict:
    """One matrix cell: prepare the workload, EC-encode its volumes,
    inject the fault (driving any heal it needs), verify byte-identical
    readback, and assert fsck-clean end state.  Returns a small report
    with timings."""
    prepare, verify = WORKLOADS[workload]
    t0 = time.monotonic()
    state = prepare(c)
    if encode and fault not in SELF_ENCODING_FAULTS:
        encode_all_volumes(c)
    verify(c, state)  # the pre-fault baseline must hold before we break it
    ctx: dict = {}
    t1 = time.monotonic()
    FAULTS[fault](c, ctx)
    t2 = time.monotonic()
    try:
        verify(c, state)
    finally:
        undo = ctx.get("undo")
        if undo is not None:
            undo()
    if ctx.get("verify_during_fault"):
        # the fault was live during verify; verify once more healed
        verify(c, state)
    rep = fsck_report(c)
    assert rep.get("ok") is True, \
        f"fsck not clean after {workload}x{fault}: " \
        f"{json.dumps({k: v for k, v in rep.items() if k != 'volumes'})}"
    return {"workload": workload, "fault": fault,
            "prepare_s": round(t1 - t0, 3),
            "fault_s": round(t2 - t1, 3),
            "verify_s": round(time.monotonic() - t2, 3)}
