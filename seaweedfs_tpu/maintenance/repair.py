"""Automatic repair planner: the master's self-healing control loop.

The planner folds two signal streams into a per-volume health ledger:

- heartbeat shard maps (Topology.ec_shard_locations / node volume maps):
  a shard or replica that stops being reported is LOST — detection is a
  heartbeat diff, no scan needed;
- scrub verdicts (maintenance/scrub.py, POSTed to the master): a shard
  that is still reported but failed parity verification is CORRUPT.

Ledger states: healthy / degraded (EC volume missing shards but still
reconstructable) / under_replicated (normal volume with fewer replicas
than its placement wants) / corrupt (unresolved scrub verdict) /
critical (fewer than k shards survive — data loss, not repairable here).

Each tick plans repairs in urgency order — shards-lost ordering, so a
3-lost volume preempts a 1-lost one — and drives the EXISTING rebuild
machinery (/admin/ec/copy, /admin/ec/rebuild, mount) through a
token-bucket-limited executor with per-node concurrency caps and
exponential backoff; every stage carries a trace span.  Corrupt shards
are deleted first (their ranges are already quarantined on the owning
server), which turns "corrupt" into "lost" and reuses the same rebuild
path — and guarantees the rebuild never uses the bad bytes as a
survivor.

The planner yields to operators: while the shell holds the master admin
lock, the background loop skips its tick.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time

from seaweedfs_tpu.stats import metrics, netflow, trace
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import layout
from seaweedfs_tpu.security.tls import scheme as _tls_scheme
from seaweedfs_tpu.utils import resilience

log = logging.getLogger("repair")

HEALTH_STATES = ("healthy", "degraded", "under_replicated", "corrupt",
                 "critical")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill up to `burst`.  Caps
    how many repairs one tick may launch — re-protection traffic must not
    starve foreground I/O (the 1309.0186 lesson: recovery traffic
    dominates steady-state load when unthrottled).

    Thread-safe: ``try_acquire`` runs on the planner's event loop while
    ``set_rate`` is called from the interference governor on the
    aggregator thread (stats/interference.py), so both hold one lock."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self) -> None:
        now = time.monotonic()
        self.tokens = min(self.burst, self.tokens +
                          (now - self._last) * self.rate)
        self._last = now

    def try_acquire(self, n: float = 1.0) -> bool:
        # a request larger than burst (one production-sized shard can
        # exceed the whole cross-rack budget) is admitted once the
        # bucket is FULL and drives tokens negative: the long-run rate
        # stays bounded by `rate` paying off the debt, instead of the
        # request starving forever behind an unreachable threshold
        with self._lock:
            self._refill()
            if self.tokens >= min(n, self.burst):
                self.tokens -= n
                return True
            return False

    def set_rate(self, rate: float) -> None:
        """Retarget the refill rate live (the governor's seam).  Tokens
        accrued so far — including negative debt from an oversized
        admission — are settled at the OLD rate first, so a retune never
        forgives or inflates debt retroactively."""
        with self._lock:
            self._refill()
            self.rate = max(0.0, float(rate))

    def credit(self, n: float) -> None:
        """Refund tokens (clamped at burst like any refill) — used when
        a pre-debited repair never launched."""
        with self._lock:
            self.tokens = min(self.burst, self.tokens + n)

    def force_debit(self, n: float) -> None:
        """Unconditionally take tokens (may go negative): the naive-
        fallback path moves more bytes than the reduced estimate it
        debited, and the shortfall must still be paid off."""
        with self._lock:
            self.tokens -= n


def build_ledger(topo, scrub_reports: dict) -> dict[int, dict]:
    """Fold the topology's heartbeat-derived volume/shard maps and the
    stored scrub reports into {vid: health info}."""
    out: dict[int, dict] = {}
    from seaweedfs_tpu.ops import codecs as _codecs
    with topo._lock:
        ec = {vid: {sid: [n.url for n in nodes]
                    for sid, nodes in per.items() if nodes}
              for vid, per in topo.ec_shard_locations.items()}
        ec_cols = dict(topo.ec_collections)
        ec_sizes = dict(topo.ec_shard_sizes)
        ec_codecs = dict(getattr(topo, "ec_codecs", {}))
        node_loc = {n.url: (n.dc, n.rack) for n in topo.nodes.values()}
        normal: dict[int, dict] = {}
        for node in topo.nodes.values():
            for vid, v in node.volumes.items():
                rec = normal.setdefault(vid, {
                    "replicas": [], "collection": v.collection,
                    "replica_placement": v.replica_placement})
                rec["replicas"].append(node.url)
        free_slots = {n.url: n.free_slots for n in topo.nodes.values()}

    for vid, shards in ec.items():
        present = sorted(shards)
        spec = _codecs.parse_tag(ec_codecs.get(vid))
        missing = [s for s in range(spec.n)
                   if s not in shards]
        info = {
            "vid": vid, "kind": "ec", "collection": ec_cols.get(vid, ""),
            "codec": spec.tag,
            "shards_present": present, "shards_missing": missing,
            "shard_locations": shards,
            "shard_size": ec_sizes.get(vid, 0),
            "node_locality": {url: list(node_loc[url])
                              for nodes in shards.values()
                              for url in nodes if url in node_loc},
        }
        corrupt: list[dict] = []
        last_scrub = None
        quarantined: dict = {}
        for node, rep in (scrub_reports.get(vid) or {}).items():
            for c in rep.get("corrupt", []):
                corrupt.append(dict(c, node=node))
            ls = rep.get("last_scrub")
            if ls and (last_scrub is None or ls > last_scrub):
                last_scrub = ls
            q = rep.get("quarantined")
            if q:
                quarantined[node] = q
        info["corrupt"] = corrupt
        info["last_scrub"] = last_scrub
        info["quarantined"] = quarantined
        if len(present) < spec.k:
            info["state"] = "critical"
        elif corrupt:
            info["state"] = "corrupt"
        elif missing:
            info["state"] = "degraded"
        else:
            info["state"] = "healthy"
        # shards-lost ordering: a 3-lost volume preempts a 1-lost one,
        # and corruption counts like loss (the shard must be replaced)
        info["urgency"] = len(missing) + len(corrupt)
        out[vid] = info

    for vid, rec in normal.items():
        if vid in out:
            continue  # mid-EC-transition: the shard entry wins
        try:
            want = t.ReplicaPlacement.parse(
                rec.get("replica_placement", "000")).copy_count
        except (ValueError, KeyError):
            want = 1
        reps = sorted(set(rec["replicas"]))
        rep = (scrub_reports.get(vid) or {})
        crc = sum(r.get("crc_mismatches", 0) for r in rep.values())
        info = {
            "vid": vid, "kind": "normal",
            "collection": rec.get("collection", ""),
            "replicas": reps, "want_replicas": want,
            "crc_mismatches": crc,
            "last_scrub": max((r.get("last_scrub") or 0
                               for r in rep.values()), default=None),
            "free_slots": free_slots,
        }
        if crc:
            info["state"] = "corrupt"
            info["urgency"] = 1 + crc
        elif len(reps) < want:
            info["state"] = "under_replicated"
            info["urgency"] = want - len(reps)
        else:
            info["state"] = "healthy"
            info["urgency"] = 0
        out[vid] = info
    return out


class RepairPlanner:
    """Plans and executes repairs against the cluster's admin HTTP API.

    `master` provides .topo and ._session; everything else rides env
    knobs: WEEDTPU_REPAIR_CONCURRENCY (per-node active-repair cap,
    default 2), WEEDTPU_REPAIR_RATE / WEEDTPU_REPAIR_BURST (token bucket,
    default 1/s burst 4)."""

    def __init__(self, master, *, node_concurrency: int | None = None,
                 rate: float | None = None, burst: float | None = None,
                 backoff_base: float = 2.0, backoff_max: float = 300.0,
                 xrack_rate: float | None = None,
                 xrack_burst: float | None = None):
        self.master = master
        self.node_concurrency = node_concurrency if node_concurrency \
            else int(_env_float("WEEDTPU_REPAIR_CONCURRENCY", 2))
        self.bucket = TokenBucket(
            rate if rate is not None
            else _env_float("WEEDTPU_REPAIR_RATE", 1.0),
            burst if burst is not None
            else _env_float("WEEDTPU_REPAIR_BURST", 4.0))
        # cross-rack repair-byte budget (bytes/s + burst): repairs whose
        # survivor plan must pull partials across racks acquire their
        # ESTIMATED cross-rack bytes here before launching; when the
        # bucket runs dry the remaining (lower-urgency — candidates are
        # urgency-ordered) repairs wait for a later tick instead of
        # melting the inter-rack fabric (the 1309.0186 failure mode)
        self.xrack_bucket = TokenBucket(
            xrack_rate if xrack_rate is not None
            else _env_float("WEEDTPU_REPAIR_XRACK_BUDGET",
                            256 * 1024 * 1024),
            xrack_burst if xrack_burst is not None
            else _env_float("WEEDTPU_REPAIR_XRACK_BURST",
                            1024 * 1024 * 1024))
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        # vid -> {node -> last scrub report}
        self.scrub_reports: dict[int, dict[str, dict]] = {}
        self._active_vids: set[int] = set()
        self._active_nodes: dict[str, int] = {}
        self._backoff: dict[int, tuple[int, float]] = {}
        self._tasks: set[asyncio.Task] = set()
        self.history: list[dict] = []
        # survivor-selection audit trail: one record per EC repair with
        # the chosen rebuilder/helpers, locality classes, and estimated
        # vs actual repair bytes — surfaced in /maintenance/status
        self.decisions: list[dict] = []
        # repairs deferred by an exhausted cross-rack budget last tick
        self.waiting_xrack: list[int] = []
        # cumulative repair bytes by locality class (reduced path)
        self.locality_bytes: dict[str, int] = {}

    # -- scrub intake ---------------------------------------------------

    def record_scrub(self, node: str, payload: dict) -> None:
        for vid_s, rep in (payload.get("volumes") or {}).items():
            try:
                vid = int(vid_s)
            except ValueError:
                continue
            per = self.scrub_reports.setdefault(vid, {})
            per[node] = rep
        # bound: vid space is client-influenced; drop oldest-known first
        while len(self.scrub_reports) > 4096:
            self.scrub_reports.pop(next(iter(self.scrub_reports)))

    # -- ledger / status ------------------------------------------------

    def ledger(self) -> dict[int, dict]:
        led = build_ledger(self.master.topo, self.scrub_reports)
        # keep the exported health gauge fresh on every ledger build —
        # the background tick calls here, so /metrics shows live state
        # even when nobody polls /maintenance/status
        counts = {s: 0 for s in HEALTH_STATES}
        for info in led.values():
            counts[info["state"]] = counts.get(info["state"], 0) + 1
        for state, n in counts.items():
            metrics.VOLUME_HEALTH.labels(state).set(n)
        return led

    def status(self) -> dict:
        return {
            "tokens": round(self.bucket.tokens, 2),
            "node_concurrency": self.node_concurrency,
            "active": sorted(self._active_vids),
            "backoffs": {str(v): {"failures": f,
                                  "retry_in_s": round(max(0.0, ts -
                                                          time.monotonic()),
                                                      1)}
                         for v, (f, ts) in self._backoff.items()},
            "history": self.history[-20:],
            "xrack": {
                "budget_bytes_per_s": self.xrack_bucket.rate,
                "burst_bytes": self.xrack_bucket.burst,
                "tokens": round(self.xrack_bucket.tokens),
                "waiting": sorted(self.waiting_xrack),
            },
            "decisions": self.decisions[-10:],
            "repair_bytes_by_locality": dict(self.locality_bytes),
        }

    # -- planning -------------------------------------------------------

    def _repair_node(self, info: dict) -> str | None:
        """The node a repair would run on (for the per-node cap): the EC
        rebuilder holding the most shards, or the copy target for an
        under-replicated volume."""
        if info["kind"] == "ec":
            counts: dict[str, int] = {}
            for nodes in info.get("shard_locations", {}).values():
                for url in nodes:
                    counts[url] = counts.get(url, 0) + 1
            return max(counts, key=counts.get) if counts else None
        free = info.get("free_slots", {})
        have = set(info.get("replicas", []))
        for url in sorted(free, key=lambda u: -free[u]):
            if url not in have and free[url] > 0:
                return url
        return None

    def _plan_survivors(self, info: dict,
                        shards: dict | None = None) -> dict | None:
        """Locality-aware survivor selection for one degraded EC volume.

        Ranks survivor sources by locality class relative to the chosen
        rebuilder (same node < same rack < same DC < other DC, labels
        from the heartbeat topology) and picks the MINIMAL set of helper
        nodes covering k survivors — with partial-sum aggregation every
        extra node costs one more shard-range of repair traffic, so
        fewer, closer nodes is strictly better.  Returns the plan plus
        exact-or-upper-bound byte estimates for both the reduced path
        and the naive copy-survivors baseline (the cross-rack budget
        debits whichever path will run); None when the volume is not a
        reducible EC repair (nothing missing, < k survivors, or no
        shard-size report yet)."""
        from seaweedfs_tpu.topology.topology import locality_class
        from seaweedfs_tpu.ops import codecs as _codecs
        if info.get("kind") != "ec":
            return None
        spec = _codecs.parse_tag(info.get("codec"))
        shards = {int(s): list(n) for s, n in
                  (shards if shards is not None
                   else info.get("shard_locations") or {}).items()
                  if n}
        missing = [s for s in range(spec.n)
                   if s not in shards]
        if not missing or len(shards) < spec.k:
            return None
        shard_size = int(info.get("shard_size") or 0)
        if shard_size <= 0:
            # no shard-size report (pre-upgrade helpers): every byte
            # estimate would be 0 and the cross-rack budget silently
            # bypassed — and such helpers can't serve /admin/ec/partial
            # anyway, so degrade honestly to the naive path
            return None
        node_loc = info.get("node_locality") or {}
        counts: dict[str, int] = {}
        for nodes in shards.values():
            for url in nodes:
                counts[url] = counts.get(url, 0) + 1
        rebuilder = max(counts, key=counts.get)
        rdc, rrack = node_loc.get(rebuilder, ("", ""))

        def loc_of(url: str) -> int:
            dc, rack = node_loc.get(url, ("", ""))
            return locality_class(rdc, rrack, dc, rack,
                                  same_node=url == rebuilder)

        local = sorted(s for s, nodes in shards.items()
                       if rebuilder in nodes)
        # codec-aware survivor demand: which shards the rebuild actually
        # reads, and how many bytes each helper shard ships per lost
        # shard.  RS: any k, full shard rows.  LRC: the lost shard's
        # local group (repair_support) — single-group fan-in, no wide
        # reads.  MSR: d whole helper files, each shipping one combined
        # sub-row (shard_size/alpha bytes) per lost shard.
        needed: set[int] | None = None  # None = any-k (MDS)
        per_helper_shard = shard_size
        need = spec.k - len(local)
        if spec.family == "lrc":
            from seaweedfs_tpu.ops import lrc as _lrc
            code = _lrc.get_code(*spec.params)
            needed = set()
            cur = set(shards)
            for sid in missing:
                sup = code.repair_support(sid, sorted(cur))
                if sup is None:
                    needed = None
                    break
                needed |= set(sup) - {s for s in sup if s in missing}
                cur.add(sid)  # rebuilt: a survivor for the next loss
            if needed is None:
                try:
                    needed = set(code.decode_select(sorted(shards),
                                                    list(missing)))
                except ValueError:
                    return None
            need = len(needed - set(local))
        elif spec.family == "msr":
            d_helpers = spec.params[1]
            if len(shards) < d_helpers:
                # fewer than d survivors: the regenerating plan cannot
                # run; let the naive copy+rebuild path handle it
                return None
            need = d_helpers - len(local)
            per_helper_shard = shard_size // max(1, spec.alpha)
        from seaweedfs_tpu.topology.topology import locality_name
        remote_by_node: dict[str, list[int]] = {}
        naive_xrack = 0
        naive_by_loc: dict[str, int] = {}
        for sid, nodes in sorted(shards.items()):
            if rebuilder in nodes:
                continue
            if needed is not None and sid not in needed:
                continue  # outside the codec's survivor demand
            best = min(nodes, key=loc_of)
            remote_by_node.setdefault(best, []).append(sid)
            # the naive baseline copies EVERY survivor not already on
            # the rebuilder, from its first listed location
            src_loc = loc_of(nodes[0])
            if src_loc >= 2:
                naive_xrack += shard_size
            src = locality_name(src_loc)
            naive_by_loc[src] = naive_by_loc.get(src, 0) + shard_size
        ordered = sorted(remote_by_node.items(),
                         key=lambda kv: (loc_of(kv[0]), -len(kv[1]),
                                         kv[0]))
        groups: list[dict] = []
        have = 0
        for url, sids in ordered:
            if have >= need:
                break
            groups.append({"node": url, "shards": sorted(sids),
                           "locality": loc_of(url),
                           "shard_size": shard_size})
            have += len(sids)
        covered = len(local) + have if needed is None else             len([s for s in local if s in needed]) + have
        floor = need + (len(local) if needed is None
                        else len([s for s in local if s in needed]))
        if covered < floor or covered < min(
                spec.k, floor if needed is not None else spec.k):
            return None
        n_lost = len(missing)
        est_remote = n_lost * per_helper_shard * len(groups)
        est_xrack = n_lost * per_helper_shard * sum(
            1 for g in groups if g["locality"] >= 2)
        return {
            "rebuilder": rebuilder, "lost": missing, "groups": groups,
            "codec": spec.tag,
            "local_shards": local, "shard_size": shard_size,
            "est_remote_bytes": est_remote,
            "est_xrack_bytes": est_xrack,
            "naive_remote_bytes":
                (len(shards) - len(local)) * shard_size,
            "naive_xrack_bytes": naive_xrack,
            "naive_by_locality": naive_by_loc,
            "locality_classes": {g["node"]: g["locality"]
                                 for g in groups},
        }

    def _reduced_enabled(self) -> bool:
        return os.environ.get("WEEDTPU_REPAIR_REDUCED", "1") != "0"

    def _capacity_boost(self, infos) -> None:
        """Forward-looking urgency input from the capacity forecaster
        (stats/history.py): a repair whose survivors sit on a disk
        predicted to fill within WEEDTPU_FORECAST_URGENT_S moves up the
        queue — rebuild it while the bytes still have somewhere to go,
        instead of discovering the full disk mid-copy."""
        fc = getattr(self.master, "forecaster", None)
        if fc is None:
            return
        try:
            urgent = fc.filling_nodes(
                _env_float("WEEDTPU_FORECAST_URGENT_S", 21600.0))
        except Exception:
            return
        if not urgent:
            return
        for info in infos:
            if info["kind"] == "ec":
                nodes = {url for locs in
                         info.get("shard_locations", {}).values()
                         for url in locs}
            else:
                nodes = set(info.get("replicas", []))
            if nodes & urgent:
                info["urgency"] += 1
                info["capacity_urgent"] = True

    async def tick(self) -> list[dict]:
        """One planning pass: launch repair tasks for the most urgent
        repairable volumes, bounded by the token bucket and per-node
        caps.  Returns the actions launched (not their outcomes — await
        wait_idle() for those)."""
        led = self.ledger()
        cands = [i for i in led.values()
                 if i["state"] in ("degraded", "corrupt",
                                   "under_replicated")]
        self._capacity_boost(cands)
        # urgency first (shards lost), then fewest survivors: the volume
        # closest to k survivors is one failure from data loss and must
        # reach the front of the queue — and of the cross-rack budget
        cands.sort(key=lambda i: (-i["urgency"],
                                  len(i.get("shards_present", ()))))
        now = time.monotonic()
        actions: list[dict] = []
        waiting_xrack: list[int] = []
        for info in cands:
            vid = info["vid"]
            if vid in self._active_vids:
                continue
            bo = self._backoff.get(vid)
            if bo and now < bo[1]:
                continue
            if info["kind"] == "normal" and info["state"] == "corrupt":
                # a corrupt store needle heals by replica reads + vacuum;
                # nothing to rebuild unless also under-replicated
                if len(info["replicas"]) >= info["want_replicas"]:
                    continue
            node = self._repair_node(info)
            if node is None:
                continue
            if self._active_nodes.get(node, 0) >= self.node_concurrency:
                continue
            # cross-rack budget: debit the estimated cross-rack bytes of
            # whichever path will run BEFORE launching; a repair the
            # bucket cannot cover waits for a later tick (refill), while
            # zero-cross-rack repairs further down the queue still run
            plan = self._plan_survivors(info)
            if plan is not None:
                info["_plan"] = plan
                est_x = plan["est_xrack_bytes"] if self._reduced_enabled() \
                    else plan["naive_xrack_bytes"]
                if est_x > 0 and not self.xrack_bucket.try_acquire(est_x):
                    waiting_xrack.append(vid)
                    continue
            if not self.bucket.try_acquire():
                if plan is not None:
                    # refund the cross-rack debit of a repair that never
                    # launched (clamped at burst like any refill)
                    self.xrack_bucket.credit(
                        plan["est_xrack_bytes"]
                        if self._reduced_enabled()
                        else plan["naive_xrack_bytes"])
                break  # rate-limited: later ticks pick up the rest
            self._active_vids.add(vid)
            self._active_nodes[node] = self._active_nodes.get(node, 0) + 1
            task = asyncio.create_task(self._run_one(info, node))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            actions.append({"vid": vid, "kind": info["kind"],
                            "state": info["state"], "node": node,
                            "urgency": info["urgency"]})
        self.waiting_xrack = waiting_xrack
        return actions

    async def wait_idle(self) -> None:
        while self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)

    # -- execution ------------------------------------------------------

    async def _post(self, url: str, path: str, body: dict) -> dict:
        import aiohttp
        # the master session's default 30s total timeout would abort a
        # realistically-sized shard copy or rebuild mid-flight (the shell
        # gives these 600s too)
        async with self.master._session.post(
                f"{_tls_scheme()}://{url}{path}", json=body,
                timeout=aiohttp.ClientTimeout(total=600)) as r:
            try:
                data = await r.json()
            except Exception:
                data = {}
            if r.status != 200:
                raise RuntimeError(
                    f"{url}{path}: HTTP {r.status} "
                    f"{data.get('error', '')}".strip())
            return data

    async def _run_one(self, info: dict, node: str) -> None:
        vid = info["vid"]
        t0 = time.monotonic()
        root = trace.new_root()
        outcome = "ok"
        try:
            # every byte this repair moves — survivor copies, purges,
            # rebuild orchestration, and the shard pulls the target
            # volume server makes on our behalf (the class header
            # re-enters its middleware) — books as class=repair
            with netflow.flow("repair"), \
                    trace.span("repair.volume", parent=root, vid=vid,
                               kind=info["kind"], state=info["state"],
                               urgency=info["urgency"]):
                if info["kind"] == "ec":
                    resolved = await self._repair_ec(vid, info)
                else:
                    await self._replicate_volume(vid, info, node)
                    resolved = set()
            self._backoff.pop(vid, None)
            # clear ONLY the verdicts this repair actually resolved
            # (purged + rebuilt); unlocalized or unpurgeable corruption
            # stays on the ledger until a scrub pass re-verifies it
            for rep in (self.scrub_reports.get(vid) or {}).values():
                rep["corrupt"] = [c for c in rep.get("corrupt", [])
                                  if c.get("shard", -1) not in resolved]
            metrics.REPAIR_ACTIONS.labels(info["kind"], "ok").inc()
        except Exception as e:
            n = self._backoff.get(vid, (0, 0.0))[0] + 1
            # decorrelated jitter (utils/resilience.py): N volumes whose
            # repairs failed together must not retry together
            delay = resilience.backoff_delay(n, self.backoff_base,
                                             self.backoff_max)
            self._backoff[vid] = (n, time.monotonic() + delay)
            metrics.REPAIR_ACTIONS.labels(info["kind"], "error").inc()
            outcome = f"error: {e}"
            log.warning("repair of volume %d failed (attempt %d, backoff "
                        "%.1fs): %s", vid, n, delay, e)
        finally:
            self._active_vids.discard(vid)
            left = self._active_nodes.get(node, 1) - 1
            if left <= 0:
                self._active_nodes.pop(node, None)
            else:
                self._active_nodes[node] = left
        self.history.append({"vid": vid, "kind": info["kind"],
                             "state": info["state"], "outcome": outcome,
                             "seconds": round(time.monotonic() - t0, 3)})
        del self.history[:-100]

    async def _repair_ec(self, vid: int, info: dict) -> set[int]:
        """Mirror of the shell's ec.rebuild for ONE volume, preceded by a
        purge of scrub-verdicted corrupt shards so the rebuild can never
        pick bad bytes as a survivor.  Returns the corrupt shard ids this
        run resolved; raises when corruption remains unresolved — a
        rebuild from possibly-corrupt survivors is worse than staying
        degraded behind the read-path quarantine."""
        shards = {sid: list(nodes)
                  for sid, nodes in info.get("shard_locations", {}).items()}
        resolved: set[int] = set()
        unresolved: list[str] = []
        for c in info.get("corrupt", []):
            sid, node = c.get("shard", -1), c.get("node")
            if sid < 0:
                # unlocalized: quarantine (when any) guards reads, but we
                # cannot pick a shard to replace — needs operator eyes
                unresolved.append("unlocalized corruption "
                                  f"at [{c.get('offset')}, "
                                  f"+{c.get('size')})")
                continue
            owners = shards.get(sid, [])
            if node not in owners:
                # remote-scrub verdicts name the REPORTING node; purge on
                # a node that actually owns the shard
                node = owners[0] if owners else None
            if node is None:
                resolved.add(sid)  # already gone: the loss path rebuilds
                continue
            # len(shards) tracks earlier purges in this loop already
            from seaweedfs_tpu.ops import codecs as _c2
            k_min = _c2.parse_tag(info.get("codec")).k
            if sid in shards and len(shards) - 1 < k_min:
                unresolved.append(
                    f"shard {sid} corrupt but only {len(shards)} shards "
                    "present — purging would drop below k")
                continue
            with trace.span("repair.purge_corrupt", vid=vid, shard=sid,
                            peer=node):
                await self._post(node, "/admin/ec/delete_shards",
                                 {"volume": vid, "shards": [sid]})
            nodes = shards.get(sid, [])
            if node in nodes:
                nodes.remove(node)
            if not nodes:
                shards.pop(sid, None)
            resolved.add(sid)
        if unresolved:
            # do NOT rebuild: /admin/ec/copy streams raw shard files (the
            # quarantine only guards needle reads), so a rebuild here
            # could bake the bad bytes into fresh shards
            raise RuntimeError("; ".join(unresolved))
        from seaweedfs_tpu.ops import codecs as _codecs
        spec = _codecs.parse_tag(info.get("codec"))
        present = set(shards)
        missing = [s for s in range(spec.n)
                   if s not in present]
        if not missing:
            return resolved
        if len(present) < spec.k:
            raise RuntimeError(
                f"only {len(present)} shards survive, need "
                f"{spec.k}")
        collection = info.get("collection", "")
        # survivor plan: the tick's (budget-debited) plan when the purge
        # loop above didn't change the shard map, else a fresh one
        plan = info.get("_plan")
        if plan is None or resolved or \
                sorted(plan["lost"]) != sorted(missing):
            plan = self._plan_survivors(info, shards=shards)
        # reduced pays whenever bytes would otherwise cross the network
        # (helper partials needed, or naive would copy survivors the
        # rebuilder doesn't even need).  With EVERY survivor already
        # local the plain rebuild moves zero repair bytes too and keeps
        # the faster native zero-copy decode path.
        if plan is not None and self._reduced_enabled() and \
                (plan["groups"] or plan["naive_remote_bytes"] > 0):
            rebuilder = plan["rebuilder"]
            with trace.span("repair.survivors", vid=vid,
                            rebuilder=rebuilder,
                            lost=",".join(map(str, missing)),
                            helpers=",".join(
                                f"{g['node']}:{g['locality']}"
                                for g in plan["groups"]),
                            est_remote_bytes=plan["est_remote_bytes"],
                            est_xrack_bytes=plan["est_xrack_bytes"]):
                try:
                    resp = await self._post(
                        rebuilder, "/admin/ec/rebuild",
                        {"volume": vid, "codec": spec.tag,
                         "reduced": {"lost": missing,
                                     "groups": plan["groups"],
                                     "shard_size": plan["shard_size"]}})
                except Exception as e:
                    # graceful degradation: the survivor-copy path below
                    # still heals (at naive cost); record why we fell back
                    log.warning("reduced rebuild of volume %d on %s "
                                "failed (%s); falling back to survivor "
                                "copies", vid, rebuilder, e)
                    self._record_decision(plan, vid, mode="naive_fallback",
                                          error=str(e))
                    # the tick debited only the reduced estimate; the
                    # survivor-copy path below moves naive-level
                    # cross-rack bytes, so force the shortfall into the
                    # bucket as debt — a cluster-wide fallback storm must
                    # still be throttled at the bytes it actually moves
                    self.xrack_bucket.force_debit(max(
                        0.0, plan["naive_xrack_bytes"]
                        - plan["est_xrack_bytes"]))
                    plan = None  # the tail must not record this twice
                else:
                    with trace.span("repair.mount", vid=vid,
                                    node=rebuilder):
                        await self._post(rebuilder, "/admin/ec/mount",
                                         {"volume": vid,
                                          "collection": collection})
                    self._record_decision(plan, vid, mode="reduced",
                                          result=resp)
                    log.info("repair: volume %d reduced-rebuilt shards "
                             "%s on %s (%d remote bytes, %d replans, "
                             "purged %d corrupt)", vid, missing,
                             rebuilder,
                             sum((resp.get("helper_bytes") or {})
                                 .values()),
                             resp.get("replans", 0), len(resolved))
                    return resolved
        counts: dict[str, int] = {}
        for nodes in shards.values():
            for url in nodes:
                counts[url] = counts.get(url, 0) + 1
        rebuilder = max(counts, key=counts.get)
        borrowed: list[int] = []
        for sid, nodes in sorted(shards.items()):
            if rebuilder in nodes:
                continue
            with trace.span("repair.copy_survivor", vid=vid, shard=sid,
                            source=nodes[0], target=rebuilder):
                await self._post(rebuilder, "/admin/ec/copy",
                                 {"volume": vid, "collection": collection,
                                  "source": nodes[0], "shards": [sid],
                                  "copy_ecx": False})
            borrowed.append(sid)
        with trace.span("repair.rebuild", vid=vid, node=rebuilder,
                        missing=len(missing)):
            await self._post(rebuilder, "/admin/ec/rebuild",
                             {"volume": vid, "codec": spec.tag})
        if borrowed:
            await self._post(rebuilder, "/admin/ec/delete_shards",
                             {"volume": vid, "shards": borrowed})
        with trace.span("repair.mount", vid=vid, node=rebuilder):
            await self._post(rebuilder, "/admin/ec/mount",
                             {"volume": vid, "collection": collection})
        if plan is not None:
            self._record_decision(plan, vid, mode="naive")
        log.info("repair: volume %d rebuilt shards %s on %s "
                 "(purged %d corrupt)", vid, missing, rebuilder,
                 len(resolved))
        return resolved

    def _record_decision(self, plan: dict, vid: int, mode: str,
                         result: dict | None = None,
                         error: str | None = None) -> None:
        """One survivor-selection audit record (surfaced in
        /maintenance/status) + the repair-byte-by-locality ledger."""
        from seaweedfs_tpu.stats import metrics as _metrics
        rec = {"ts": round(time.time(), 3), "vid": vid, "mode": mode,
               "codec": plan.get("codec", "rs_10_4"),
               "rebuilder": plan["rebuilder"], "lost": plan["lost"],
               "helpers": [{"node": g["node"], "shards": g["shards"],
                            "locality": g["locality"]}
                           for g in plan["groups"]],
               "est_remote_bytes": plan["est_remote_bytes"],
               "est_xrack_bytes": plan["est_xrack_bytes"],
               "naive_remote_bytes": plan["naive_remote_bytes"]}
        if error:
            rec["error"] = error
        by_loc: dict[str, int] = {}
        if result is not None:
            rec["actual_bytes"] = sum(
                (result.get("helper_bytes") or {}).values())
            rec["replans"] = result.get("replans", 0)
            by_loc = dict(result.get("by_locality") or {})
        elif mode in ("naive", "naive_fallback"):
            # the naive path copies EVERY off-rebuilder survivor (not
            # just the reduced plan's minimal helper groups); attribute
            # them by each copy's first-listed source (estimate: the
            # copy handler doesn't report per-source bytes)
            by_loc = dict(plan.get("naive_by_locality") or {})
        for name, n in by_loc.items():
            self.locality_bytes[name] = \
                self.locality_bytes.get(name, 0) + n
            if result is None:
                # reduced-path bytes were already metered at the
                # rebuilder's fetch hop; the master only books the
                # naive-copy estimate nobody else measures
                _metrics.REPAIR_BYTES.labels(name).inc(n)
        self.decisions.append(rec)
        del self.decisions[:-50]

    async def _replicate_volume(self, vid: int, info: dict,
                                target: str) -> None:
        source = (info.get("replicas") or [None])[0]
        if source is None:
            raise RuntimeError("no surviving replica to copy from")
        with trace.span("repair.replicate", vid=vid, source=source,
                        target=target):
            await self._post(target, "/admin/volume/copy",
                             {"volume": vid, "source": source,
                              "collection": info.get("collection", "")})
        log.info("repair: volume %d re-replicated %s -> %s", vid, source,
                 target)
