"""Autopilot: the master-side policy engine that ACTS on telemetry.

Rounds 6-13 built the senses (heat sketches, the history TSDB with
capacity forecasts and alerts, the interference index) and the
actuators (fleet EC conversion, reduced-read repair, the rate
governor), but nothing connected them: hot chunks decoded per-read
forever, cold replicated volumes never became EC, and a disk whose
``predicted_full_seconds`` alarm fired just rendered a dashboard row.
The SSD-array study (PAPERS.md, arXiv 1709.05365) shows online EC
systems lose their latency budget to exactly this kind of unscheduled
background placement work, and the warehouse study (arXiv 1309.0186)
shows placement decisions dominate cluster network cost — so the
decision layer sits HERE, as typed, dry-run-able, traced action plans.

Three policies, evaluated each tick against ``/cluster/heat``, the
health ledger, and the capacity forecasts:

- **tiering** — demote volumes that have been COLD for a sustained
  window (``WEEDTPU_AUTOPILOT_COLD_RPS`` / ``_COLD_S``) to EC by
  enqueueing them on the fleet-conversion scheduler with ``seal=True``
  (the shard set mounts and the .dat retires once the conversion
  commits); promote EC volumes that have been HOT for a sustained
  window (``_HOT_RPS`` / ``_HOT_S``, measured by the heat sketches'
  monotone ``sustained_s`` clock — never inferred from decayed
  estimates) back to the replicated/mmap fast path through the volume
  server's ``/admin/volume/unconvert`` decode-and-thaw path.
- **balancing** — when a disk's ``predicted_full_seconds`` fires inside
  ``WEEDTPU_AUTOPILOT_FULL_HORIZON_S``, plan a move of that node's
  coldest plain volume to the emptiest non-filling node, executed by
  the volume server's ``/admin/volume/move`` (staged copy, CRC verify,
  commit on target, retire on source; abortable mid-failure with no
  partial state; every byte books as netflow ``class=rebalance``).
- **codec selection** — per-volume erasure-code choice from the same
  heat evidence: a sustained-hot EC volume plans a recode to LRC
  (degraded reads touch one local parity group), a sustained-cold one
  to PM-MSR (repair ships d/(k*alpha) shard-equivalents instead of k),
  executed by the volume server's in-place ``/admin/ec/recode`` on the
  shard-majority node.  Paced by its own governed ``codec`` bucket
  (``WEEDTPU_AUTOPILOT_CODEC_RATE``/``_BURST``); warm middle-band
  volumes keep their codec.
- **action ledger** — every plan is a pinned trace plus a decision
  record with a state machine ``planned -> approved -> executing ->
  done | aborted``.  ``WEEDTPU_AUTOPILOT=plan`` (the DEFAULT) creates
  plans but executes NOTHING until an operator approves one
  (``cluster.autopilot -approve <id>``); ``execute`` auto-approves;
  ``0`` disables planning outright.  Hysteresis keeps flapping volumes
  from thrashing: cold/hot must be SUSTAINED (the cold clock resets on
  any warm sighting; the hot clock is the sketch entry's first_seen,
  which eviction resets), and every executed — or failed — action arms
  a per-volume ``WEEDTPU_AUTOPILOT_COOLDOWN_S`` lockout before the
  volume can be planned again.  Per-policy token buckets
  (``_TIER_RATE``/``_BALANCE_RATE``) pace plan creation, and the
  interference governor retunes them live like any other background
  work class.

The autopilot itself never touches data: it only drives the existing
abort-safe actuators, and every actuator call increments
``actuator_calls`` so plan-only mode is PROVABLY inert (the test
asserts zero).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

from seaweedfs_tpu.maintenance.repair import TokenBucket, _env_float
from seaweedfs_tpu.stats import metrics, netflow, trace
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import layout

log = logging.getLogger("autopilot")

PLAN_STATES = ("planned", "approved", "executing", "done", "aborted")
POLICIES = ("tiering_demote", "tiering_promote", "balance_move",
            "chunk_promote", "codec_select")


def autopilot_mode() -> str:
    """WEEDTPU_AUTOPILOT: ``plan`` (default — decide, record, execute
    nothing without operator approval), ``execute`` (closed loop), or
    ``0`` (off).  Read per tick so tests and operators can flip a live
    master."""
    m = os.environ.get("WEEDTPU_AUTOPILOT", "plan").strip().lower()
    if m in ("0", "off", "false", "no"):
        return "0"
    return m if m in ("plan", "execute") else "plan"


class Autopilot:
    """One per master.  ``tick()`` reads the telemetry planes and emits
    action plans; ``approve``/``abort`` drive the state machine;
    ``_execute`` is the ONLY place actuator calls happen."""

    KEEP_PLANS = 200  # terminal plans retained for the ledger view

    def __init__(self, master, *,
                 cold_rps: float | None = None,
                 cold_s: float | None = None,
                 hot_rps: float | None = None,
                 hot_s: float | None = None,
                 cooldown_s: float | None = None,
                 horizon_s: float | None = None,
                 tier_rate: float | None = None,
                 balance_rate: float | None = None):
        self.master = master
        self.cold_rps = cold_rps if cold_rps is not None else \
            _env_float("WEEDTPU_AUTOPILOT_COLD_RPS", 0.2)
        self.cold_s = cold_s if cold_s is not None else \
            _env_float("WEEDTPU_AUTOPILOT_COLD_S", 900.0)
        self.hot_rps = hot_rps if hot_rps is not None else \
            _env_float("WEEDTPU_AUTOPILOT_HOT_RPS", 5.0)
        self.hot_s = hot_s if hot_s is not None else \
            _env_float("WEEDTPU_AUTOPILOT_HOT_S", 120.0)
        self.cooldown_s = cooldown_s if cooldown_s is not None else \
            _env_float("WEEDTPU_AUTOPILOT_COOLDOWN_S", 900.0)
        self.horizon_s = horizon_s if horizon_s is not None else \
            _env_float("WEEDTPU_AUTOPILOT_FULL_HORIZON_S", 21600.0)
        # per-policy pacing: plans/second with a small burst.  The
        # governor retunes these live (targets autopilot_tier /
        # autopilot_balance) exactly like the repair and convert buckets
        self.buckets = {
            "tiering": TokenBucket(
                tier_rate if tier_rate is not None
                else _env_float("WEEDTPU_AUTOPILOT_TIER_RATE", 0.5),
                _env_float("WEEDTPU_AUTOPILOT_TIER_BURST", 4.0)),
            "balance": TokenBucket(
                balance_rate if balance_rate is not None
                else _env_float("WEEDTPU_AUTOPILOT_BALANCE_RATE", 0.1),
                _env_float("WEEDTPU_AUTOPILOT_BALANCE_BURST", 2.0)),
            "chunk": TokenBucket(
                _env_float("WEEDTPU_AUTOPILOT_CHUNK_RATE", 1.0),
                _env_float("WEEDTPU_AUTOPILOT_CHUNK_BURST", 8.0)),
            "codec": TokenBucket(
                _env_float("WEEDTPU_AUTOPILOT_CODEC_RATE", 0.1),
                _env_float("WEEDTPU_AUTOPILOT_CODEC_BURST", 2.0)),
        }
        # chunk-granular promotion: sustained-hot chunks from the fleet
        # heat sketch are seeded into their hot-tier home filer (the
        # missing finer-grained sibling of volume tiering)
        self.chunk_rps = _env_float("WEEDTPU_AUTOPILOT_CHUNK_RPS", 2.0)
        self.chunk_s = _env_float("WEEDTPU_AUTOPILOT_CHUNK_S", 30.0)
        self._chunk_last: dict[str, float] = {}  # per-fid cooldown
        self.plans: dict[str, dict] = {}  # insertion-ordered ledger
        self._plan_seq = 0
        # hysteresis state: when each volume was FIRST seen cold (reset
        # on any warm sighting), and the per-volume action cooldown
        self._cold_since: dict[int, float] = {}
        # codec_select's own sustained-cold clock: a volume can be
        # tiering-stable yet still drift between codec temperature
        # bands, so the two hysteresis clocks are independent
        self._codec_cold_since: dict[int, float] = {}
        self._last_action: dict[int, tuple[float, str]] = {}
        self._tasks: set[asyncio.Task] = set()
        self.ticks = 0
        # incremented by EVERY actuator call (enqueue, unconvert POST,
        # move POST, shard retirement) — the plan-only proof reads this
        self.actuator_calls = 0
        # sustained-hot EC volumes that could NOT be planned because no
        # node holds k shards (promote needs shard consolidation,
        # which this engine does not do): counted + logged, never
        # silently dropped
        self.promote_blocked_spread = 0
        # codec_select plans that could not run because no node holds
        # k shards (recode decodes locally, like promote)
        self.recode_blocked_spread = 0

    # -- the tick ---------------------------------------------------------

    async def tick(self) -> list[dict]:
        """One policy pass.  Returns the plans CREATED this tick (the
        full ledger lives in status()).  In execute mode, freshly
        planned work is auto-approved and launched; in plan mode it
        waits for an operator."""
        mode = autopilot_mode()
        if mode == "0":
            return []
        self.ticks += 1
        now = time.time()
        try:
            heat_view = await asyncio.to_thread(self.master.cached_heat)
        except Exception as e:
            log.warning("autopilot: heat fan-out failed (%s); planning "
                        "from ledger/forecast only", e)
            heat_view = {}
        ledger = self.master.maintenance.ledger()
        vol_heat = self._volume_heat(heat_view)
        new: list[dict] = []
        new += self._plan_tiering(now, vol_heat, ledger)
        new += self._plan_balancing(now, vol_heat)
        new += self._plan_chunk_promote(now, heat_view)
        new += self._plan_codec_select(now, vol_heat, ledger)
        if mode == "execute":
            for plan in [p for p in self.plans.values()
                         if p["state"] == "planned"]:
                self.approve(plan["id"])
        self._gc_plans()
        return [self.serialize_plan(p) for p in new]

    async def wait_idle(self) -> None:
        while self._tasks:
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)

    # -- inputs -----------------------------------------------------------

    @staticmethod
    def _volume_heat(heat_view: dict) -> dict[int, dict]:
        """The fleet heat view's per-volume records, keyed by vid."""
        out: dict[int, dict] = {}
        for rec in (heat_view.get("volumes") or {}).get("top", []):
            try:
                out[int(rec["key"])] = rec
            except (KeyError, TypeError, ValueError):
                continue
        return out

    def _active_vids(self) -> set[int]:
        return {p["vid"] for p in self.plans.values()
                if p["state"] in ("planned", "approved", "executing")}

    def _in_cooldown(self, vid: int, now: float) -> bool:
        rec = self._last_action.get(vid)
        return rec is not None and now - rec[0] < self.cooldown_s

    # -- tiering policy ---------------------------------------------------

    def _plan_tiering(self, now: float, vol_heat: dict[int, dict],
                      ledger: dict[int, dict]) -> list[dict]:
        conv = self.master.convert
        # volumes already in the conversion pipeline — queued, active,
        # or parked in the re-queue backlog — must not be re-planned
        parked = set(conv.queued) | set(conv.active) | set(conv._backoff)
        active = self._active_vids()
        plans: list[dict] = []
        for vid, info in sorted(ledger.items()):
            rec = vol_heat.get(vid)
            rps = float(rec.get("rps", 0.0)) if rec else 0.0
            sustained = float(rec.get("sustained_s", 0.0)) if rec else 0.0
            if info["kind"] == "normal":
                if info["state"] != "healthy":
                    # degraded/under-replicated: repair's problem first
                    self._cold_since.pop(vid, None)
                    continue
                if rps > self.cold_rps:
                    # warm sighting: the sustained-cold clock restarts
                    self._cold_since.pop(vid, None)
                    continue
                since = self._cold_since.setdefault(vid, now)
                cold_for = now - since
                if cold_for < self.cold_s:
                    continue  # not sustained yet (hysteresis)
                if vid in parked or vid in active or \
                        self._in_cooldown(vid, now):
                    continue
                if not self.buckets["tiering"].try_acquire():
                    break  # paced: later ticks pick up the rest
                plans.append(self._new_plan(
                    "tiering_demote", vid,
                    collection=info.get("collection", ""),
                    reason={"rps": round(rps, 3),
                            "cold_for_s": round(cold_for, 1),
                            "threshold_rps": self.cold_rps}))
            elif info["kind"] == "ec":
                self._cold_since.pop(vid, None)
                if rec is None or rps < self.hot_rps:
                    continue
                if sustained < self.hot_s:
                    continue  # hot, but not SUSTAINED hot (hysteresis)
                if info["state"] != "healthy":
                    continue  # missing/corrupt shards: heal before tiering
                if vid in active or self._in_cooldown(vid, now):
                    continue
                node, others = self._promote_node(info)
                if node is None:
                    # no node holds k shards locally: this engine has
                    # no shard-consolidation actuator (ROADMAP
                    # follow-on), so the promote CANNOT run — say so
                    # (no silent caps) instead of skipping invisibly
                    self.promote_blocked_spread += 1
                    from seaweedfs_tpu.utils import weedlog
                    weedlog.warn_ratelimited(
                        f"autopilot_spread:{vid}", 300.0,
                        "autopilot: volume %d is sustained-hot EC but "
                        "no node holds %d+ shards; promote needs shard "
                        "consolidation (unbuilt) — not planned", vid,
                        layout.DATA_SHARDS, name="autopilot")
                    continue
                if not self.buckets["tiering"].try_acquire():
                    break
                plans.append(self._new_plan(
                    "tiering_promote", vid, node=node,
                    collection=info.get("collection", ""),
                    other_shard_nodes=others,
                    reason={"rps": round(rps, 3),
                            "sustained_s": round(sustained, 1),
                            "degraded_fraction":
                                rec.get("degraded_fraction", 0.0),
                            "threshold_rps": self.hot_rps}))
        return plans

    @staticmethod
    def _promote_node(info: dict,
                      k: int | None = None) -> tuple[str | None, dict]:
        """The node to decode on — it must hold at least k shards
        locally (rebuild_ec_files regenerates the rest in place) — plus
        {node: [shards]} for every OTHER node whose remnant shards the
        promote (or recode) retires afterwards.  `k` defaults to the
        volume's own codec stripe width from the ledger."""
        if k is None:
            from seaweedfs_tpu.ops import codecs as _codecs
            k = _codecs.parse_tag(info.get("codec")).k
        per_node: dict[str, list[int]] = {}
        for sid, nodes in (info.get("shard_locations") or {}).items():
            for url in nodes:
                per_node.setdefault(url, []).append(int(sid))
        if not per_node:
            return None, {}
        best = max(per_node, key=lambda u: len(per_node[u]))
        if len(per_node[best]) < k:
            return None, {}
        others = {u: sorted(s) for u, s in per_node.items() if u != best}
        return best, others

    # -- codec selection policy -------------------------------------------

    def _plan_codec_select(self, now: float, vol_heat: dict[int, dict],
                           ledger: dict[int, dict]) -> list[dict]:
        """Per-volume codec choice from the heat sketches: a
        sustained-HOT EC volume (lots of degraded/partial reads at
        stake) wants LRC — single-shard repair touches one local group
        instead of k-wide decode; a sustained-COLD archival volume
        wants PM-MSR — repair bandwidth drops to d/(k*alpha) shard
        equivalents and nobody is waiting on its read latency.  Same
        hysteresis discipline as tiering (the cold clock resets on any
        warm sighting; hot uses the sketch's monotone sustained_s),
        same per-volume cooldown, its own governed `codec` bucket.
        Warm middle-band volumes keep whatever codec they have — the
        policy only moves volumes OUT of a mismatched band."""
        from seaweedfs_tpu.ops import codecs as _codecs
        if self.buckets["codec"].rate <= 0:
            return []
        active = self._active_vids()
        plans: list[dict] = []
        for vid, info in sorted(ledger.items()):
            if info.get("kind") != "ec":
                self._codec_cold_since.pop(vid, None)
                continue
            if info.get("state") != "healthy":
                # missing shards: heal first — a recode decodes the
                # stripe and would race the repair plane
                self._codec_cold_since.pop(vid, None)
                continue
            cur = _codecs.parse_tag(info.get("codec"))
            rec = vol_heat.get(vid)
            rps = float(rec.get("rps", 0.0)) if rec else 0.0
            sustained = float(rec.get("sustained_s", 0.0)) if rec else 0.0
            target = reason = None
            if rps >= self.hot_rps:
                self._codec_cold_since.pop(vid, None)
                if sustained >= self.hot_s and cur.family != "lrc":
                    target = _codecs.parse_tag("lrc").tag
                    reason = {"band": "hot", "rps": round(rps, 3),
                              "sustained_s": round(sustained, 1),
                              "threshold_rps": self.hot_rps}
            elif rps <= self.cold_rps:
                since = self._codec_cold_since.setdefault(vid, now)
                cold_for = now - since
                if cold_for >= self.cold_s and cur.family != "msr":
                    target = _codecs.parse_tag("msr").tag
                    reason = {"band": "cold", "rps": round(rps, 3),
                              "cold_for_s": round(cold_for, 1),
                              "threshold_rps": self.cold_rps}
            else:
                self._codec_cold_since.pop(vid, None)
            if target is None or target == cur.tag:
                continue
            if vid in active or self._in_cooldown(vid, now):
                continue
            node, others = self._promote_node(info, k=cur.k)
            if node is None:
                self.recode_blocked_spread += 1
                from seaweedfs_tpu.utils import weedlog
                weedlog.warn_ratelimited(
                    f"autopilot_recode_spread:{vid}", 300.0,
                    "autopilot: volume %d wants codec %s but no node "
                    "holds %d+ shards; recode needs shard "
                    "consolidation (unbuilt) — not planned", vid,
                    target, cur.k, name="autopilot")
                continue
            if not self.buckets["codec"].try_acquire():
                break
            plans.append(self._new_plan(
                "codec_select", vid, node=node,
                from_codec=cur.tag, to_codec=target,
                collection=info.get("collection", ""),
                other_shard_nodes=others, reason=reason))
        return plans

    # -- balancing policy -------------------------------------------------

    def _plan_balancing(self, now: float,
                        vol_heat: dict[int, dict]) -> list[dict]:
        fc = getattr(self.master, "forecaster", None)
        if fc is None:
            return []
        try:
            snap = fc.snapshot()
        except Exception:
            return []
        filling = [d for d in snap.get("disks", [])
                   if d.get("predicted_full_seconds", 1e18)
                   < self.horizon_s]
        if not filling:
            return []
        topo = self.master.topo
        with topo._lock:
            free = {n.url: n.free_slots for n in topo.nodes.values()}
            by_node = {n.url: {vid: (v.size, v.replica_placement)
                               for vid, v in n.volumes.items()}
                       for n in topo.nodes.values()}
        filling_nodes = {d["vs"] for d in filling}
        active = self._active_vids()
        plans: list[dict] = []
        planned_src: set[str] = set()
        for d in sorted(filling,
                        key=lambda r: r["predicted_full_seconds"]):
            src = d["vs"]
            if src in planned_src:
                continue  # one move per filling node per tick
            targets = [u for u in sorted(free, key=lambda u: -free[u])
                       if u != src and u not in filling_nodes
                       and free.get(u, 0) > 0]
            if not targets:
                continue
            cands = []
            for vid, (size, placement) in by_node.get(src, {}).items():
                if vid in active or self._in_cooldown(vid, now):
                    continue
                try:
                    copies = t.ReplicaPlacement.parse(
                        placement or "000").copy_count
                except (ValueError, KeyError):
                    copies = 1
                if copies > 1:
                    # the move protocol relocates the ONLY copy; fixing
                    # replicated placement is volume.fix.replication's
                    # job, not a rebalance
                    continue
                rec = vol_heat.get(vid)
                rps = float(rec.get("rps", 0.0)) if rec else 0.0
                # coldest first; among equally cold, move the LARGEST
                # (fewest moves to relieve the disk)
                cands.append((rps, -size, vid))
            if not cands:
                continue
            cands.sort()
            rps, neg_size, vid = cands[0]
            if not self.buckets["balance"].try_acquire():
                break
            planned_src.add(src)
            plans.append(self._new_plan(
                "balance_move", vid, source=src, target=targets[0],
                reason={"predicted_full_seconds":
                        d["predicted_full_seconds"],
                        "dir": d.get("dir", ""),
                        "volume_bytes": -neg_size,
                        "volume_rps": round(rps, 3),
                        "horizon_s": self.horizon_s}))
        return plans

    # -- chunk promotion policy -------------------------------------------

    def _live_filers(self) -> list[str]:
        now = time.time()
        return sorted(a for a, ts in
                      self.master.cluster_members.get("filer", {}).items()
                      if now - ts < 30.0)

    def _plan_chunk_promote(self, now: float,
                            heat_view: dict) -> list[dict]:
        """Chunk-granular promotion: a chunk the fleet heat sketch shows
        sustained-hot gets seeded into its hot-tier home filer (the same
        rendezvous ring every filer computes), so the whole cluster
        serves it from one warm copy before organic misses converge
        there.  One plan per home filer per tick, paced by the governed
        `chunk` bucket."""
        if self.buckets["chunk"].rate <= 0:
            return []
        top = (heat_view.get("chunks") or {}).get("top", [])
        if not top:
            return []
        filers = self._live_filers()
        if not filers:
            return []
        from seaweedfs_tpu.utils.hashring import RendezvousRing
        ring = RendezvousRing(filers)
        active_fids = {f for p in self.plans.values()
                       if p["policy"] == "chunk_promote"
                       and p["state"] in ("planned", "approved",
                                          "executing")
                       for f in p.get("fids", [])}
        by_home: dict[str, list[tuple[float, str]]] = {}
        for rec in top:
            fid = str(rec.get("key", ""))
            if "," not in fid:
                continue  # not a blob fid
            rps = float(rec.get("rps", 0.0))
            if rps < self.chunk_rps or \
                    float(rec.get("sustained_s", 0.0)) < self.chunk_s:
                continue
            last = self._chunk_last.get(fid)
            if last is not None and now - last < self.cooldown_s:
                continue
            if fid in active_fids:
                continue
            home = ring.home(fid)
            if home is not None:
                by_home.setdefault(home, []).append((rps, fid))
        plans: list[dict] = []
        for home in sorted(by_home):
            if not self.buckets["chunk"].try_acquire():
                break
            batch = sorted(by_home[home], reverse=True)[:32]
            fids = [f for _, f in batch]
            plans.append(self._new_plan(
                "chunk_promote",
                vid=int(fids[0].partition(",")[0]),
                node=home, fids=fids,
                reason={"hottest_rps": round(batch[0][0], 3),
                        "chunks": len(fids),
                        "rps_floor": self.chunk_rps,
                        "sustained_floor_s": self.chunk_s}))
        # bound the per-fid cooldown map (hot sets churn; dead entries
        # must not accrete forever)
        if len(self._chunk_last) > 4096:
            self._chunk_last = {f: ts for f, ts
                                in self._chunk_last.items()
                                if now - ts < self.cooldown_s}
        return plans

    # -- the plan ledger --------------------------------------------------

    def _new_plan(self, policy: str, vid: int, **fields) -> dict:
        """Create one plan: a decision record + its own pinned trace
        root (the runbook's `cluster.trace <trace_id>` waterfall shows
        planning AND every actuator hop the execution later makes)."""
        self._plan_seq += 1
        pid = f"ap{self._plan_seq}"
        root = trace.new_root(sampled=True)
        trace.pin_trace(root.trace_id)
        plan = {"id": pid, "policy": policy, "vid": vid,
                "state": "planned", "created": round(time.time(), 3),
                "mode": autopilot_mode(), "trace_id": root.trace_id,
                **fields, "_root": root}
        with trace.span("autopilot.plan", parent=root, policy=policy,
                        vid=vid, plan=pid, mode=plan["mode"]):
            pass  # the planning decision itself, on the pinned trace
        self.plans[pid] = plan
        metrics.AUTOPILOT_PLANS.labels(policy).inc()
        log.info("autopilot: planned %s %s vid=%d %s trace=%s",
                 pid, policy, vid, fields.get("reason", {}),
                 root.trace_id)
        return plan

    def serialize_plan(self, plan: dict) -> dict:
        return {k: v for k, v in plan.items() if not k.startswith("_")}

    def approve(self, pid: str) -> dict:
        """planned -> approved, and launch the execution task.  The
        operator's runbook step in plan mode; automatic in execute
        mode."""
        plan = self.plans.get(pid)
        if plan is None:
            raise KeyError(pid)
        if plan["state"] != "planned":
            raise ValueError(
                f"plan {pid} is {plan['state']}, not planned")
        plan["state"] = "approved"
        task = asyncio.create_task(self._execute(plan))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return plan

    def abort(self, pid: str) -> dict:
        """planned/approved -> aborted.  An EXECUTING plan cannot be
        yanked from here — the actuators are abort-safe against their
        own failures, but an orphaned in-flight move would be worse
        than letting it finish or fail."""
        plan = self.plans.get(pid)
        if plan is None:
            raise KeyError(pid)
        if plan["state"] not in ("planned", "approved"):
            raise ValueError(
                f"plan {pid} is {plan['state']}; only planned/approved "
                "plans abort")
        plan["state"] = "aborted"
        plan["outcome"] = "operator abort"
        return plan

    def _gc_plans(self) -> None:
        terminal = [pid for pid, p in self.plans.items()
                    if p["state"] in ("done", "aborted")]
        for pid in terminal[:max(0, len(terminal) - self.KEEP_PLANS)]:
            del self.plans[pid]

    # -- execution (the ONLY actuator call site) --------------------------

    async def _post(self, node: str, path: str, body: dict,
                    timeout: float = 600.0) -> dict:
        from seaweedfs_tpu.utils.http import post_json
        self.actuator_calls += 1
        return await post_json(self.master._session, node, path, body,
                               timeout)

    async def _execute(self, plan: dict) -> None:
        if plan["state"] != "approved":
            # an abort landed between approve() scheduling this task
            # and the event loop running it: the operator was told the
            # plan died, so it must not execute
            return
        plan["state"] = "executing"
        policy, vid = plan["policy"], plan["vid"]
        t0 = time.monotonic()
        try:
            with trace.span("autopilot.execute", parent=plan.get("_root"),
                            policy=policy, vid=vid, plan=plan["id"]):
                if policy == "tiering_demote":
                    await self._exec_demote(plan)
                elif policy == "tiering_promote":
                    await self._exec_promote(plan)
                elif policy == "balance_move":
                    await self._exec_move(plan)
                elif policy == "chunk_promote":
                    await self._exec_chunk_promote(plan)
                elif policy == "codec_select":
                    await self._exec_recode(plan)
                else:
                    raise RuntimeError(f"unknown policy {policy}")
            plan["state"] = "done"
            metrics.AUTOPILOT_ACTIONS.labels(policy, "done").inc()
        except Exception as e:
            plan["state"] = "aborted"
            plan["error"] = str(e)
            metrics.AUTOPILOT_ACTIONS.labels(policy, "aborted").inc()
            log.warning("autopilot: %s %s vid=%d aborted: %s",
                        plan["id"], policy, vid, e)
        finally:
            # success AND failure arm the cooldown: a broken actuator
            # must not be retried at tick cadence.  Chunk plans cool
            # down per-fid (their vid is incidental — arming the volume
            # cooldown would block unrelated volume-level plans)
            if policy == "chunk_promote":
                for fid in plan.get("fids", []):
                    self._chunk_last[fid] = time.time()
            else:
                self._last_action[vid] = (time.time(), policy)
            plan["seconds"] = round(time.monotonic() - t0, 3)

    async def _exec_demote(self, plan: dict) -> None:
        """Hand the volume to the paced conversion pipeline, sealed:
        once the (tmp+rename) conversion commits, the scheduler mounts
        the shard set and retires the .dat.  The scheduler owns pacing,
        interference pauses, and dead-node re-queues from here."""
        self.actuator_calls += 1
        accepted = self.master.convert.enqueue([plan["vid"]], seal=True)
        plan["outcome"] = "enqueued" if accepted else "already queued"

    async def _exec_promote(self, plan: dict) -> None:
        """Decode-and-thaw on the shard-majority node, then retire
        remnant shards elsewhere.  Tiering traffic books as
        class=convert (the same plane its demote twin rides)."""
        vid, node = plan["vid"], plan["node"]
        with netflow.flow("convert"):
            data = await self._post(node, "/admin/volume/unconvert",
                                    {"volume": vid,
                                     "collection":
                                         plan.get("collection", "")})
            retired: dict[str, list[int]] = {}
            for url, sids in (plan.get("other_shard_nodes")
                              or {}).items():
                try:
                    await self._post(url, "/admin/ec/delete_shards",
                                     {"volume": vid, "shards": sids},
                                     timeout=60.0)
                    retired[url] = sids
                except Exception as e:
                    # the volume IS promoted; stray shards are garbage,
                    # not danger (heartbeat diffing sees them gone when
                    # the node returns and retries via a later plan)
                    log.warning("autopilot: remnant shard retirement "
                                "on %s failed: %s", url, e)
        plan["outcome"] = {"decoded": data.get("decoded"),
                           "thawed": data.get("thawed"),
                           "remnants_retired": retired}

    async def _exec_move(self, plan: dict) -> None:
        """One staged, CRC-verified, abort-safe volume move, driven by
        the source volume server."""
        with netflow.flow("rebalance"):
            data = await self._post(
                plan["source"], "/admin/volume/move",
                {"volume": plan["vid"], "target": plan["target"]})
        plan["outcome"] = {"crc": data.get("crc"),
                           "target": data.get("target")}

    async def _exec_recode(self, plan: dict) -> None:
        """One in-place codec change on the shard-majority node, then
        remnant-shard retirement elsewhere — the same shape as promote,
        riding the convert traffic class (it IS a re-encode)."""
        vid = plan["vid"]
        with netflow.flow("convert"):
            data = await self._post(plan["node"], "/admin/ec/recode",
                                    {"volume": vid,
                                     "codec": plan["to_codec"],
                                     "collection":
                                         plan.get("collection", "")},
                                    timeout=1800.0)
            retired: dict[str, list[int]] = {}
            for url, sids in (plan.get("other_shard_nodes")
                              or {}).items():
                try:
                    await self._post(url, "/admin/ec/delete_shards",
                                     {"volume": vid, "shards": sids},
                                     timeout=60.0)
                    retired[url] = sids
                except Exception as e:
                    log.warning("autopilot: remnant shard retirement "
                                "on %s failed: %s", url, e)
        plan["outcome"] = {"codec": data.get("codec"),
                           "from": data.get("from"),
                           "shards": data.get("shards"),
                           "remnants_retired": retired}

    async def _exec_chunk_promote(self, plan: dict) -> None:
        """Seed the batch into its home filer's hot tier.  The pull-
        through bytes are speculative, so they book as class=readahead
        — the governor's interference index sees and paces them."""
        with netflow.flow("readahead"):
            data = await self._post(plan["node"], "/__hot__/seed",
                                    {"fids": plan["fids"]}, timeout=120.0)
        plan["outcome"] = {"seeded": data.get("seeded"),
                           "skipped": data.get("skipped")}

    # -- views ------------------------------------------------------------

    def status(self) -> dict:
        now = time.time()
        counts = {s: 0 for s in PLAN_STATES}
        for p in self.plans.values():
            counts[p["state"]] = counts.get(p["state"], 0) + 1
        return {
            "mode": autopilot_mode(),
            "ticks": self.ticks,
            "actuator_calls": self.actuator_calls,
            "promote_blocked_spread": self.promote_blocked_spread,
            "recode_blocked_spread": self.recode_blocked_spread,
            "states": counts,
            "knobs": {"cold_rps": self.cold_rps, "cold_s": self.cold_s,
                      "hot_rps": self.hot_rps, "hot_s": self.hot_s,
                      "chunk_rps": self.chunk_rps,
                      "chunk_s": self.chunk_s,
                      "cooldown_s": self.cooldown_s,
                      "full_horizon_s": self.horizon_s},
            "buckets": {name: {"rate_per_s": b.rate, "burst": b.burst,
                               "tokens": round(b.tokens, 2)}
                        for name, b in self.buckets.items()},
            "hysteresis": {
                "cold_tracking": {str(v): round(now - ts, 1)
                                  for v, ts in self._cold_since.items()},
                "cooldowns": {str(v): {"policy": pol,
                                       "remaining_s": round(
                                           max(0.0, self.cooldown_s -
                                               (now - ts)), 1)}
                              for v, (ts, pol)
                              in self._last_action.items()
                              if now - ts < self.cooldown_s},
            },
            "plans": [self.serialize_plan(p)
                      for p in list(self.plans.values())[-50:]],
        }

    def headline(self) -> dict:
        """The compact block /maintenance/status embeds."""
        st = {s: 0 for s in PLAN_STATES}
        recent = []
        for p in self.plans.values():
            st[p["state"]] = st.get(p["state"], 0) + 1
        for p in list(self.plans.values())[-5:]:
            recent.append({"id": p["id"], "policy": p["policy"],
                           "vid": p["vid"], "state": p["state"]})
        return {"mode": autopilot_mode(), "ticks": self.ticks,
                "states": st, "recent": recent}
