"""Master-side fleet-conversion scheduler: paced background EC encode.

The data plane (ops/fleet_convert.py + the volume server's
/admin/ec/fleet_convert) can saturate every chip on a node; THIS module
decides when it is allowed to.  Conversion is planned background work —
the online-EC interference study (PAPERS.md, arXiv 1709.05365) shows a
foreground-speed conversion burst is indistinguishable from a repair
storm to the serving path — so the scheduler:

- queues volumes (``/maintenance/convert`` POST, the shell, or the
  autopilot demote path) and groups them by owning volume server, up to
  WEEDTPU_CONVERT_BATCH volumes per node call so each node's device
  stream gets real multi-volume batches to interleave;
- paces launches through a token bucket (WEEDTPU_CONVERT_RATE volumes/s,
  WEEDTPU_CONVERT_BURST) and never converts on a node the repair planner
  is actively repairing — loss recovery always outranks conversion;
- PAUSES while any alert EXACTLY named in WEEDTPU_CONVERT_PAUSE_ALERTS
  fires (default: ``interference_high,disk_full_soon``; exact-name
  matching — substring matching let a rule like
  ``no_interference_baseline`` pause conversion, the same bug class as
  the internal-path prefix fix).  When the interference governor
  (stats/interference.py) is active it supersedes the
  ``interference_high`` pause: continuous rate backoff replaces the
  binary stop, while capacity pauses (``disk_full_soon``) still halt
  conversion outright — a full disk is not a pacing problem;
- books every orchestration byte as netflow class=convert and rides the
  process retry budget (class ``convert``) with decorrelated-jitter
  backoff: a node that dies mid-conversion gets its volumes RE-QUEUED,
  not dropped — the volume server's tmp+rename contract means nothing
  partial is ever visible there.

Ticked by the master's background loop next to the repair planner, and
deterministically via POST /maintenance/convert {"tick": true}.
"""

from __future__ import annotations

import logging
import os
import time

from seaweedfs_tpu.maintenance.repair import TokenBucket, _env_float
from seaweedfs_tpu.security.tls import scheme as _tls_scheme
from seaweedfs_tpu.stats import metrics, netflow, trace
from seaweedfs_tpu.utils import resilience

log = logging.getLogger("convert")


class ConvertScheduler:
    """Queue + pacing for fleet EC conversion, one per master."""

    def __init__(self, master, *, rate: float | None = None,
                 burst: float | None = None,
                 node_batch: int | None = None):
        self.master = master
        self.bucket = TokenBucket(
            rate if rate is not None
            else _env_float("WEEDTPU_CONVERT_RATE", 2.0),
            burst if burst is not None
            else _env_float("WEEDTPU_CONVERT_BURST", 8.0))
        self.node_batch = node_batch if node_batch \
            else int(_env_float("WEEDTPU_CONVERT_BATCH", 4))
        self.pause_alerts = tuple(
            s.strip() for s in os.environ.get(
                "WEEDTPU_CONVERT_PAUSE_ALERTS",
                "interference_high,disk_full_soon").split(",")
            if s.strip())
        self.queued: list[int] = []
        self._queued_set: set[int] = set()
        self.active: set[int] = set()
        self._backoff: dict[int, tuple[int, float]] = {}
        self.history: list[dict] = []
        self.converted = 0
        self.failed_final = 0
        self.paused_reason: str | None = None
        # re-queue bookkeeping, surfaced as weedtpu_convert_requeued_total
        # and the /maintenance/convert "requeued" block: re-queues were
        # only visible in logs, and the autopilot must see the parked
        # backlog to avoid re-planning volumes already waiting here
        self.requeued_by_reason: dict[str, int] = {}
        # vids whose conversion should be SEALED on success: mount the
        # shard set and delete the .dat/.idx, so the EC set SERVES (the
        # autopilot demote's full tiering semantics; plain conversions
        # keep the frozen .dat as the fast read path)
        self._seal: set[int] = set()
        # seals that converted but then half-failed (mounted, .dat not
        # deleted — or neither): once the mount landed the ledger reads
        # the vid as EC, so the autopilot never re-plans it; the tick
        # retries these until the .dat is gone
        self._seal_stuck: set[int] = set()

    # -- intake ----------------------------------------------------------

    def enqueue(self, vids, seal: bool = False) -> list[int]:
        """Queue volumes for conversion (idempotent per vid).  With
        ``seal=True`` a successful conversion also mounts the shard set
        and deletes the source .dat — the demote-to-EC tiering step."""
        accepted = []
        for v in vids:
            try:
                vid = int(v)
            except (TypeError, ValueError):
                continue
            if seal:
                self._seal.add(vid)
            if vid in self._queued_set or vid in self.active:
                continue
            self.queued.append(vid)
            self._queued_set.add(vid)
            accepted.append(vid)
        return accepted

    def requeue(self, vids, error: str,
                reason: str = "node_error") -> None:
        """A node call failed: its volumes go back on the queue with
        per-vid exponential backoff (decorrelated jitter), never lost."""
        now = time.monotonic()
        for vid in vids:
            n = self._backoff.get(vid, (0, 0.0))[0] + 1
            delay = resilience.backoff_delay(n, 2.0, 300.0)
            self._backoff[vid] = (n, now + delay)
            if vid not in self._queued_set:
                self.queued.append(vid)
                self._queued_set.add(vid)
            metrics.CONVERT_REQUEUED.labels(reason).inc()
        self.requeued_by_reason[reason] = \
            self.requeued_by_reason.get(reason, 0) + len(vids)
        log.warning("conversion re-queued %s after: %s",
                    sorted(vids), error)

    # -- pacing gates ----------------------------------------------------

    def _paused_by_alert(self) -> str | None:
        """Name of a firing alert that pauses conversion, if any.
        EXACT-name matching against WEEDTPU_CONVERT_PAUSE_ALERTS — a
        rule named ``no_interference_baseline`` must not pause anything
        (the PR 12 exact-or-slash lesson, applied to alert names).  The
        interference-pacing rule is skipped while the governor is
        active: continuous backoff replaces the binary pause."""
        alerts = getattr(self.master, "alerts", None)
        if alerts is None or not self.pause_alerts:
            return None
        governed: str | None = None
        gov = getattr(self.master, "governor", None)
        if gov is not None:
            from seaweedfs_tpu.stats.interference import governor_enabled
            if governor_enabled():
                governed = gov.INTERFERENCE_ALERT
        try:
            for rule in alerts.status().get("rules", []):
                if rule.get("state") != "firing":
                    continue
                name = rule.get("name", "")
                if name == governed:
                    continue  # the governor paces this one instead
                if name in self.pause_alerts:
                    return name
        except Exception:
            return None
        return None

    def _node_of(self, vid: int) -> str | None:
        """The volume server holding `vid` as a plain (non-EC) volume."""
        topo = self.master.topo
        with topo._lock:
            for url, node in topo.nodes.items():
                if vid in node.volumes:
                    return url
        return None

    # -- status ----------------------------------------------------------

    def status(self) -> dict:
        now = time.monotonic()
        return {
            "queued": list(self.queued),
            "active": sorted(self.active),
            "tokens": round(self.bucket.tokens, 2),
            "rate_per_s": self.bucket.rate,
            "node_batch": self.node_batch,
            "paused": self.paused_reason,
            "pause_alerts": list(self.pause_alerts),
            "converted": self.converted,
            "failed": self.failed_final,
            "backoffs": {str(v): {"failures": f,
                                  "retry_in_s": round(max(0.0, ts - now),
                                                      1)}
                         for v, (f, ts) in self._backoff.items()},
            # the re-queue backlog as structured data: total per reason
            # plus the vids currently parked behind a backoff — the
            # autopilot reads this (and `queued`/`active` above) so it
            # never re-plans a volume already in the pipeline
            "requeued": {
                "total": sum(self.requeued_by_reason.values()),
                "by_reason": dict(self.requeued_by_reason),
                "parked": sorted(self._backoff),
            },
            "sealing": sorted(self._seal),
            "seal_stuck": sorted(self._seal_stuck),
            "history": self.history[-10:],
        }

    # -- execution -------------------------------------------------------

    async def tick(self) -> list[dict]:
        """Launch as many paced node-batches as tokens allow.  Returns
        the launched action records (awaited to completion: conversion
        ticks are deterministic for tests and the chaos driver, and the
        per-node HTTP call itself is the long-running part)."""
        self.paused_reason = self._paused_by_alert()
        if self.paused_reason:
            return []
        await self._retry_stuck_seals()
        if not self.queued:
            return []
        repair_active = dict(getattr(self.master.maintenance,
                                     "_active_nodes", {}))
        now = time.monotonic()
        by_node: dict[str, list[int]] = {}
        unplaceable: list[int] = []
        for vid in list(self.queued):
            bk = self._backoff.get(vid)
            if bk and bk[1] > now:
                continue  # backing off: stays queued for a later tick
            node = self._node_of(vid)
            if node is None:
                if vid in self._backoff:
                    # its node failed a conversion recently and may have
                    # aged out of the topology while down: keep the vid
                    # queued for the node's return (re-queued, never
                    # dropped) instead of declaring it unplaceable
                    continue
                unplaceable.append(vid)
                continue
            if repair_active.get(node):
                continue  # repair on that node outranks conversion
            if len(by_node.setdefault(node, [])) < self.node_batch:
                by_node[node].append(vid)
        # volumes with no locatable .dat (already EC, deleted) drop out
        for vid in unplaceable:
            self._drop(vid)
            self._seal.discard(vid)
            self.history.append({"vid": vid, "outcome": "unplaceable"})
        actions: list[dict] = []
        for node, vids in by_node.items():
            granted = [v for v in vids if self.bucket.try_acquire(1.0)]
            if not granted:  # dry bucket: the rest stays queued
                continue
            for v in granted:
                self._drop(v)
                self.active.add(v)
            actions.append(await self._convert_on(node, granted))
        del self.history[:-100]
        return actions

    def _drop(self, vid: int) -> None:
        if vid in self._queued_set:
            self._queued_set.discard(vid)
            try:
                self.queued.remove(vid)
            except ValueError:
                pass

    async def _convert_on(self, node: str, vids: list[int]) -> dict:
        import aiohttp
        t0 = time.monotonic()
        rec = {"node": node, "volumes": list(vids)}
        try:
            # class=convert on every hop (the volume server's middleware
            # re-enters the class for the hops IT makes on our behalf);
            # retries ride the process-wide budget under their own class
            # so a conversion storm can't starve repair retries
            with netflow.flow("convert"), \
                    trace.span("convert.batch", node=node,
                               volumes=len(vids)):
                async def _once():
                    async with self.master._session.post(
                            f"{_tls_scheme()}://{node}"
                            f"/admin/ec/fleet_convert",
                            json={"volumes": vids},
                            timeout=aiohttp.ClientTimeout(total=600)
                    ) as r:
                        try:
                            data = await r.json()
                        except Exception:
                            data = {}
                        if r.status != 200:
                            raise RuntimeError(
                                f"{node}: HTTP {r.status} "
                                f"{data.get('error', '')}".strip())
                        return data

                # inline-retry ONLY connection-level failures (refused,
                # reset): a timeout may mean the conversion is STILL
                # RUNNING server-side, and an HTTP error won't change on
                # replay — both fall through to requeue-with-backoff,
                # which revisits once the node's job table settles
                data = await resilience.retry_async(
                    _once, attempts=2, cls="convert",
                    retry_on=(ConnectionError,
                              aiohttp.ClientConnectionError))
            done = [int(v) for v in data.get("converted", [])]
            rec.update(outcome="ok", converted=done,
                       bytes=data.get("bytes"),
                       wall_s=data.get("wall_s"))
            self.converted += len(done)
            for vid in vids:
                self._backoff.pop(vid, None)
            sealed = await self._seal_converted(node, done)
            if sealed:
                rec["sealed"] = sealed
            missed = [v for v in vids if v not in done]
            if missed:
                # the node skipped some (busy/not found): try again later
                self.requeue(missed, f"skipped by {node}",
                             reason="skipped")
        except Exception as e:
            rec.update(outcome=f"error: {e}")
            self.requeue(vids, str(e))
        finally:
            for vid in vids:
                self.active.discard(vid)
        rec["seconds"] = round(time.monotonic() - t0, 3)
        self.history.append(rec)
        return rec

    async def _seal_converted(self, node: str, done: list[int]
                              ) -> list[int]:
        """Finish the demote for seal-flagged conversions: mount the
        committed shard set and delete the source .dat/.idx, so the EC
        set SERVES (and the disk space comes back).  Runs only AFTER
        the tmp+rename commit — a seal failure leaves the safe
        intermediate state (frozen .dat + full shard set), parked on
        _seal_stuck and retried by later ticks (once the mount landed
        the ledger reads the vid as EC, so the AUTOPILOT cannot re-plan
        it — the retry must live here), never a volume with neither
        copy."""
        from seaweedfs_tpu.utils.http import post_json
        sealed: list[int] = []
        for vid in done:
            if vid not in self._seal:
                continue
            try:
                with netflow.flow("convert"), \
                        trace.span("convert.seal", node=node, vid=vid):
                    for path in ("/admin/ec/mount",
                                 "/admin/volume/delete"):
                        await post_json(self.master._session, node,
                                        path, {"volume": vid},
                                        timeout=60.0)
                self._seal.discard(vid)
                self._seal_stuck.discard(vid)
                sealed.append(vid)
            except Exception as e:
                self._seal_stuck.add(vid)
                log.warning("seal of converted volume %d on %s failed "
                            "(stays frozen with its shard set; will "
                            "retry): %s", vid, node, e)
        return sealed

    async def _retry_stuck_seals(self) -> None:
        """Finish seals whose mount/delete hop failed after the
        conversion committed.  Both steps are idempotent (re-mount of a
        mounted set is a no-op, delete of a deleted .dat is a no-op),
        so retrying is always safe; a vid whose node is gone stays
        parked for the node's return."""
        for vid in list(self._seal_stuck):
            if vid in self.active:
                continue
            node = self._node_of(vid)
            if node is None:
                # .dat already gone (delete succeeded, mount was the
                # failure — or the node left): nothing further to seal
                # here once no node reports the plain volume
                self._seal_stuck.discard(vid)
                self._seal.discard(vid)
                continue
            await self._seal_converted(node, [vid])
