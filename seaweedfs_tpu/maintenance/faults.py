"""Test-only fault injection: make the heal loop provable end-to-end.

Faults are injected either through the WEEDTPU_FAULTS env var at volume
server start, or live through the loopback-only /admin/faults endpoint.
Supported actions:

  delete_shard:vid:sid          remove one EC shard file (and close its fd
                                in the mounted EcVolume) — "disk died"
  flip_bit:vid:sid:offset[:bit] XOR one bit in a shard file in place —
                                silent corruption the scrubber must catch
  delay_shard_read:ms           stall every /admin/ec/shard_read response —
                                a slow peer for degraded-read tests

Env spec: directives joined by ';', e.g.
  WEEDTPU_FAULTS="delete_shard:1:3;flip_bit:1:7:4096"
"""

from __future__ import annotations

import logging
import os

from seaweedfs_tpu.storage.ec import layout

log = logging.getLogger("faults")


def parse_env(spec: str) -> list[dict]:
    out: list[dict] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        action = fields[0]
        try:
            if action == "delete_shard":
                out.append({"action": action, "volume": int(fields[1]),
                            "shard": int(fields[2])})
            elif action == "flip_bit":
                out.append({"action": action, "volume": int(fields[1]),
                            "shard": int(fields[2]),
                            "offset": int(fields[3]),
                            "bit": int(fields[4]) if len(fields) > 4 else 0})
            elif action == "delay_shard_read":
                out.append({"action": action, "ms": float(fields[1])})
            else:
                log.warning("faults: unknown directive %r", part)
        except (IndexError, ValueError):
            log.warning("faults: malformed directive %r", part)
    return out


def _ec_base(store, vid: int) -> str | None:
    for loc in store.locations:
        for cand in (loc.base_path(vid, loc.collections.get(vid, "")),
                     loc.base_path(vid)):
            if os.path.exists(cand + ".ecx") or any(
                    os.path.exists(cand + layout.to_ext(i))
                    for i in range(layout.TOTAL_SHARDS)):
                return cand
    return None


def delete_shard(store, vid: int, sid: int) -> bool:
    """Remove one shard file; the mounted EcVolume drops its fd so the
    next heartbeat reports the loss."""
    base = _ec_base(store, vid)
    if base is None:
        return False
    p = base + layout.to_ext(sid)
    if os.path.exists(p):
        os.remove(p)
    ev = store.get_ec_volume(vid)
    if ev is not None:
        f = ev.shards.pop(sid, None)
        if f is not None:
            f.close()
    log.warning("faults: deleted shard %d of volume %d", sid, vid)
    return True


def flip_bit(store, vid: int, sid: int, offset: int, bit: int = 0) -> bool:
    """XOR one bit of a shard file in place (the mounted EcVolume reads
    through the page cache, so the corruption is immediately live)."""
    base = _ec_base(store, vid)
    if base is None:
        return False
    p = base + layout.to_ext(sid)
    if not os.path.exists(p):
        return False
    size = os.path.getsize(p)
    if not size:
        return False
    offset %= size
    with open(p, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (1 << (bit % 8))]))
    log.warning("faults: flipped bit %d at offset %d of volume %d "
                "shard %d", bit, offset, vid, sid)
    return True


def apply(store, fault: dict) -> dict:
    """Apply one parsed fault to a Store; returns {**fault, ok: bool}.
    delay_shard_read is server state, not store state — the volume
    server handles it before calling here."""
    action = fault.get("action")
    ok = False
    if action == "delete_shard":
        ok = delete_shard(store, int(fault["volume"]), int(fault["shard"]))
    elif action == "flip_bit":
        ok = flip_bit(store, int(fault["volume"]), int(fault["shard"]),
                      int(fault["offset"]), int(fault.get("bit", 0)))
    return dict(fault, ok=ok)
