"""Test-only fault injection: make the heal AND resilience loops provable.

Two fault planes live here:

**Store faults** (applied to one volume server's Store) — injected
through the WEEDTPU_FAULTS env var at volume server start, or live
through the loopback-only /admin/faults endpoint:

  delete_shard:vid:sid          remove one EC shard file (and close its fd
                                in the mounted EcVolume) — "disk died"
  flip_bit:vid:sid:offset[:bit] XOR one bit in a shard file in place —
                                silent corruption the scrubber must catch
  delay_shard_read:ms           stall every /admin/ec/shard_read response —
                                a slow peer for degraded-read tests
  delay_file_pull:ms            stall every /admin/file peer pull — holds a
                                volume copy/move open so chaos cells can
                                kill a node mid-transfer

**Process-wide faults** (network + disk) — a module-level registry the
HTTP stacks and the EC shard writer consult, so an in-process chaos
cluster (every server in one interpreter) can cut links and fail disks
without containers:

  partition:a:b                 refuse dials between a and b (each a role
                                name like "filer"/"volume"/"master"/"s3",
                                a netloc, "region:<name>", or "*");
                                bidirectional
  peer_latency:dst:ms[:jitter]  add latency to every dial/request toward
                                dst (role or netloc)
  region_partition:a:b          refuse every dial CROSSING the a<->b
                                region boundary (nodes/callers declare
                                regions via register_region); traffic
                                inside either region is untouched
  wan_latency:a:b:ms[:jitter]   add latency only to dials crossing the
                                a<->b region boundary — the WAN RTT
                                knob; intra-region dials stay fast
  peer_error:dst:pct            fail requests toward dst with probability
                                pct/100 (injected ConnectionResetError)
  shard_write_error:EIO|ENOSPC  every EC shard write (encode/rebuild)
                                raises that OSError; "off" clears
  clear_net                     drop every process-wide fault

Env spec: directives joined by ';', e.g.
  WEEDTPU_FAULTS="delete_shard:1:3;flip_bit:1:7:4096"

Servers call ``register_node(netloc, role)`` at start so role↔role
partitions resolve a dial's destination netloc back to its role.  All
check_* hooks are O(1) no-ops while no process-wide fault is armed
(one module-global truthiness test on the hot path).
"""

from __future__ import annotations

import errno as _errno
import logging
import os
import random
import threading

from seaweedfs_tpu.storage.ec import layout

log = logging.getLogger("faults")

_rand = random.Random()

# -- process-wide network/disk fault registry ----------------------------

_lock = threading.Lock()
_partitions: set[tuple[str, str]] = set()        # bidirectional pairs
_latency: dict[str, tuple[float, float]] = {}    # dst -> (ms, jitter_ms)
_error_rate: dict[str, float] = {}               # dst -> probability 0..1
_disk_shard_write: str | None = None             # "EIO" | "ENOSPC" | None
_roles: dict[str, str] = {}                      # netloc -> role
_regions: dict[str, str] = {}                    # netloc -> region name
# unordered region pair -> (ms, jitter_ms): latency charged only when a
# dial CROSSES that boundary (dst-keyed peer_latency can't express
# this — it would also slow region-internal dials toward the same dst)
_wan_latency: dict[tuple[str, str], tuple[float, float]] = {}
NET_ACTIVE = False  # cheap hot-path gate; True while any fault is armed


def register_node(netloc: str, role: str) -> None:
    """Record netloc→role so role↔role partitions can match a dial's
    destination.  Called by every server at start; harmless twice."""
    _roles[netloc] = role


def register_region(netloc: str, region: str) -> None:
    """Record netloc→region so region_partition / wan_latency faults can
    tell which dials cross a region boundary.  The GeoCluster harness
    registers every node of both clusters; single-region deployments
    never call this and pay nothing."""
    if region:
        _regions[netloc] = region


def _recompute_active() -> None:
    global NET_ACTIVE
    NET_ACTIVE = bool(_partitions or _latency or _error_rate
                      or _wan_latency or _disk_shard_write)


def clear_net() -> None:
    global _disk_shard_write
    with _lock:
        _partitions.clear()
        _latency.clear()
        _error_rate.clear()
        _wan_latency.clear()
        _disk_shard_write = None
        _recompute_active()


def add_partition(a: str, b: str) -> None:
    with _lock:
        _partitions.add((a, b))
        _recompute_active()


def remove_partition(a: str, b: str) -> None:
    with _lock:
        _partitions.discard((a, b))
        _partitions.discard((b, a))
        _recompute_active()


def set_peer_latency(dst: str, ms: float, jitter_ms: float = 0.0) -> None:
    with _lock:
        if ms <= 0 and jitter_ms <= 0:
            _latency.pop(dst, None)
        else:
            _latency[dst] = (ms, jitter_ms)
        _recompute_active()


def set_peer_error_rate(dst: str, pct: float) -> None:
    with _lock:
        if pct <= 0:
            _error_rate.pop(dst, None)
        else:
            _error_rate[dst] = min(1.0, pct / 100.0)
        _recompute_active()


def set_shard_write_error(kind: str | None) -> None:
    global _disk_shard_write
    with _lock:
        _disk_shard_write = kind if kind in ("EIO", "ENOSPC") else None
        _recompute_active()


def set_wan_latency(region_a: str, region_b: str, ms: float,
                    jitter_ms: float = 0.0) -> None:
    key = (min(region_a, region_b), max(region_a, region_b))
    with _lock:
        if ms <= 0 and jitter_ms <= 0:
            _wan_latency.pop(key, None)
        else:
            _wan_latency[key] = (ms, jitter_ms)
        _recompute_active()


def net_snapshot() -> dict:
    with _lock:
        return {"partitions": sorted(list(p) for p in _partitions),
                "latency_ms": {d: list(v) for d, v in _latency.items()},
                "wan_latency_ms": {f"{a}<->{b}": list(v)
                                   for (a, b), v in _wan_latency.items()},
                "error_rate": {d: round(p * 100.0, 1)
                               for d, p in _error_rate.items()},
                "shard_write_error": _disk_shard_write,
                "nodes": dict(_roles),
                "regions": dict(_regions)}


def _ids(netloc_or_role: str) -> set[str]:
    """Every identity a side of a dial answers to: its literal name, its
    registered role and region (for netlocs), and the wildcard."""
    out = {netloc_or_role, "*"}
    role = _roles.get(netloc_or_role)
    if role:
        out.add(role)
    region = _regions.get(netloc_or_role)
    if region:
        out.add("region:" + region)
    return out


def _side_ids(src) -> set[str]:
    """Identity set for a dial's caller side: a plain role string, or an
    iterable of identities (a region-aware client passes
    ``{role, "region:<r>"}`` so region faults can match it — clients
    don't know their own netloc, so register_region can't help them)."""
    if isinstance(src, str):
        return _ids(src)
    out: set[str] = set()
    for s in src:
        out |= _ids(s)
    return out or {"*"}


def check_dial(src, dst_netloc: str) -> None:
    """Raise ConnectionRefusedError when (src, dst) crosses an armed
    partition.  `src` is the caller's role (clients don't know their own
    netloc) or an iterable of identities; `dst_netloc` resolves to its
    role/region via register_node/register_region."""
    if not NET_ACTIVE:
        return
    srcs = _side_ids(src)
    dsts = _ids(dst_netloc)
    with _lock:
        parts = list(_partitions)
    for a, b in parts:
        if (a in srcs and b in dsts) or (a in dsts and b in srcs):
            raise ConnectionRefusedError(
                _errno.ECONNREFUSED,
                f"faults: partition {a}<->{b} refuses {src} -> "
                f"{dst_netloc}")


def dial_latency_s(dst_netloc: str) -> float:
    """Injected latency (seconds) for a request toward dst, 0 when
    none is armed."""
    if not NET_ACTIVE:
        return 0.0
    with _lock:
        lat = dict(_latency)
    for key in _ids(dst_netloc):
        if key in lat:
            ms, jitter = lat[key]
            return max(0.0, ms + _rand.uniform(-jitter, jitter)) / 1000.0
    return 0.0


def maybe_inject_error(dst_netloc: str) -> None:
    """Raise ConnectionResetError with the armed probability for dst."""
    if not NET_ACTIVE:
        return
    with _lock:
        rates = dict(_error_rate)
    for key in _ids(dst_netloc):
        p = rates.get(key)
        if p is not None and _rand.random() < p:
            raise ConnectionResetError(
                _errno.ECONNRESET,
                f"faults: injected error toward {dst_netloc}")


def wan_latency_s(src, dst_netloc: str) -> float:
    """Injected WAN latency (seconds) when the (src, dst) dial crosses
    an armed region boundary, 0 otherwise."""
    if not NET_ACTIVE:
        return 0.0
    with _lock:
        lat = dict(_wan_latency)
    if not lat:
        return 0.0
    srcs = _side_ids(src)
    dsts = _ids(dst_netloc)
    for (a, b), (ms, jitter) in lat.items():
        ra, rb = "region:" + a, "region:" + b
        if (ra in srcs and rb in dsts) or (ra in dsts and rb in srcs):
            return max(0.0, ms + _rand.uniform(-jitter, jitter)) / 1000.0
    return 0.0


def check_net(src, dst_netloc: str) -> float:
    """Combined client hook: partition check + error injection; returns
    the latency (seconds) the caller should sleep.  One call site per
    HTTP stack keeps the hooks from drifting apart.  `src` is a role
    string or an iterable of identities (role + "region:<r>")."""
    if not NET_ACTIVE:
        return 0.0
    check_dial(src, dst_netloc)
    maybe_inject_error(dst_netloc)
    return dial_latency_s(dst_netloc) + wan_latency_s(src, dst_netloc)


def check_shard_write(path: str) -> None:
    """Raise the armed disk error before an EC shard write (encode and
    rebuild both call here before opening their tmp shard files)."""
    if not NET_ACTIVE or _disk_shard_write is None:
        return
    if _disk_shard_write == "ENOSPC":
        raise OSError(_errno.ENOSPC,
                      f"faults: injected ENOSPC writing shards for {path}")
    raise OSError(_errno.EIO,
                  f"faults: injected EIO writing shards for {path}")


# -- env / admin parsing -------------------------------------------------

def parse_env(spec: str) -> list[dict]:
    out: list[dict] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        action = fields[0]
        try:
            if action == "delete_shard":
                out.append({"action": action, "volume": int(fields[1]),
                            "shard": int(fields[2])})
            elif action == "flip_bit":
                out.append({"action": action, "volume": int(fields[1]),
                            "shard": int(fields[2]),
                            "offset": int(fields[3]),
                            "bit": int(fields[4]) if len(fields) > 4 else 0})
            elif action in ("delay_shard_read", "delay_file_pull"):
                out.append({"action": action, "ms": float(fields[1])})
            elif action in ("partition", "unpartition",
                            "region_partition", "region_unpartition"):
                out.append({"action": action, "a": fields[1],
                            "b": fields[2]})
            elif action == "wan_latency":
                out.append({"action": action, "a": fields[1],
                            "b": fields[2], "ms": float(fields[3]),
                            "jitter": float(fields[4])
                            if len(fields) > 4 else 0.0})
            elif action == "peer_latency":
                out.append({"action": action, "dst": fields[1],
                            "ms": float(fields[2]),
                            "jitter": float(fields[3])
                            if len(fields) > 3 else 0.0})
            elif action == "peer_error":
                out.append({"action": action, "dst": fields[1],
                            "pct": float(fields[2])})
            elif action == "shard_write_error":
                out.append({"action": action, "kind": fields[1]})
            elif action == "clear_net":
                out.append({"action": action})
            else:
                log.warning("faults: unknown directive %r", part)
        except (IndexError, ValueError):
            log.warning("faults: malformed directive %r", part)
    return out


def apply_net(fault: dict) -> bool:
    """Apply one parsed PROCESS-WIDE fault; False when it isn't one
    (store faults go through apply())."""
    action = fault.get("action")
    if action == "partition":
        add_partition(str(fault["a"]), str(fault["b"]))
    elif action == "unpartition":
        remove_partition(str(fault["a"]), str(fault["b"]))
    elif action == "region_partition":
        add_partition("region:" + str(fault["a"]),
                      "region:" + str(fault["b"]))
    elif action == "region_unpartition":
        remove_partition("region:" + str(fault["a"]),
                         "region:" + str(fault["b"]))
    elif action == "wan_latency":
        set_wan_latency(str(fault["a"]), str(fault["b"]),
                        float(fault["ms"]),
                        float(fault.get("jitter", 0.0)))
    elif action == "peer_latency":
        set_peer_latency(str(fault["dst"]), float(fault["ms"]),
                         float(fault.get("jitter", 0.0)))
    elif action == "peer_error":
        set_peer_error_rate(str(fault["dst"]), float(fault["pct"]))
    elif action == "shard_write_error":
        set_shard_write_error(str(fault.get("kind", "")) or None)
    elif action == "clear_net":
        clear_net()
    else:
        return False
    log.warning("faults: applied %s", fault)
    return True


# -- store faults --------------------------------------------------------

def _ec_base(store, vid: int) -> str | None:
    for loc in store.locations:
        for cand in (loc.base_path(vid, loc.collections.get(vid, "")),
                     loc.base_path(vid)):
            if os.path.exists(cand + ".ecx") or any(
                    os.path.exists(cand + layout.to_ext(i))
                    for i in range(layout.MAX_TOTAL_SHARDS)):
                return cand
    return None


def delete_shard(store, vid: int, sid: int) -> bool:
    """Remove one shard file; the mounted EcVolume drops its fd so the
    next heartbeat reports the loss."""
    base = _ec_base(store, vid)
    if base is None:
        return False
    p = base + layout.to_ext(sid)
    if os.path.exists(p):
        os.remove(p)
    ev = store.get_ec_volume(vid)
    if ev is not None:
        f = ev.shards.pop(sid, None)
        if f is not None:
            f.close()
        ev.clear_quarantine(sid)
    log.warning("faults: deleted shard %d of volume %d", sid, vid)
    return True


def flip_bit(store, vid: int, sid: int, offset: int, bit: int = 0) -> bool:
    """XOR one bit of a shard file in place (the mounted EcVolume reads
    through the page cache, so the corruption is immediately live)."""
    base = _ec_base(store, vid)
    if base is None:
        return False
    p = base + layout.to_ext(sid)
    if not os.path.exists(p):
        return False
    size = os.path.getsize(p)
    if not size:
        return False
    offset %= size
    with open(p, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (1 << (bit % 8))]))
    log.warning("faults: flipped bit %d at offset %d of volume %d "
                "shard %d", bit, offset, vid, sid)
    return True


def apply(store, fault: dict) -> dict:
    """Apply one parsed fault to a Store; returns {**fault, ok: bool}.
    delay_shard_read is server state, not store state — the volume
    server handles it before calling here.  Process-wide faults route
    through apply_net first."""
    action = fault.get("action")
    ok = False
    if action == "delete_shard":
        ok = delete_shard(store, int(fault["volume"]), int(fault["shard"]))
    elif action == "flip_bit":
        ok = flip_bit(store, int(fault["volume"]), int(fault["shard"]),
                      int(fault["offset"]), int(fault.get("bit", 0)))
    else:
        ok = apply_net(fault)
    return dict(fault, ok=ok)
