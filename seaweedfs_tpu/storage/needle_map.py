"""In-RAM needle map: needleId -> (offset, size) per volume, plus the
bookkeeping metrics the master heartbeat needs.

The reference offers compact-sectioned arrays, leveldb, and sorted-file
variants (weed/storage/needle_map/compact_map.go, needle_map_leveldb.go);
here one dict-backed map covers the in-memory kind — CPython dicts are
open-addressing tables, i.e. already the compact-map idea — and the
metrics/persistence contract matches so other kinds can slot in later.
"""

from __future__ import annotations

import os
from typing import BinaryIO, Iterator

from seaweedfs_tpu.storage import idx, types as t


class NeedleMap:
    """needleId -> (offset_units, size) with live/deleted accounting
    (metric semantics follow weed/storage/needle_map_metric.go)."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0
        self._idx_file: BinaryIO | None = None

    # -- core ----------------------------------------------------------

    def put(self, needle_id: int, offset_units: int, size: int) -> None:
        old = self._m.get(needle_id)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
        self._m[needle_id] = (offset_units, size)
        self.file_count += 1
        self.maximum_key = max(self.maximum_key, needle_id)
        if self._idx_file is not None:
            self._idx_file.write(idx.pack_entry(needle_id, offset_units, size))

    def get(self, needle_id: int) -> tuple[int, int] | None:
        v = self._m.get(needle_id)
        if v is None or not t.size_is_valid(v[1]):
            return None
        return v

    def delete(self, needle_id: int) -> int:
        """Tombstone the entry; returns the freed byte count (0 if absent)."""
        old = self._m.get(needle_id)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._m[needle_id] = (old[0], t.TOMBSTONE_FILE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        if self._idx_file is not None:
            self._idx_file.write(
                idx.pack_entry(needle_id, old[0], t.TOMBSTONE_FILE_SIZE))
        return old[1]

    def __len__(self) -> int:
        return sum(1 for v in self._m.values() if t.size_is_valid(v[1]))

    def items(self) -> Iterator[tuple[int, tuple[int, int]]]:
        return iter(self._m.items())

    @property
    def content_size(self) -> int:
        return sum(v[1] for v in self._m.values() if t.size_is_valid(v[1]))

    # -- persistence -----------------------------------------------------

    def attach_idx(self, f: BinaryIO) -> None:
        """Subsequent put/delete calls append entries to this .idx file."""
        self._idx_file = f

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    @classmethod
    def load_from_idx(cls, path: str) -> "NeedleMap":
        nm = cls()
        if not os.path.exists(path):
            return nm
        with open(path, "rb") as f:
            data = f.read()
        ids, offs, sizes = idx.read_columns(data)
        for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
            if t.size_is_valid(size):
                nm.put(nid, off, size)
            else:  # tombstone entry replayed from the log
                old = nm._m.get(nid)
                if old is not None and t.size_is_valid(old[1]):
                    nm.deleted_count += 1
                    nm.deleted_bytes += old[1]
                nm._m[nid] = (old[0] if old is not None else off, size)
        return nm


class SortedFileNeedleMap:
    """Read-only, low-memory needle map: binary search over a sorted `.sdx`
    sidecar instead of an in-RAM table (reference:
    weed/storage/needle_map_sorted_file.go).  Built from the `.idx` log
    (latest entry wins, tombstones dropped) the first time a volume is
    opened with needle_map_kind="sorted_file", rebuilt when the .idx is
    newer than the .sdx.

    Exposes the read-side NeedleMap surface (get/len/items/metrics);
    put/delete raise — the kind is for sealed volumes, like the reference.
    """

    ENTRY = t.NEEDLE_MAP_ENTRY_SIZE  # 16 bytes, same layout as .idx

    def __init__(self, sdx_path: str):
        self.sdx_path = sdx_path
        self._fd = os.open(sdx_path, os.O_RDONLY)
        self._size = os.path.getsize(sdx_path)
        self._n = self._size // self.ENTRY
        self.file_count = self._n
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0
        if self._n:
            nid, _, _ = self._entry_at(self._n - 1)
            self.maximum_key = nid

    @classmethod
    def build(cls, idx_path: str, sdx_path: str) -> None:
        """Compact the .idx log into a sorted .sdx (live entries only)."""
        nm = NeedleMap.load_from_idx(idx_path)
        entries = sorted((nid, v) for nid, v in nm._m.items()
                         if t.size_is_valid(v[1]))
        tmp = sdx_path + ".tmp"
        with open(tmp, "wb") as f:
            for nid, (off, size) in entries:
                f.write(idx.pack_entry(nid, off, size))
        os.replace(tmp, sdx_path)

    @classmethod
    def open_for(cls, idx_path: str, sdx_path: str) -> "SortedFileNeedleMap":
        if not os.path.exists(sdx_path) or (
                os.path.exists(idx_path) and
                os.path.getmtime(idx_path) > os.path.getmtime(sdx_path)):
            cls.build(idx_path, sdx_path)
        return cls(sdx_path)

    def _entry_at(self, i: int) -> tuple[int, int, int]:
        # pread: no shared file-position state, safe for concurrent readers
        return idx.unpack_entry(
            os.pread(self._fd, self.ENTRY, i * self.ENTRY))

    def get(self, needle_id: int) -> tuple[int, int] | None:
        lo, hi = 0, self._n - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            nid, off, size = self._entry_at(mid)
            if nid == needle_id:
                return (off, size) if t.size_is_valid(size) else None
            if nid < needle_id:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    def put(self, needle_id: int, offset_units: int, size: int) -> None:
        raise PermissionError("sorted-file needle map is read-only")

    def delete(self, needle_id: int) -> int:
        raise PermissionError("sorted-file needle map is read-only")

    def __len__(self) -> int:
        return self._n

    def items(self) -> Iterator[tuple[int, tuple[int, int]]]:
        for i in range(self._n):
            nid, off, size = self._entry_at(i)
            yield nid, (off, size)

    @property
    def _m(self) -> dict:
        # compatibility view for callers that introspect the table
        # (max_file_key/export); built lazily, sealed volumes are small sets
        return {nid: v for nid, v in self.items()}

    @property
    def content_size(self) -> int:
        return sum(v[1] for _, v in self.items())

    def attach_idx(self, f) -> None:
        pass  # read-only; nothing to append

    def flush(self) -> None:
        pass

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass
