"""In-RAM needle map: needleId -> (offset, size) per volume, plus the
bookkeeping metrics the master heartbeat needs.

The reference offers compact-sectioned arrays, leveldb, and sorted-file
variants (weed/storage/needle_map/compact_map.go, needle_map_leveldb.go);
here one dict-backed map covers the in-memory kind — CPython dicts are
open-addressing tables, i.e. already the compact-map idea — and the
metrics/persistence contract matches so other kinds can slot in later.
"""

from __future__ import annotations

import os
from typing import BinaryIO, Iterator

from seaweedfs_tpu.storage import idx, types as t


class NeedleMap:
    """needleId -> (offset_units, size) with live/deleted accounting
    (metric semantics follow weed/storage/needle_map_metric.go)."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0
        self._idx_file: BinaryIO | None = None

    # -- core ----------------------------------------------------------

    def put(self, needle_id: int, offset_units: int, size: int) -> None:
        old = self._m.get(needle_id)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
        self._m[needle_id] = (offset_units, size)
        self.file_count += 1
        self.maximum_key = max(self.maximum_key, needle_id)
        if self._idx_file is not None:
            self._idx_file.write(idx.pack_entry(needle_id, offset_units, size))

    def get(self, needle_id: int) -> tuple[int, int] | None:
        v = self._m.get(needle_id)
        if v is None or not t.size_is_valid(v[1]):
            return None
        return v

    def delete(self, needle_id: int) -> int:
        """Tombstone the entry; returns the freed byte count (0 if absent)."""
        old = self._m.get(needle_id)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._m[needle_id] = (old[0], t.TOMBSTONE_FILE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        if self._idx_file is not None:
            self._idx_file.write(
                idx.pack_entry(needle_id, old[0], t.TOMBSTONE_FILE_SIZE))
        return old[1]

    def __len__(self) -> int:
        return sum(1 for v in self._m.values() if t.size_is_valid(v[1]))

    def items(self) -> Iterator[tuple[int, tuple[int, int]]]:
        return iter(self._m.items())

    @property
    def content_size(self) -> int:
        return sum(v[1] for v in self._m.values() if t.size_is_valid(v[1]))

    # -- persistence -----------------------------------------------------

    def attach_idx(self, f: BinaryIO) -> None:
        """Subsequent put/delete calls append entries to this .idx file."""
        self._idx_file = f

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    @classmethod
    def load_from_idx(cls, path: str) -> "NeedleMap":
        nm = cls()
        if not os.path.exists(path):
            return nm
        with open(path, "rb") as f:
            data = f.read()
        ids, offs, sizes = idx.read_columns(data)
        for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
            if t.size_is_valid(size):
                nm.put(nid, off, size)
            else:  # tombstone entry replayed from the log
                old = nm._m.get(nid)
                if old is not None and t.size_is_valid(old[1]):
                    nm.deleted_count += 1
                    nm.deleted_bytes += old[1]
                nm._m[nid] = (old[0] if old is not None else off, size)
        return nm
