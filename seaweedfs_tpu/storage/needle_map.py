"""In-RAM needle map: needleId -> (offset, size) per volume, plus the
bookkeeping metrics the master heartbeat needs.

Three kinds, mirroring the reference's needle-map families
(weed/storage/needle_map/compact_map.go, needle_map_leveldb.go,
needle_map_sorted_file.go):

  NeedleMap           dict-backed, fastest puts, ~100+ B/needle — small
                      volumes and tests
  CompactNeedleMap    memory-bounded default: sorted numpy columns
                      (20 B/needle) + a dict overflow merged in bulk — the
                      numpy analogue of the reference's sectioned CompactMap
  SortedFileNeedleMap read-only binary search over a sorted `.sdx` sidecar
                      for sealed volumes

All kinds share the same surface: put/get/delete/drop, len/items,
file_count/deleted_count/deleted_bytes/maximum_key, content_size,
attach_idx/flush.
"""

from __future__ import annotations

import os
from typing import BinaryIO, Iterator

import numpy as np

from seaweedfs_tpu.stats import metrics
from seaweedfs_tpu.storage import idx, types as t


def _count_drop(kind: str, n: int = 1) -> None:
    """Integrity-repair drops were silently swallowed; they are now a
    /metrics counter so an operator can see a volume shedding entries
    (weedtpu_needle_map_integrity_drops_total{kind=...})."""
    if n > 0:
        metrics.NEEDLE_MAP_DROPS.labels(kind).inc(n)


class NeedleMap:
    """needleId -> (offset_units, size) with live/deleted accounting
    (metric semantics follow weed/storage/needle_map_metric.go)."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0
        self._idx_file: BinaryIO | None = None

    # -- core ----------------------------------------------------------

    def put(self, needle_id: int, offset_units: int, size: int) -> None:
        old = self._m.get(needle_id)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
        self._m[needle_id] = (offset_units, size)
        self.file_count += 1
        self.maximum_key = max(self.maximum_key, needle_id)
        if self._idx_file is not None:
            self._idx_file.write(idx.pack_entry(needle_id, offset_units, size))

    def get(self, needle_id: int) -> tuple[int, int] | None:
        v = self._m.get(needle_id)
        if v is None or not t.size_is_valid(v[1]):
            return None
        return v

    def delete(self, needle_id: int) -> int:
        """Tombstone the entry; returns the freed byte count (0 if absent)."""
        old = self._m.get(needle_id)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._m[needle_id] = (old[0], t.TOMBSTONE_FILE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        if self._idx_file is not None:
            self._idx_file.write(
                idx.pack_entry(needle_id, old[0], t.TOMBSTONE_FILE_SIZE))
        return old[1]

    def drop(self, needle_id: int) -> None:
        """Remove an entry without tombstone accounting (integrity repair
        of torn writes: the data never existed, so it isn't 'deleted')."""
        if self._m.pop(needle_id, None) is not None:
            _count_drop("integrity_repair")

    def __len__(self) -> int:
        return sum(1 for v in self._m.values() if t.size_is_valid(v[1]))

    def items(self) -> Iterator[tuple[int, tuple[int, int]]]:
        return iter(self._m.items())

    @property
    def content_size(self) -> int:
        return sum(v[1] for v in self._m.values() if t.size_is_valid(v[1]))

    # -- persistence -----------------------------------------------------

    def attach_idx(self, f: BinaryIO) -> None:
        """Subsequent put/delete calls append entries to this .idx file."""
        self._idx_file = f

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    @classmethod
    def load_from_idx(cls, path: str) -> "NeedleMap":
        nm = cls()
        if not os.path.exists(path):
            return nm
        with open(path, "rb") as f:
            data = f.read()
        ids, offs, sizes = idx.read_columns(data)
        for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
            if t.size_is_valid(size):
                nm.put(nid, off, size)
            else:  # tombstone entry replayed from the log
                old = nm._m.get(nid)
                if old is not None and t.size_is_valid(old[1]):
                    nm.deleted_count += 1
                    nm.deleted_bytes += old[1]
                nm._m[nid] = (old[0] if old is not None else off, size)
        return nm


class CompactNeedleMap:
    """Memory-bounded needle map: three sorted numpy columns (ids u64,
    offsets u32, sizes i32 — 16 B/needle vs ~100+ B for a Python dict) plus
    a dict overflow for recent mutations, bulk-merged into the base when it
    grows past MERGE_THRESHOLD.

    The numpy re-idiom of the reference's sectioned CompactMap
    (weed/storage/needle_map/compact_map.go:18-50): where Go keeps
    fixed-size sections of sorted entries with per-section overflow, one
    flat sorted base + vectorized merge gives the same bound with
    searchsorted lookups.

    Internally synchronized: unlike the dict kind, whose get is one
    GIL-atomic dict lookup, lookups here are multi-step against arrays that
    _merge() swaps out, and Volume's hot read paths call nm.get() without
    the volume lock."""

    MERGE_THRESHOLD = 65536

    def __init__(self) -> None:
        import threading
        self._ids = np.empty(0, dtype=np.uint64)   # sorted ascending
        self._offs = np.empty(0, dtype=np.uint32)  # .idx offsets are u32
        self._sizes = np.empty(0, dtype=np.int32)  # TOMBSTONE for deleted
        # nid -> (off, size), or None for entries dropped by integrity repair
        self._overflow: dict[int, tuple[int, int] | None] = {}
        self._mu = threading.Lock()
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0
        self._live = 0
        self._live_bytes = 0
        self._idx_file: BinaryIO | None = None

    # -- core ----------------------------------------------------------

    def _base_get(self, needle_id: int) -> tuple[int, int] | None:
        i = int(np.searchsorted(self._ids, np.uint64(needle_id)))
        if i < len(self._ids) and int(self._ids[i]) == needle_id:
            return int(self._offs[i]), int(self._sizes[i])
        return None

    def _raw_get(self, needle_id: int) -> tuple[int, int] | None:
        """Entry incl. tombstones; None if absent or dropped. Caller holds
        self._mu."""
        if needle_id in self._overflow:
            return self._overflow[needle_id]
        return self._base_get(needle_id)

    def put(self, needle_id: int, offset_units: int, size: int) -> None:
        with self._mu:
            old = self._raw_get(needle_id)
            if old is not None and t.size_is_valid(old[1]):
                self.deleted_count += 1
                self.deleted_bytes += old[1]
                self._live -= 1
                self._live_bytes -= old[1]
            self._overflow[needle_id] = (offset_units, size)
            self.file_count += 1
            if t.size_is_valid(size):
                self._live += 1
                self._live_bytes += size
            self.maximum_key = max(self.maximum_key, needle_id)
            if self._idx_file is not None:
                self._idx_file.write(
                    idx.pack_entry(needle_id, offset_units, size))
            if len(self._overflow) >= self.MERGE_THRESHOLD:
                self._merge()

    def get(self, needle_id: int) -> tuple[int, int] | None:
        with self._mu:
            v = self._raw_get(needle_id)
        if v is None or not t.size_is_valid(v[1]):
            return None
        return v

    def delete(self, needle_id: int) -> int:
        with self._mu:
            old = self._raw_get(needle_id)
            if old is None or not t.size_is_valid(old[1]):
                return 0
            self._overflow[needle_id] = (old[0], t.TOMBSTONE_FILE_SIZE)
            self.deleted_count += 1
            self.deleted_bytes += old[1]
            self._live -= 1
            self._live_bytes -= old[1]
            if self._idx_file is not None:
                self._idx_file.write(
                    idx.pack_entry(needle_id, old[0], t.TOMBSTONE_FILE_SIZE))
            if len(self._overflow) >= self.MERGE_THRESHOLD:
                self._merge()
            return old[1]

    def drop(self, needle_id: int) -> None:
        with self._mu:
            old = self._raw_get(needle_id)
            if old is None:
                return
            if t.size_is_valid(old[1]):
                self._live -= 1
                self._live_bytes -= old[1]
            self._overflow[needle_id] = None
        _count_drop("integrity_repair")

    def _merge(self) -> None:
        """Fold the overflow dict into the sorted base columns in one
        vectorized pass; dropped (None) entries vanish. Caller holds
        self._mu."""
        if not self._overflow:
            return
        ov = sorted(self._overflow.items())
        ov_ids = np.array([k for k, _ in ov], dtype=np.uint64)
        keep = ~np.isin(self._ids, ov_ids, assume_unique=True)
        live = [(k, v) for k, v in ov if v is not None]
        self._ids = np.concatenate(
            [self._ids[keep], np.array([k for k, _ in live], np.uint64)])
        self._offs = np.concatenate(
            [self._offs[keep], np.array([v[0] for _, v in live], np.uint32)])
        self._sizes = np.concatenate(
            [self._sizes[keep], np.array([v[1] for _, v in live], np.int32)])
        order = np.argsort(self._ids, kind="stable")
        self._ids = self._ids[order]
        self._offs = self._offs[order]
        self._sizes = self._sizes[order]
        self._overflow = {}

    def __len__(self) -> int:
        return self._live

    def items(self) -> Iterator[tuple[int, tuple[int, int]]]:
        # snapshot under the lock, yield outside it: scans (vacuum, fsck)
        # must not block writers for their whole duration, and the arrays
        # are replaced — never mutated — so the snapshot stays consistent
        with self._mu:
            ids, offs, sizes = self._ids, self._offs, self._sizes
            ov = dict(self._overflow)
        for i in range(len(ids)):
            nid = int(ids[i])
            if nid not in ov:
                yield nid, (int(offs[i]), int(sizes[i]))
        for nid, v in ov.items():
            if v is not None:
                yield nid, v

    @property
    def content_size(self) -> int:
        return self._live_bytes

    # -- persistence -----------------------------------------------------

    def attach_idx(self, f: BinaryIO) -> None:
        self._idx_file = f

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    @classmethod
    def load_from_idx(cls, path: str) -> "CompactNeedleMap":
        """Vectorized .idx replay with a bounded memory profile: the file is
        read in 16MB slices into preallocated 16B/entry columns, then split
        by a running-maximum test — entries whose id exceeds every earlier
        id are already sorted AND unique (needle ids are assigned ascending,
        so this is nearly the whole file), while the out-of-order remainder
        (overwrites and tombstones of older ids) forms a small table that is
        stable-sorted, deduped latest-wins, and applied as in-place
        overrides/inserts. Peak RSS stays ~1.5x the steady 16B/needle
        instead of the several-x transients a whole-file np.unique costs."""
        nm = cls()
        if not os.path.exists(path):
            return nm
        n_total = os.path.getsize(path) // t.NEEDLE_MAP_ENTRY_SIZE
        if n_total == 0:
            return nm
        # one chunked pass: in-order entries (id above every earlier id —
        # already sorted and unique) land directly in the preallocated base
        # columns; the out-of-order remainder is collected per chunk. Peak
        # RSS is the 16B/entry base + per-chunk transients.
        base_ids = np.empty(n_total, np.uint64)
        base_offs = np.empty(n_total, np.uint32)
        base_sizes = np.empty(n_total, np.int32)
        out_chunks: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        w = 0
        prev_max = 0
        total_valid = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(16 * 1024 * 1024)  # multiple of the 16B entry
                if not chunk:
                    break
                a, b, c = idx.read_columns(chunk)
                if len(a) == 0:  # torn trailing partial entry
                    break
                v = c > 0  # vectorized t.size_is_valid
                nm.file_count += int(v.sum())
                total_valid += int(c[v].astype(np.int64).sum())
                if v.any():
                    nm.maximum_key = max(nm.maximum_key, int(a[v].max()))
                racc = np.maximum.accumulate(a)
                thresh = np.empty_like(racc)
                thresh[0] = prev_max
                np.maximum(racc[:-1], np.uint64(prev_max), out=thresh[1:])
                ino = a > thresh  # strictly above all earlier ids in the file
                prev_max = max(prev_max, int(racc[-1]))
                k = int(ino.sum())
                base_ids[w:w + k] = a[ino]
                base_offs[w:w + k] = b[ino]
                base_sizes[w:w + k] = c[ino]
                w += k
                if k < len(a):
                    om = ~ino
                    out_chunks.append((a[om], b[om], c[om]))
        base_ids = base_ids[:w]
        base_offs = base_offs[:w]
        base_sizes = base_sizes[:w]

        if out_chunks:
            out_ids = np.concatenate([x[0] for x in out_chunks])
            out_offs = np.concatenate([x[1] for x in out_chunks])
            out_sizes = np.concatenate([x[2] for x in out_chunks])
            del out_chunks
            order = np.argsort(out_ids, kind="stable")
            out_ids = out_ids[order]
            out_offs = out_offs[order]
            out_sizes = out_sizes[order]
            del order
            keep = np.empty(len(out_ids), bool)
            keep[:-1] = out_ids[:-1] != out_ids[1:]  # last of each run wins
            keep[-1] = True
            out_ids = out_ids[keep]
            out_offs = out_offs[keep]
            out_sizes = out_sizes[keep]
            del keep
            ins = np.searchsorted(base_ids, out_ids)
            hit = (ins < len(base_ids)) & (
                base_ids[np.minimum(ins, len(base_ids) - 1)] == out_ids)
            base_offs[ins[hit]] = out_offs[hit]      # in-place overrides
            base_sizes[ins[hit]] = out_sizes[hit]
            new = ~hit
            if new.any():  # out-of-order first appearances (rare)
                base_ids = np.insert(base_ids, ins[new], out_ids[new])
                base_offs = np.insert(base_offs, ins[new], out_offs[new])
                base_sizes = np.insert(base_sizes, ins[new], out_sizes[new])

        nm._ids, nm._offs, nm._sizes = base_ids, base_offs, base_sizes
        live = nm._sizes > 0
        nm._live = int(live.sum())
        nm._live_bytes = int(nm._sizes[live].astype(np.int64).sum())
        nm.deleted_count = nm.file_count - nm._live
        nm.deleted_bytes = total_valid - nm._live_bytes
        return nm


class SortedFileNeedleMap:
    """Read-only, low-memory needle map: binary search over a sorted `.sdx`
    sidecar instead of an in-RAM table (reference:
    weed/storage/needle_map_sorted_file.go).  Built from the `.idx` log
    (latest entry wins, tombstones dropped) the first time a volume is
    opened with needle_map_kind="sorted_file", rebuilt when the .idx is
    newer than the .sdx.

    Exposes the read-side NeedleMap surface (get/len/items/metrics);
    put/delete raise — the kind is for sealed volumes, like the reference.
    """

    ENTRY = t.NEEDLE_MAP_ENTRY_SIZE  # 16 bytes, same layout as .idx

    def __init__(self, sdx_path: str):
        self.sdx_path = sdx_path
        self._fd = os.open(sdx_path, os.O_RDONLY)
        self._size = os.path.getsize(sdx_path)
        self._n = self._size // self.ENTRY
        self.file_count = self._n
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.maximum_key = 0
        if self._n:
            nid, _, _ = self._entry_at(self._n - 1)
            self.maximum_key = nid

    @classmethod
    def build(cls, idx_path: str, sdx_path: str) -> None:
        """Compact the .idx log into a sorted .sdx (live entries only);
        discarded entries (tombstoned / superseded latest state) are
        counted on /metrics rather than silently swallowed."""
        nm = NeedleMap.load_from_idx(idx_path)
        entries = sorted((nid, v) for nid, v in nm._m.items()
                         if t.size_is_valid(v[1]))
        _count_drop("sdx_rebuild", len(nm._m) - len(entries))
        tmp = sdx_path + ".tmp"
        with open(tmp, "wb") as f:
            for nid, (off, size) in entries:
                f.write(idx.pack_entry(nid, off, size))
        os.replace(tmp, sdx_path)

    @classmethod
    def open_for(cls, idx_path: str, sdx_path: str) -> "SortedFileNeedleMap":
        if not os.path.exists(sdx_path) or (
                os.path.exists(idx_path) and
                os.path.getmtime(idx_path) > os.path.getmtime(sdx_path)):
            cls.build(idx_path, sdx_path)
        return cls(sdx_path)

    def _entry_at(self, i: int) -> tuple[int, int, int]:
        # pread: no shared file-position state, safe for concurrent readers
        return idx.unpack_entry(
            os.pread(self._fd, self.ENTRY, i * self.ENTRY))

    def get(self, needle_id: int) -> tuple[int, int] | None:
        lo, hi = 0, self._n - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            nid, off, size = self._entry_at(mid)
            if nid == needle_id:
                return (off, size) if t.size_is_valid(size) else None
            if nid < needle_id:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    def put(self, needle_id: int, offset_units: int, size: int) -> None:
        raise PermissionError("sorted-file needle map is read-only")

    def delete(self, needle_id: int) -> int:
        raise PermissionError("sorted-file needle map is read-only")

    def drop(self, needle_id: int) -> None:
        raise PermissionError("sorted-file needle map is read-only")

    def __len__(self) -> int:
        return self._n

    def items(self) -> Iterator[tuple[int, tuple[int, int]]]:
        for i in range(self._n):
            nid, off, size = self._entry_at(i)
            yield nid, (off, size)

    @property
    def content_size(self) -> int:
        return sum(v[1] for _, v in self.items())

    def attach_idx(self, f) -> None:
        pass  # read-only; nothing to append

    def flush(self) -> None:
        pass

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass


def load_needle_map(kind: str, idx_path: str):
    """Writable-kind factory: 'compact' (memory-bounded default) or
    'memory' (dict). 'sorted_file' is opened by Volume directly — it needs
    the .sdx path and forces read-only."""
    if kind == "memory":
        return NeedleMap.load_from_idx(idx_path)
    if kind == "compact":
        return CompactNeedleMap.load_from_idx(idx_path)
    raise ValueError(f"unknown needle_map_kind {kind!r}")
