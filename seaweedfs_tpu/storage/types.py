"""On-disk scalar types and constants of the needle store.

Byte-compatible with the reference formats (all integers big-endian):
- needle header [Cookie 4B][NeedleId 8B][Size 4B]
  (reference: weed/storage/types/needle_types.go:33-41)
- .idx entries [NeedleId 8B][Offset 4B][Size 4B], offset in units of 8 bytes
  (reference: weed/storage/types/offset_4bytes.go:14-17 — 32GB max volume)
- tombstone Size == -1 (reference: needle_types.go TombstoneFileSize)
"""

from __future__ import annotations

from dataclasses import dataclass

COOKIE_SIZE = 4
NEEDLE_ID_SIZE = 8
SIZE_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_CHECKSUM_SIZE = 4
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + 4 + SIZE_SIZE  # 16
OFFSET_SIZE = 4
TOMBSTONE_FILE_SIZE = -1
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB (4B offset x8)

VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


def padding_length(size: int, version: int = CURRENT_VERSION) -> int:
    """Bytes of zero padding after a needle record.

    Deliberately reproduces the reference quirk of padding a FULL extra
    block when the record is already aligned (8 - x%8, never 0; reference:
    weed/storage/needle/needle_read.go:208-214)."""
    if version == VERSION3:
        x = NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE
    else:
        x = NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE
    return NEEDLE_PADDING_SIZE - (x % NEEDLE_PADDING_SIZE)


def needle_body_length(size: int, version: int = CURRENT_VERSION) -> int:
    if version == VERSION3:
        return size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE + padding_length(size, version)
    return size + NEEDLE_CHECKSUM_SIZE + padding_length(size, version)


def actual_size(size: int, version: int = CURRENT_VERSION) -> int:
    """Total on-disk bytes of a needle record with body size `size`."""
    return NEEDLE_HEADER_SIZE + needle_body_length(size, version)


def to_offset_units(byte_offset: int) -> int:
    assert byte_offset % NEEDLE_PADDING_SIZE == 0, byte_offset
    return byte_offset // NEEDLE_PADDING_SIZE


def from_offset_units(units: int) -> int:
    return units * NEEDLE_PADDING_SIZE


@dataclass(frozen=True)
class FileId:
    """`vid,keyhex+cookie8hex` — the client-visible blob id.

    reference: weed/storage/needle/file_id.go:60-75 (leading zero bytes of
    the 12-byte key+cookie are trimmed at byte granularity).
    """

    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        raw = self.key.to_bytes(8, "big") + self.cookie.to_bytes(4, "big")
        i = 0
        while i < 7 and raw[i] == 0:  # keep at least 1 key byte + cookie
            i += 1
        return f"{self.volume_id},{raw[i:].hex()}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        vid_str, _, kc = fid.partition(",")
        if not kc:
            raise ValueError(f"bad file id {fid!r}")
        kc = kc.partition("_")[0]  # strip alternate-key suffix
        if len(kc) <= 8:
            raise ValueError(f"file id {fid!r} too short for key+cookie")
        if len(kc) % 2:
            kc = "0" + kc
        raw = bytes.fromhex(kc)
        return cls(volume_id=int(vid_str),
                   key=int.from_bytes(raw[:-4], "big"),
                   cookie=int.from_bytes(raw[-4:], "big"))


class TTL:
    """2-byte count+unit TTL (reference: weed/storage/needle/volume_ttl.go)."""

    UNITS = {"": 0, "m": 1, "h": 2, "d": 3, "w": 4, "M": 5, "y": 6}
    _MINUTES = {0: 0, 1: 1, 2: 60, 3: 24 * 60, 4: 7 * 24 * 60,
                5: 31 * 24 * 60, 6: 365 * 24 * 60}

    def __init__(self, count: int = 0, unit: int = 0):
        self.count = count
        self.unit = unit

    @classmethod
    def parse(cls, s: str) -> "TTL":
        if not s:
            return cls()
        if s[-1].isdigit():
            return cls(int(s), cls.UNITS["m"])
        return cls(int(s[:-1] or 0), cls.UNITS[s[-1]])

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        return cls(b[0], b[1])

    @property
    def minutes(self) -> int:
        return self.count * self._MINUTES.get(self.unit, 0)

    def __bool__(self) -> bool:
        return self.count != 0 and self.unit != 0

    def __eq__(self, other) -> bool:
        return isinstance(other, TTL) and (self.count, self.unit) == (other.count, other.unit)

    def __str__(self) -> str:
        if not self:
            return ""
        names = {v: k for k, v in self.UNITS.items()}
        return f"{self.count}{names.get(self.unit, '')}"


class ReplicaPlacement:
    """xyz digit code: x other-DC, y other-rack, z same-rack copies
    (reference: weed/storage/super_block/replica_placement.go)."""

    def __init__(self, diff_dc: int = 0, diff_rack: int = 0, same_rack: int = 0):
        self.diff_dc = diff_dc
        self.diff_rack = diff_rack
        self.same_rack = same_rack

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        s = (s or "000").zfill(3)
        if len(s) != 3 or not s.isdigit():
            raise ValueError(f"bad replica placement {s!r} (want xyz digits)")
        rp = cls(int(s[0]), int(s[1]), int(s[2]))
        if rp.to_byte() > 255:
            raise ValueError(f"replica placement {s!r} exceeds one byte")
        return rp

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls(b // 100, (b // 10) % 10, b % 10)

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    @property
    def copy_count(self) -> int:
        return self.diff_dc + self.diff_rack + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"

    def __eq__(self, other) -> bool:
        return isinstance(other, ReplicaPlacement) and self.to_byte() == other.to_byte()
