"""Volume super block: the first 8 bytes of every .dat file.

Byte 0 version, byte 1 replica-placement code, bytes 2-3 TTL, bytes 4-5
compaction revision (big-endian), bytes 6-7 length of an optional protobuf
extra section (reference: weed/storage/super_block/super_block.go:16-65).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from seaweedfs_tpu.storage import types as t

SUPER_BLOCK_SIZE = 8


@dataclass
class SuperBlock:
    version: int = t.CURRENT_VERSION
    replica_placement: t.ReplicaPlacement = field(
        default_factory=t.ReplicaPlacement)
    ttl: t.TTL = field(default_factory=t.TTL)
    compaction_revision: int = 0
    extra: bytes = b""

    def to_bytes(self) -> bytes:
        out = bytearray(SUPER_BLOCK_SIZE)
        out[0] = self.version
        out[1] = self.replica_placement.to_byte()
        out[2:4] = self.ttl.to_bytes()
        struct.pack_into(">H", out, 4, self.compaction_revision)
        if self.extra:
            struct.pack_into(">H", out, 6, len(self.extra))
            return bytes(out) + self.extra
        return bytes(out)

    @property
    def block_size(self) -> int:
        return SUPER_BLOCK_SIZE + len(self.extra)

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("super block truncated")
        version = b[0]
        if version not in (t.VERSION1, t.VERSION2, t.VERSION3):
            raise ValueError(f"unsupported volume version {version}")
        (rev,) = struct.unpack_from(">H", b, 4)
        (extra_size,) = struct.unpack_from(">H", b, 6)
        extra = bytes(b[SUPER_BLOCK_SIZE: SUPER_BLOCK_SIZE + extra_size]) if extra_size else b""
        return cls(version=version,
                   replica_placement=t.ReplicaPlacement.from_byte(b[1]),
                   ttl=t.TTL.from_bytes(b[2:4]),
                   compaction_revision=rev,
                   extra=extra)
