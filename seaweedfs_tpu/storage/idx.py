""".idx file codec: 16-byte entries [NeedleId 8][Offset 4][Size 4], offsets
in 8-byte units (reference: weed/storage/idx/walk.go:12-40).

Read side is vectorised with numpy — a 32GB volume's index is ~16M entries
and walking it with a Python loop would take seconds; as three numpy columns
it is milliseconds and feeds the EC `.ecx` sort for free.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Iterator

import numpy as np

from seaweedfs_tpu.storage import types as t

ENTRY = struct.Struct(">QIi")


def pack_entry(needle_id: int, offset_units: int, size: int) -> bytes:
    return ENTRY.pack(needle_id, offset_units, size)


def unpack_entry(b: bytes) -> tuple[int, int, int]:
    return ENTRY.unpack(b)


def read_columns(data: bytes) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Whole .idx buffer -> (ids u64, offset_units u32, sizes i32) columns."""
    n = len(data) // t.NEEDLE_MAP_ENTRY_SIZE
    arr = np.frombuffer(data, dtype=np.uint8, count=n * 16).reshape(n, 16)
    ids = arr[:, :8].copy().view(">u8").reshape(n).astype(np.uint64)
    offs = arr[:, 8:12].copy().view(">u4").reshape(n).astype(np.uint32)
    sizes = arr[:, 12:16].copy().view(">i4").reshape(n).astype(np.int32)
    return ids, offs, sizes


def walk(f: BinaryIO) -> Iterator[tuple[int, int, int]]:
    """Yield (needle_id, offset_units, size) in file order."""
    while True:
        chunk = f.read(t.NEEDLE_MAP_ENTRY_SIZE * 4096)
        if not chunk:
            return
        n = len(chunk) // t.NEEDLE_MAP_ENTRY_SIZE
        for i in range(n):
            yield ENTRY.unpack_from(chunk, i * t.NEEDLE_MAP_ENTRY_SIZE)
