"""Needle codec: one blob record in a volume file.

Byte-compatible with the reference's V1/V2/V3 formats
(reference: weed/storage/needle/needle_write.go:25-130 for layout,
needle_read.go:120-200 for parsing, crc.go for CRC32-Castagnoli):

V3 record =
  [Cookie 4][NeedleId 8][Size 4]                      # header
  [DataSize 4][Data][Flags 1]                         # body (if DataSize>0)
  [NameSize 1][Name]?   (flag 0x02)
  [MimeSize 1][Mime]?   (flag 0x04)
  [LastModified 5]?     (flag 0x08)
  [Ttl 2]?              (flag 0x10)
  [PairsSize 2][Pairs]? (flag 0x20)
  [Checksum 4][AppendAtNs 8][zero padding to 8B]

`Size` is the body length between header and checksum; a tombstone has
Size == -1 on the .idx side and a zero-data record in the .dat file.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

try:
    import google_crc32c
except ImportError:  # fall back to the native C++ runtime's SSE4.2 CRC
    google_crc32c = None

from seaweedfs_tpu.storage import types as t

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2


def crc32c(data: bytes) -> int:
    if not isinstance(data, bytes):
        data = bytes(data)  # google_crc32c rejects writable buffers
    if google_crc32c is not None:
        return int(google_crc32c.value(data))
    from seaweedfs_tpu import native
    return native.crc32c(data)


def crc_legacy_value(c: int) -> int:
    """Pre-2021 volumes stored this rotated form of the CRC; readers accept
    both (reference: weed/storage/needle/crc.go:27, needle_read.go:76)."""
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    data: bytes = b""
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    flags: int = 0
    last_modified: int = 0
    ttl: t.TTL | None = None
    checksum: int = 0
    append_at_ns: int = 0
    size: int = field(default=0)  # filled by encode/parse

    # -- flag helpers --------------------------------------------------

    def has(self, flag: int) -> bool:
        return bool(self.flags & flag)

    def set_flags(self) -> None:
        if self.name:
            self.flags |= FLAG_HAS_NAME
        if self.mime:
            self.flags |= FLAG_HAS_MIME
        if self.last_modified:
            self.flags |= FLAG_HAS_LAST_MODIFIED
        if self.ttl and bool(self.ttl):
            self.flags |= FLAG_HAS_TTL
        if self.pairs:
            self.flags |= FLAG_HAS_PAIRS

    # -- encode --------------------------------------------------------

    def body_size(self, version: int = t.CURRENT_VERSION) -> int:
        if version == t.VERSION1:
            return len(self.data)
        if not self.data:
            return 0
        size = 4 + len(self.data) + 1
        if self.has(FLAG_HAS_NAME):
            size += 1 + min(len(self.name), 255)
        if self.has(FLAG_HAS_MIME):
            size += 1 + len(self.mime)
        if self.has(FLAG_HAS_LAST_MODIFIED):
            size += LAST_MODIFIED_BYTES
        if self.has(FLAG_HAS_TTL):
            size += TTL_BYTES
        if self.has(FLAG_HAS_PAIRS):
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = t.CURRENT_VERSION) -> bytes:
        """Full on-disk record including padding. Sets self.size/checksum."""
        self.set_flags()
        self.checksum = crc32c(self.data)
        if version == t.VERSION1:
            self.size = len(self.data)
            out = bytearray()
            out += struct.pack(">IQi", self.cookie, self.id, self.size)
            out += self.data
            out += struct.pack(">I", self.checksum)
            out += bytes(t.padding_length(self.size, version))
            return bytes(out)

        self.size = self.body_size(version)
        out = bytearray()
        out += struct.pack(">IQi", self.cookie, self.id, self.size)
        if self.data:
            out += struct.pack(">I", len(self.data))
            out += self.data
            out += bytes([self.flags])
            if self.has(FLAG_HAS_NAME):
                name = self.name[:255]
                out += bytes([len(name)]) + name
            if self.has(FLAG_HAS_MIME):
                out += bytes([len(self.mime)]) + self.mime
            if self.has(FLAG_HAS_LAST_MODIFIED):
                out += self.last_modified.to_bytes(8, "big")[8 - LAST_MODIFIED_BYTES:]
            if self.has(FLAG_HAS_TTL):
                out += (self.ttl or t.TTL()).to_bytes()
            if self.has(FLAG_HAS_PAIRS):
                out += struct.pack(">H", len(self.pairs)) + self.pairs
        out += struct.pack(">I", self.checksum)
        if version == t.VERSION3:
            if not self.append_at_ns:
                self.append_at_ns = time.time_ns()
            out += struct.pack(">Q", self.append_at_ns)
        out += bytes(t.padding_length(self.size, version))
        return bytes(out)

    # -- decode --------------------------------------------------------

    @classmethod
    def parse_header(cls, header: bytes) -> "Needle":
        cookie, nid, size = struct.unpack(">IQi", header[: t.NEEDLE_HEADER_SIZE])
        n = cls(cookie=cookie, id=nid)
        n.size = size
        return n

    def parse_body(self, body: bytes, version: int = t.CURRENT_VERSION,
                   verify_checksum: bool = True) -> None:
        """`body` is the record after the 16-byte header (size from header)."""
        size = self.size
        if size <= 0:
            self.data = b""
            if version == t.VERSION3 and len(body) >= t.NEEDLE_CHECKSUM_SIZE + t.TIMESTAMP_SIZE:
                (self.append_at_ns,) = struct.unpack(
                    ">Q", body[t.NEEDLE_CHECKSUM_SIZE: t.NEEDLE_CHECKSUM_SIZE + 8])
            return
        if version == t.VERSION1:
            self.data = body[:size]
            (self.checksum,) = struct.unpack(">I", body[size: size + 4])
        else:
            (data_size,) = struct.unpack(">I", body[:4])
            pos = 4
            self.data = body[pos: pos + data_size]
            pos += data_size
            self.flags = body[pos]
            pos += 1
            if self.has(FLAG_HAS_NAME):
                ln = body[pos]
                self.name = body[pos + 1: pos + 1 + ln]
                pos += 1 + ln
            if self.has(FLAG_HAS_MIME):
                ln = body[pos]
                self.mime = body[pos + 1: pos + 1 + ln]
                pos += 1 + ln
            if self.has(FLAG_HAS_LAST_MODIFIED):
                self.last_modified = int.from_bytes(
                    body[pos: pos + LAST_MODIFIED_BYTES], "big")
                pos += LAST_MODIFIED_BYTES
            if self.has(FLAG_HAS_TTL):
                self.ttl = t.TTL.from_bytes(body[pos: pos + TTL_BYTES])
                pos += TTL_BYTES
            if self.has(FLAG_HAS_PAIRS):
                (psize,) = struct.unpack(">H", body[pos: pos + 2])
                self.pairs = body[pos + 2: pos + 2 + psize]
                pos += 2 + psize
            (self.checksum,) = struct.unpack(">I", body[size: size + 4])
            if version == t.VERSION3:
                (self.append_at_ns,) = struct.unpack(
                    ">Q", body[size + 4: size + 12])
        if verify_checksum:
            c = crc32c(self.data)
            if self.checksum not in (c, crc_legacy_value(c)):
                raise ValueError(
                    f"needle {self.id:x} CRC mismatch: "
                    f"stored {self.checksum:#x} != computed {c:#x}")

    def parse_meta_tail(self, tail: bytes) -> None:
        """Parse the post-data metadata block (flags | name | mime |
        last_modified | ttl | pairs) without the data bytes — the paged
        read path reads only header + this small tail
        (reference: needle_read_page.go reads meta separately too)."""
        if not tail:
            return
        try:
            self.flags = tail[0]
            pos = 1
            if self.has(FLAG_HAS_NAME):
                ln = tail[pos]
                self.name = tail[pos + 1: pos + 1 + ln]
                pos += 1 + ln
            if self.has(FLAG_HAS_MIME):
                ln = tail[pos]
                self.mime = tail[pos + 1: pos + 1 + ln]
                pos += 1 + ln
            if self.has(FLAG_HAS_LAST_MODIFIED):
                self.last_modified = int.from_bytes(
                    tail[pos: pos + LAST_MODIFIED_BYTES], "big")
                pos += LAST_MODIFIED_BYTES
            if self.has(FLAG_HAS_TTL):
                self.ttl = t.TTL.from_bytes(tail[pos: pos + TTL_BYTES])
                pos += TTL_BYTES
        except IndexError as e:
            raise ValueError(f"truncated needle meta tail: {e}") from e

    @classmethod
    def from_record(cls, record: bytes, version: int = t.CURRENT_VERSION,
                    verify_checksum: bool = True) -> "Needle":
        n = cls.parse_header(record)
        n.parse_body(record[t.NEEDLE_HEADER_SIZE:], version, verify_checksum)
        return n
