"""Host async-I/O engine for shard writeback: io_uring via ctypes.

The EC data plane's disk side was a thread pool of synchronous pwritev
calls — every submission burns a syscall round-trip per merged run, the
page cache takes a copy of every parity byte on its way to a file nobody
will read back through the cache, and the writer thread is parked inside
the kernel for the whole device latency.  This module gives the writer
pool (storage/ec/ec_files._ShardWriterPool) a real submission/completion
engine instead, the same way mount/fuse_ll.py drives libfuse: raw ctypes
against the io_uring syscalls, no external dependency.

Three modes behind one surface (``WEEDTPU_AIO=auto|uring|pwritev|
buffered``, auto-probed at import of the first engine; ``auto`` picks
the ring only when ``WEEDTPU_AIO_DIRECT=1`` gives its completions
device latency to hide — see engine_mode()):

  uring     submission/completion ring per writer thread.  A whole batch
            of merged runs is stamped into SQEs and submitted with ONE
            io_uring_enter; completions are reaped while later batches
            queue.  With ``WEEDTPU_AIO_DIRECT=1``, runs whose file
            offset, buffer addresses and lengths are all ALIGN-multiples
            are written with O_DIRECT (the page cache never copies the
            bytes); the unaligned tail of a shard is deferred and
            written with a final buffered pwrite after the direct flag
            is dropped.  O_DIRECT is opt-in because it pins throughput
            to the raw device: on hosts whose page cache outruns the
            disk (most VMs, anything with RAM to spare for a 1 GiB
            burst) bypassing the cache is a measured multi-x loss, and
            it only pays off when sustained writeback throttling on a
            fast device is the proven bottleneck.  Buffers inside a
            registered region (IORING_REGISTER_BUFFERS) go out as
            WRITE_FIXED — the kernel skips the per-op pin/unpin.
  pwritev   the synchronous vectored path (one pwritev per merged run on
            the calling thread) — the pre-engine behaviour, kept as the
            first fallback when io_uring is unavailable (seccomp, old
            kernels, exotic filesystems).
  buffered  one plain pwrite per buffer; the last-resort path and the
            reference behaviour for byte-identity tests.

Degradation is per-layer and silent-but-recorded: a failed io_uring
probe resolves auto/uring down to pwritev (``engine_info()`` reports
both the requested and resolved mode — bench.py stamps it into every
bench_history round so a fallback run never masquerades as an io_uring
regression); a per-fd EINVAL under O_DIRECT (filesystem without direct
I/O) latches that fd buffered and rewrites the failed run; a failed
buffer registration just means plain WRITEV opcodes.

Stage accounting: every engine accumulates ``submit_s`` (stamping SQEs +
io_uring_enter submission + the synchronous modes' write calls) and
``complete_s`` (waiting on / reaping CQEs) — the writer pool folds them
into the stats dict next to write_data_s/write_parity_s, and
stats/pipeline.py maps both onto the disk resource so /debug/pipeline
shows where the write stage actually spends its wall.

Knobs: ``WEEDTPU_AIO`` (mode, above), ``WEEDTPU_AIO_DEPTH`` (ring
entries per writer thread, default 64), ``WEEDTPU_AIO_DIRECT=1``
(opt into O_DIRECT for aligned runs in uring mode; default off).
"""

from __future__ import annotations

import ctypes
import errno
import fcntl
import mmap
import os
import struct
import sys
import threading

import numpy as np

# O_DIRECT wants the file offset, each buffer address and each buffer
# length aligned to the logical block size; 4096 satisfies every sane
# device and matches the page cache the direct write bypasses
ALIGN = 4096

MODES = ("uring", "pwritev", "buffered")

# x86_64 / aarch64 share these numbers (asm-generic)
_NR_io_uring_setup = 425
_NR_io_uring_enter = 426
_NR_io_uring_register = 427

_IORING_OFF_SQ_RING = 0
_IORING_OFF_CQ_RING = 0x8000000
_IORING_OFF_SQES = 0x10000000

_IORING_ENTER_GETEVENTS = 1
_IORING_FEAT_SINGLE_MMAP = 1
_IORING_REGISTER_BUFFERS = 0

_OP_NOP = 0
_OP_WRITEV = 2
_OP_WRITE_FIXED = 5

_libc = ctypes.CDLL(None, use_errno=True)
_syscall = _libc.syscall
_syscall.restype = ctypes.c_long


class _SQOff(ctypes.Structure):
    _fields_ = [(n, ctypes.c_uint32) for n in
                ("head", "tail", "ring_mask", "ring_entries", "flags",
                 "dropped", "array", "resv1")] + \
               [("user_addr", ctypes.c_uint64)]


class _CQOff(ctypes.Structure):
    _fields_ = [(n, ctypes.c_uint32) for n in
                ("head", "tail", "ring_mask", "ring_entries", "overflow",
                 "cqes", "flags", "resv1")] + \
               [("user_addr", ctypes.c_uint64)]


class _Params(ctypes.Structure):
    _fields_ = [("sq_entries", ctypes.c_uint32),
                ("cq_entries", ctypes.c_uint32),
                ("flags", ctypes.c_uint32),
                ("sq_thread_cpu", ctypes.c_uint32),
                ("sq_thread_idle", ctypes.c_uint32),
                ("features", ctypes.c_uint32),
                ("wq_fd", ctypes.c_uint32),
                ("resv", ctypes.c_uint32 * 3),
                ("sq_off", _SQOff),
                ("cq_off", _CQOff)]


class _IoVec(ctypes.Structure):
    _fields_ = [("base", ctypes.c_uint64), ("len", ctypes.c_uint64)]


def _pwrite_all(fd: int, view, off: int) -> None:
    """pwrite may write short (RLIMIT_FSIZE edge, fs under pressure); a
    silent short write would commit a shard with a zero gap."""
    mv = memoryview(view)
    while len(mv) > 0:
        n = os.pwrite(fd, mv, off)
        if n <= 0:
            raise OSError("pwrite returned 0")
        mv = mv[n:]
        off += n


def _pwritev_all(fd: int, bufs: list, off: int) -> None:
    """Vectored pwrite of buffers destined for one contiguous file range:
    a run of per-unit parity blocks lands in a single syscall instead of
    one pwrite per unit.  Short writes (possibly mid-iovec) resume."""
    if not hasattr(os, "pwritev"):
        for b in bufs:
            _pwrite_all(fd, b, off)
            off += memoryview(b).nbytes
        return
    mvs = [memoryview(b) for b in bufs]
    while mvs:
        n = os.pwritev(fd, mvs, off)
        if n <= 0:
            raise OSError("pwritev returned 0")
        off += n
        while mvs and n >= len(mvs[0]):
            n -= len(mvs[0])
            mvs.pop(0)
        if mvs and n:
            mvs[0] = mvs[0][n:]


def aligned_empty(shape, align: int = ALIGN) -> np.ndarray:
    """np.empty whose base address is `align`-aligned: the parity rings
    and rebuild output pools allocate through this so their rows qualify
    for O_DIRECT (a row is aligned when the base is and the trailing
    dimension is an align-multiple — true for every production block
    size; tiny test volumes simply fall back to buffered writes)."""
    if isinstance(shape, int):
        shape = (shape,)
    n = 1
    for s in shape:
        n *= int(s)
    raw = np.empty(n + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + n].reshape(shape)


def _buf_addr(buf) -> int:
    if isinstance(buf, np.ndarray):
        return buf.ctypes.data
    mv = memoryview(buf)
    return ctypes.addressof(ctypes.c_char.from_buffer(mv))


# -- the ring --------------------------------------------------------------

class _Ring:
    """One io_uring instance: SQ/CQ mmaps, SQE stamping, batched enter.
    NOT thread-safe — each writer thread owns its own ring."""

    def __init__(self, depth: int):
        p = _Params()
        fd = _syscall(_NR_io_uring_setup, ctypes.c_uint(depth),
                      ctypes.byref(p))
        if fd < 0:
            raise OSError(ctypes.get_errno(), "io_uring_setup failed")
        self.fd = fd
        self.depth = p.sq_entries
        try:
            sq_sz = p.sq_off.array + p.sq_entries * 4
            cq_sz = p.cq_off.cqes + p.cq_entries * 16
            if p.features & _IORING_FEAT_SINGLE_MMAP:
                sz = max(sq_sz, cq_sz)
                self._sq = mmap.mmap(fd, sz, flags=mmap.MAP_SHARED,
                                     prot=mmap.PROT_READ | mmap.PROT_WRITE,
                                     offset=_IORING_OFF_SQ_RING)
                self._cq = self._sq
            else:
                self._sq = mmap.mmap(fd, sq_sz, flags=mmap.MAP_SHARED,
                                     prot=mmap.PROT_READ | mmap.PROT_WRITE,
                                     offset=_IORING_OFF_SQ_RING)
                self._cq = mmap.mmap(fd, cq_sz, flags=mmap.MAP_SHARED,
                                     prot=mmap.PROT_READ | mmap.PROT_WRITE,
                                     offset=_IORING_OFF_CQ_RING)
            self._sqes = mmap.mmap(fd, p.sq_entries * 64,
                                   flags=mmap.MAP_SHARED,
                                   prot=mmap.PROT_READ | mmap.PROT_WRITE,
                                   offset=_IORING_OFF_SQES)
        except BaseException:
            os.close(fd)
            raise
        o = p.sq_off
        self._sq_head_off, self._sq_tail_off = o.head, o.tail
        self._sq_mask = struct.unpack_from("<I", self._sq, o.ring_mask)[0]
        self._sq_array_off = o.array
        c = p.cq_off
        self._cq_head_off, self._cq_tail_off = c.head, c.tail
        self._cq_mask = struct.unpack_from("<I", self._cq, c.ring_mask)[0]
        self._cqes_off = c.cqes
        self._to_submit = 0
        self.inflight = 0

    # -- raw ring ops -----------------------------------------------------

    def _u32(self, m, off) -> int:
        return struct.unpack_from("<I", m, off)[0]

    def sq_space(self) -> int:
        head = self._u32(self._sq, self._sq_head_off)
        tail = self._u32(self._sq, self._sq_tail_off)
        return self.depth - (tail - head)

    def push(self, opcode: int, fd: int, addr: int, ln: int, off: int,
             user_data: int, buf_index: int = 0) -> None:
        """Stamp one SQE; the caller guarantees sq_space() > 0."""
        tail = self._u32(self._sq, self._sq_tail_off)
        idx = tail & self._sq_mask
        sqe = struct.pack("<BBHiQQIIQH", opcode, 0, 0, fd, off, addr, ln,
                          0, user_data, buf_index)
        self._sqes[idx * 64:idx * 64 + len(sqe)] = sqe
        self._sqes[idx * 64 + len(sqe):idx * 64 + 64] = \
            b"\0" * (64 - len(sqe))
        struct.pack_into("<I", self._sq,
                         self._sq_array_off + idx * 4, idx)
        struct.pack_into("<I", self._sq, self._sq_tail_off,
                         (tail + 1) & 0xFFFFFFFF)
        self._to_submit += 1
        self.inflight += 1

    def enter(self, min_complete: int = 0) -> None:
        flags = _IORING_ENTER_GETEVENTS if min_complete else 0
        while True:
            r = _syscall(_NR_io_uring_enter, ctypes.c_uint(self.fd),
                         ctypes.c_uint(self._to_submit),
                         ctypes.c_uint(min_complete),
                         ctypes.c_uint(flags), ctypes.c_void_p(0),
                         ctypes.c_size_t(0))
            if r >= 0:
                self._to_submit -= min(self._to_submit, int(r))
                return
            e = ctypes.get_errno()
            if e == errno.EINTR:
                continue
            raise OSError(e, "io_uring_enter failed")

    def pop(self):
        """-> (user_data, res) or None when the CQ is empty."""
        head = self._u32(self._cq, self._cq_head_off)
        tail = self._u32(self._cq, self._cq_tail_off)
        if head == tail:
            return None
        idx = head & self._cq_mask
        user_data, res = struct.unpack_from(
            "<Qi", self._cq, self._cqes_off + idx * 16)
        struct.pack_into("<I", self._cq, self._cq_head_off,
                         (head + 1) & 0xFFFFFFFF)
        self.inflight -= 1
        return user_data, res

    def register_buffers(self, arrays) -> list[tuple[int, int]]:
        """IORING_REGISTER_BUFFERS over the given numpy arrays; returns
        the [(addr, len)] regions on success, [] when the kernel refuses
        (memlock limits, too many/huge regions) — callers then just use
        plain WRITEV."""
        if not arrays:
            return []
        iov = (_IoVec * len(arrays))()
        regions = []
        for i, a in enumerate(arrays):
            addr, ln = _buf_addr(a), memoryview(a).nbytes
            iov[i].base, iov[i].len = addr, ln
            regions.append((addr, ln))
        r = _syscall(_NR_io_uring_register, ctypes.c_uint(self.fd),
                     ctypes.c_uint(_IORING_REGISTER_BUFFERS),
                     ctypes.byref(iov), ctypes.c_uint(len(arrays)))
        return regions if r == 0 else []

    def close(self) -> None:
        if self.fd >= 0:
            try:
                if self._sqes is not None:
                    self._sqes.close()
                if self._cq is not self._sq and self._cq is not None:
                    self._cq.close()
                if self._sq is not None:
                    self._sq.close()
            except (BufferError, ValueError):
                pass
            os.close(self.fd)
            self.fd = -1


# -- probe + mode resolution ----------------------------------------------

_probe_lock = threading.Lock()
_URING_OK: bool | None = None


def probe_uring() -> bool:
    """One NOP through a real ring, cached: io_uring may be compiled out,
    seccomp-filtered, or (in containers) sysctl-disabled — the probe is
    the only honest answer."""
    global _URING_OK
    with _probe_lock:
        if _URING_OK is None:
            try:
                ring = _Ring(4)
                try:
                    ring.push(_OP_NOP, -1, 0, 0, 0, 1)
                    ring.enter(min_complete=1)
                    cqe = ring.pop()
                    _URING_OK = cqe is not None and cqe[1] >= 0
                finally:
                    ring.close()
            except Exception:
                _URING_OK = False
        return _URING_OK


def _reset_probe_cache() -> None:
    """Tests: force the next probe_uring() to re-probe."""
    global _URING_OK
    with _probe_lock:
        _URING_OK = None


def requested_mode() -> str:
    mode = os.environ.get("WEEDTPU_AIO", "auto").strip().lower()
    return mode if mode in MODES + ("auto",) else "auto"


def engine_mode() -> str:
    """The RESOLVED engine mode for this process right now: the env
    request degraded down the fallback chain uring -> pwritev ->
    buffered as far as this host requires.

    ``auto`` picks the ring only when O_DIRECT is opted in: an async
    engine pays off when completions have device latency to hide, and a
    direct write has exactly that.  Page-cache writes complete at
    memcpy speed inside the syscall — filesystems without NOWAIT
    buffered-write support (overlayfs, most container roots) punt every
    ring write to an io-wq worker, a measured ~10-15% loss against
    plain pwritev batching with nothing overlapped in return.  An
    explicit ``WEEDTPU_AIO=uring`` still forces the ring for buffered
    writes (benchmarking, hosts whose fs completes them inline)."""
    req = requested_mode()
    if req == "buffered":
        return "buffered"
    if req == "pwritev":
        return "pwritev" if hasattr(os, "pwritev") else "buffered"
    if req == "uring":
        if probe_uring():
            return "uring"
        print("weedtpu: WEEDTPU_AIO=uring requested but the io_uring "
              "probe failed; falling back to pwritev", file=sys.stderr)
    elif _direct_enabled() and probe_uring():
        return "uring"
    return "pwritev" if hasattr(os, "pwritev") else "buffered"


def engine_info() -> dict:
    """Requested vs resolved mode + probe verdict — bench.py stamps this
    into the round config, cluster.perf shows it in triage."""
    return {"requested": requested_mode(), "mode": engine_mode(),
            "uring_available": probe_uring(), "align": ALIGN}


def _depth() -> int:
    try:
        return max(8, int(os.environ.get("WEEDTPU_AIO_DEPTH", "64")))
    except ValueError:
        return 64


def _direct_enabled() -> bool:
    return os.environ.get("WEEDTPU_AIO_DIRECT", "0") == "1"


def engine_label() -> str:
    """Mode label for like-for-like comparison keys: the resolved mode,
    with ``+direct`` appended when O_DIRECT is opted in — a uring+direct
    data path is bounded by the raw device and is not comparable to a
    page-cache uring one."""
    mode = engine_mode()
    if mode == "uring" and _direct_enabled():
        return "uring+direct"
    return mode


# -- the engine ------------------------------------------------------------

class WriteEngine:
    """Per-thread write engine: queue merged runs with writev(), finish
    them with drain().  Owned by exactly one writer thread (rings are
    not thread-safe); the synchronous modes complete inside writev() and
    drain() is a no-op for them.

    Accounting: ``submit_s`` (SQE stamping + enter()s that only submit +
    the synchronous modes' whole write calls), ``complete_s`` (enter()s
    that wait + CQE reaping + deferred-tail writes), ``wbytes`` (bytes
    fully written), ``direct_bytes`` (subset written with O_DIRECT),
    ``fixed_bytes`` (subset via registered buffers)."""

    def __init__(self, mode: str | None = None, depth: int | None = None,
                 reg=None):
        self.mode = mode or engine_mode()
        self.submit_s = 0.0
        self.complete_s = 0.0
        self.wbytes = 0
        self.direct_bytes = 0
        self.fixed_bytes = 0
        self._ring: _Ring | None = None
        self._regions: list[tuple[int, int]] = []
        self._pending: dict[int, tuple] = {}
        self._tails: list[tuple[int, list, int]] = []
        self._seq = 0
        self._direct_fds: set[int] = set()
        self._no_direct_fds: set[int] = set()
        self._errors: list[BaseException] = []
        if self.mode == "uring":
            try:
                self._ring = _Ring(depth or _depth())
                if reg:
                    self._regions = self._ring.register_buffers(list(reg))
            except Exception:
                # ring-per-thread setup can fail where the probe passed
                # (RLIMIT_NOFILE, memlock): degrade THIS engine only
                self._ring = None
                self.mode = "pwritev" if hasattr(os, "pwritev") \
                    else "buffered"

    # -- O_DIRECT bookkeeping ---------------------------------------------

    def _set_direct(self, fd: int) -> None:
        if fd in self._direct_fds:
            return
        fl = fcntl.fcntl(fd, fcntl.F_GETFL)
        fcntl.fcntl(fd, fcntl.F_SETFL, fl | os.O_DIRECT)
        self._direct_fds.add(fd)

    def _clear_direct(self, fd: int) -> None:
        if fd not in self._direct_fds:
            return
        fl = fcntl.fcntl(fd, fcntl.F_GETFL)
        fcntl.fcntl(fd, fcntl.F_SETFL, fl & ~os.O_DIRECT)
        self._direct_fds.discard(fd)

    def _split_aligned(self, bufs: list, off: int):
        """-> (aligned_prefix, tail_bufs, tail_off): the longest prefix
        of buffers whose file offset, address and length all stay
        ALIGN-multiples; everything after the first violation rides the
        buffered tail path (a mid-run violation breaks the offsets of
        every later buffer anyway)."""
        pre = []
        cur = off
        for i, b in enumerate(bufs):
            addr, ln = _buf_addr(b), memoryview(b).nbytes
            if cur % ALIGN or addr % ALIGN or ln % ALIGN:
                return pre, bufs[i:], cur
            pre.append((b, addr, ln))
            cur += ln
        return pre, [], cur

    def _buf_index(self, addr: int, ln: int) -> int:
        for i, (base, rlen) in enumerate(self._regions):
            if addr >= base and addr + ln <= base + rlen:
                return i
        return -1

    # -- submission --------------------------------------------------------

    def ensure_buffered(self, fd: int) -> None:
        """Barrier for non-engine I/O on fd (copy_file_range): completes
        in-flight ring writes, writes out deferred tails targeting fd,
        and drops the direct flag so the next op sees plain buffered
        semantics over fully-ordered prior writes."""
        if self._ring is None:
            return  # sync modes complete in writev(); nothing queued
        import time as _time
        t0 = _time.perf_counter()
        try:
            if self._ring.inflight:
                self._reap_all()
            self._clear_direct(fd)
            if self._tails:
                keep = []
                for tfd, tbufs, toff in self._tails:
                    if tfd != fd:
                        keep.append((tfd, tbufs, toff))
                        continue
                    _pwritev_all(tfd, tbufs, toff)
                    self.wbytes += sum(memoryview(b).nbytes
                                       for b in tbufs)
                self._tails = keep
        finally:
            self.complete_s += _time.perf_counter() - t0

    def writev(self, fd: int, bufs: list, off: int) -> None:
        """Write `bufs` contiguously at `off`.  Synchronous modes finish
        here; uring queues SQEs and returns — drain() is the barrier.
        The caller keeps the buffers alive until drain() returns."""
        import time as _time
        t0 = _time.perf_counter()
        if self._ring is None:
            try:
                if self.mode == "buffered":
                    for b in bufs:
                        _pwrite_all(fd, b, off)
                        off += memoryview(b).nbytes
                else:
                    _pwritev_all(fd, bufs, off)
                self.wbytes += sum(memoryview(b).nbytes for b in bufs)
            finally:
                self.submit_s += _time.perf_counter() - t0
            return
        try:
            if _direct_enabled() and fd not in self._no_direct_fds:
                # O_DIRECT classification: only the aligned prefix may
                # carry the flag; the unaligned tail is deferred to a
                # buffered pwrite at drain(), after the ring quiesces
                # and the fd drops O_DIRECT
                pre, tail, tail_off = self._split_aligned(bufs, off)
                if pre:
                    self._set_direct(fd)
                    self._submit_run(fd, pre, off, direct=True)
                if tail:
                    self._tails.append((fd, list(tail), tail_off))
            else:
                # plain (page-cache) ring writes have no alignment
                # requirement: the WHOLE run goes out as SQEs — batched
                # submission is the point of the engine whether or not
                # O_DIRECT is opted in
                run = [(b, _buf_addr(b), memoryview(b).nbytes)
                       for b in bufs]
                self._submit_run(fd, run, off, direct=False)
        finally:
            self.submit_s += _time.perf_counter() - t0

    def _submit_run(self, fd: int, run: list, off: int,
                    direct: bool) -> None:
        """Stamp SQEs for one contiguous run of (buf, addr, len): one SQE
        per buffer when every one sits in a registered region
        (WRITE_FIXED skips the per-op page pinning); else one vectored
        SQE for the whole run."""
        if not run:
            return
        idxs = [self._buf_index(a, ln) for _, a, ln in run]
        if all(i >= 0 for i in idxs):
            cur = off
            for (b, a, ln), bi in zip(run, idxs):
                self._push((_OP_WRITE_FIXED, fd, a, ln, cur, bi),
                           [b], None, direct)
                cur += ln
        else:
            iov = (_IoVec * len(run))()
            for i, (_, a, ln) in enumerate(run):
                iov[i].base, iov[i].len = a, ln
            self._push((_OP_WRITEV, fd, ctypes.addressof(iov),
                        len(run), off, 0),
                       [b for b, _, _ in run], iov, direct)

    def _push(self, sqe_args, bufs, keepalive, direct: bool) -> None:
        ring = self._ring
        while ring.sq_space() <= 0:
            self._reap_some(1)
        op, fd, addr, ln, off, bi = sqe_args
        self._seq += 1
        ud = self._seq
        nbytes = ln if op == _OP_WRITE_FIXED else \
            sum(memoryview(b).nbytes for b in bufs)
        self._pending[ud] = (op, fd, bufs, off, nbytes, keepalive, bi,
                             direct)
        ring.push(op, fd, addr, ln, off, ud, bi if bi >= 0 else 0)
        # no enter() here: SQEs accumulate and go to the kernel in ONE
        # enter at the next reap (enter always flushes _to_submit) — the
        # whole point of the ring over a syscall per pwritev

    # -- completion --------------------------------------------------------

    def _complete(self, ud: int, res: int) -> None:
        op, fd, bufs, off, nbytes, _keep, bi, direct = \
            self._pending.pop(ud)
        if res == nbytes:
            self.wbytes += nbytes
            if direct:
                self.direct_bytes += nbytes
            if op == _OP_WRITE_FIXED:
                self.fixed_bytes += nbytes
            return
        if res == -errno.EINVAL and direct:
            # this filesystem (or this fd's backing store) refuses
            # O_DIRECT after the probe said otherwise: latch the fd
            # buffered and rewrite the whole failed run.  The per-op
            # flag (not fd membership in _direct_fds) decides — the
            # FIRST failing CQE already un-latched the fd, and every
            # other in-flight direct run completing after it must take
            # this same rewrite path instead of hard-failing the encode
            self._clear_direct(fd)
            self._no_direct_fds.add(fd)
            _pwritev_all(fd, bufs, off)
            self.wbytes += nbytes
            return
        if res < 0:
            raise OSError(-res, os.strerror(-res))
        # short write: finish the remainder synchronously (a direct op
        # clears the flag first — the remainder is no longer aligned)
        if direct:
            self._clear_direct(fd)
            self._no_direct_fds.add(fd)
        mvs = [memoryview(b) for b in bufs]
        skip = res
        rest_off = off + res
        rest = []
        for mv in mvs:
            if skip >= len(mv):
                skip -= len(mv)
                continue
            rest.append(mv[skip:] if skip else mv)
            skip = 0
        _pwritev_all(fd, rest, rest_off)
        self.wbytes += nbytes

    def _reap_some(self, want: int) -> None:
        ring = self._ring
        got = 0
        while got < want and ring.inflight:
            cqe = ring.pop()
            if cqe is None:
                ring.enter(min_complete=1)
                continue
            got += 1
            try:
                self._complete(*cqe)
            except BaseException as e:
                self._errors.append(e)

    def _reap_all(self) -> None:
        if self._ring is not None:
            self._reap_some(self._ring.inflight + len(self._pending))

    def drain(self) -> None:
        """Complete every queued write (including deferred unaligned
        tails); raises the first error.  No-op for synchronous modes."""
        import time as _time
        t0 = _time.perf_counter()
        try:
            self._reap_all()
            tails, self._tails = self._tails, []
            for fd, bufs, off in tails:
                try:
                    self._clear_direct(fd)
                    _pwritev_all(fd, bufs, off)
                    self.wbytes += sum(memoryview(b).nbytes for b in bufs)
                except BaseException as e:
                    self._errors.append(e)
        finally:
            self.complete_s += _time.perf_counter() - t0
        if self._errors:
            err, self._errors = self._errors[0], []
            raise err

    def close(self) -> None:
        try:
            self.drain()
        finally:
            for fd in list(self._direct_fds):
                try:
                    self._clear_direct(fd)
                except OSError:
                    pass
            if self._ring is not None:
                self._ring.close()
                self._ring = None
