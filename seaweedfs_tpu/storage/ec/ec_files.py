"""EC shard file generation / rebuild / decode — the TPU data plane.

The reference streams 10x256KB buffers through a CPU SIMD encoder
(weed/storage/erasure_coding/ec_encoder.go:120-235). Here each batch is a
[10, B] uint8 matrix shipped to the device once and erasure-coded by the
bit-sliced MXU codec; B defaults to 16MB per shard (160MB per batch) so the
kernel runs deep in its throughput regime and host<->device transfers
amortise. Data shards are written straight from the host buffer — only
parity ([4, B]) comes back from the device.

Functions mirror the reference's capability surface:
  write_ec_files      <- WriteEcFiles (ec_encoder.go:56)
  rebuild_ec_files    <- RebuildEcFiles (ec_encoder.go:91)
  write_sorted_ecx    <- WriteSortedFileFromIdx (ec_encoder.go:27)
  write_dat_file      <- WriteDatFile (ec_decoder.go:153)
  write_idx_from_ecx  <- WriteIdxFileFromEcIndex (ec_decoder.go:18)
  find_dat_file_size  <- FindDatFileSize (ec_decoder.go:48)
"""

from __future__ import annotations

import functools
import os
import queue
import sys
import threading
import time

import numpy as np

from seaweedfs_tpu.storage import idx as idxf
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import layout

DEFAULT_BATCH = 16 * 1024 * 1024  # bytes per shard per device round-trip


@functools.lru_cache(maxsize=8)
def _mesh_codec(k: int, m: int):
    from seaweedfs_tpu.models import rs
    from seaweedfs_tpu.parallel import mesh as pmesh
    return pmesh.ShardedRSEncoder(rs.get_code(k, m), pmesh.make_mesh())


def _get_codec(kind: str | None = None, tag: str | None = None):
    """Select the EC codec backend: the `ec.codec` knob of this framework.

    auto (default): Pallas on TPU, native C++ AVX2 on CPU hosts, XLA
    bit-sliced otherwise.  Override with WEEDTPU_EC_CODEC=tpu|jax|cpp|numpy.

    `tag` picks the CODE (ops/codecs grammar: rs_10_4 / lrc_10_2_2 /
    msr_9_16); non-RS families build through the codec registry, which
    reuses the same backend kinds over their matrices."""
    kind = kind or os.environ.get("WEEDTPU_EC_CODEC", "auto")
    if tag is not None:
        from seaweedfs_tpu.ops import codecs as _codecs
        spec = _codecs.parse_tag(tag)
        if spec.family != "rs":
            return _codecs.make_codec(spec.tag, kind)
    k, m = layout.DATA_SHARDS, layout.PARITY_SHARDS
    if kind in ("cpp", "native"):
        from seaweedfs_tpu.ops import native_codec
        return native_codec.get_codec(k, m)
    if kind == "numpy":
        from seaweedfs_tpu.models import rs
        return rs.get_code(k, m)
    if kind == "mesh":
        # multi-chip column-parallel codec (parallel/mesh.py): stripes
        # shard over every attached device; memoized so the jitted
        # shard_maps compile once per (k, m)
        return _mesh_codec(k, m)
    if kind == "auto":
        import jax
        if jax.default_backend() == "tpu":
            from seaweedfs_tpu.ops import pallas_gf
            return pallas_gf.get_codec(k, m)
        from seaweedfs_tpu import native
        if native.available():
            from seaweedfs_tpu.ops import native_codec
            return native_codec.get_codec(k, m)
        from seaweedfs_tpu.ops import gfmat_jax
        return gfmat_jax.get_codec(k, m)
    if kind == "tpu":
        from seaweedfs_tpu.ops import pallas_gf
        return pallas_gf.get_codec(k, m)
    from seaweedfs_tpu.ops import gfmat_jax
    return gfmat_jax.get_codec(k, m)


# backend seam (ops/dispatch.py): parity dispatch, the d2h sync point,
# and reconstruction, without backend imports in this layer
from seaweedfs_tpu.stats import netflow as _netflow  # noqa: E402
from seaweedfs_tpu.stats import pipeline as _pipeline  # noqa: E402
from seaweedfs_tpu.stats import profile as _profile  # noqa: E402
from seaweedfs_tpu.ops.dispatch import (  # noqa: E402
    dispatch_parity as _dispatch_parity,
    materialize as _materialize,
    reconstruct_batch as _reconstruct_batch,
)

# batch buffers in flight: read N+1 / encode N / drain N-1
PIPELINE_DEPTH = int(os.environ.get("WEEDTPU_EC_PIPELINE_DEPTH", "3"))
# queued writes per shard fd before submission backpressures
WRITER_DEPTH = int(os.environ.get("WEEDTPU_EC_WRITER_DEPTH", "4"))


def _writer_threads(nshards: int) -> int:
    """Writer threads for an nshards-wide writer pool.  Shard fds are
    striped over the workers (same shard -> same worker, so per-shard
    write order holds); WEEDTPU_EC_WRITERS pins the count.  The default
    is CPU-aware: one worker per shard maximises overlap on a wide
    storage host, but on a 2-core box 14 threads just thrash the
    scheduler and the page-cache locks — there, a couple of workers
    already saturate the copy bandwidth."""
    env = int(os.environ.get("WEEDTPU_EC_WRITERS", "0"))
    if env > 0:
        return max(1, min(nshards, env))
    return max(2, min(nshards, os.cpu_count() or 2))


def _map_readonly(fd: int, size: int):
    """Read-only map of a source file for the encode/rebuild producers.

    When the file plausibly fits in RAM (or WEEDTPU_EC_PREFAULT=always)
    the map is created MAP_POPULATE: one batched kernel pass sets up
    every PTE, measurably faster than the ~256k/GiB demand faults a
    fresh mapping otherwise takes while the writer threads are
    saturating the cores.  A volume bigger than a quarter of RAM (or
    WEEDTPU_EC_PREFAULT=never) streams with plain demand faulting +
    MADV_SEQUENTIAL readahead instead — populating it upfront would
    serialize the whole disk read ahead of the first encoded byte and
    churn the page cache."""
    import mmap as mmap_mod
    flags = mmap_mod.MAP_SHARED
    populate = getattr(mmap_mod, "MAP_POPULATE", 0)
    mode = os.environ.get("WEEDTPU_EC_PREFAULT", "auto")
    if populate and mode != "never":
        if mode == "always":
            flags |= populate
        else:
            try:
                ram = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
            except (ValueError, OSError, AttributeError):
                ram = 0
            if ram and size <= ram // 4:
                flags |= populate
    mm = mmap_mod.mmap(fd, 0, flags=flags, prot=mmap_mod.PROT_READ)
    try:
        mm.madvise(mmap_mod.MADV_SEQUENTIAL)
    except (AttributeError, OSError):
        pass
    return mm


def write_ec_files(base: str, dat_path: str | None = None,
                   large_block: int = layout.LARGE_BLOCK_SIZE,
                   small_block: int = layout.SMALL_BLOCK_SIZE,
                   batch_size: int = DEFAULT_BATCH,
                   progress=None, cancel=None, stats=None,
                   codec_tag: str | None = None) -> None:
    """Encode `<base>.dat` (or dat_path) into `<base>.ec00` .. `.ec13`,
    plus a `<base>.vif` volume-info sidecar recording the encode-time dat
    size and version (the reference's .vif, volume_info.go:16-40, as JSON):
    the layout was cut from the FILE size, which later lookups cannot
    reliably re-derive from the index once tail needles get deleted.

    `progress(bytes_done)` is called per batch with ACTUAL volume bytes
    consumed and `cancel()` (returning True) aborts mid-stream — a 30GB
    encode must be observable and stoppable (the reference streams progress
    over its gRPC seam).  `stats`, when a dict, receives per-stage wall-time
    attribution (read/encode/write seconds) for bench.py.

    Shards build under `.tmp` names and commit by rename only when the
    whole encode succeeds, so a cancelled/crashed encode leaves any
    previous valid shard set (and its .ecx/.vif) untouched.  Stale `.tmp`
    files from an earlier failed/cancelled attempt are recycled in place
    (opened without O_TRUNC): a retried encode overwrites the already-
    allocated pages instead of faulting in fresh ones, which matters both
    on hosts with lazy page allocation and for filesystems that would
    otherwise re-extend the files block by block."""
    dat_path = dat_path or base + ".dat"
    dat_size = os.path.getsize(dat_path)
    from seaweedfs_tpu.ops import codecs as _codecs
    spec = _codecs.parse_tag(codec_tag or _codecs.default_tag())
    codec = _get_codec(tag=spec.tag)

    # chaos hook: an armed shard_write_error fault (maintenance/faults)
    # fails the encode exactly like a dying disk would — before any tmp
    # shard file exists, so the previous valid shard set stays intact
    from seaweedfs_tpu.maintenance import faults as _faults
    _faults.check_shard_write(base)

    tmp_paths = [base + layout.to_ext(i) + ".tmp"
                 for i in range(spec.n)]
    # O_RDWR without O_TRUNC: recycle pages of stale tmp files (see above);
    # _encode_stream ftruncates each fd to its exact final size.
    out_fds = [os.open(p_, os.O_RDWR | os.O_CREAT, 0o644) for p_ in tmp_paths]
    ok = False
    try:
        _encode_stream(codec, dat_path, dat_size, large_block, small_block,
                       batch_size, out_fds, progress, cancel, stats)
        ok = True
    finally:
        for fd in out_fds:
            os.close(fd)
        if ok:
            write_vif(base, dat_size, codec=spec.tag)
            for i, p_ in enumerate(tmp_paths):
                os.replace(p_, base + layout.to_ext(i))
        else:
            for p_ in tmp_paths:
                try:
                    os.remove(p_)
                except OSError:
                    pass


def _iter_units(dat_size: int, large_block: int, small_block: int,
                batch_size: int, data_shards: int = layout.DATA_SHARDS):
    """Yield (row_start, block, col, step, shard_off) column-batch work
    units in shard file order: N full rows of k large blocks, then
    small-block rows.  shard_off is the unit's byte offset inside every
    shard file (all n shard files are parallel arrays of blocks).
    `data_shards` is the codec's stripe width k (10 for RS/LRC, 9 for
    MSR volumes)."""
    k = data_shards
    processed = 0
    remaining = dat_size
    shard_base = 0
    while remaining > large_block * k:
        step = min(batch_size, large_block)
        assert large_block % step == 0, (large_block, step)
        for col in range(0, large_block, step):
            yield processed, large_block, col, step, shard_base + col
        processed += large_block * k
        remaining -= large_block * k
        shard_base += large_block
    while remaining > 0:
        step = min(batch_size, small_block)
        assert small_block % step == 0, (small_block, step)
        for col in range(0, small_block, step):
            yield processed, small_block, col, step, shard_base + col
        processed += small_block * k
        remaining -= small_block * k
        shard_base += small_block


class EncodeCancelled(RuntimeError):
    pass


_CFR_OK = True  # copy_file_range support, latched off on first failure


def _copy_range(src_fd: int, dst_fd: int, src_off: int, dst_off: int,
                count: int, src_view: np.ndarray | None = None) -> None:
    """In-kernel copy of a .dat slice into a shard file (no user-space
    transit), falling back to pwrite from the mmap view where
    copy_file_range is unsupported (non-regular files, cross-fs, old
    kernels)."""
    global _CFR_OK
    if _CFR_OK and hasattr(os, "copy_file_range"):
        so, do, left = src_off, dst_off, count
        try:
            while left > 0:
                n = os.copy_file_range(src_fd, dst_fd, left, so, do)
                if n <= 0:
                    raise OSError("copy_file_range returned 0")
                so += n
                do += n
                left -= n
            return
        except OSError:
            _CFR_OK = False
            src_off, dst_off, count = so, do, left  # resume where CFR died
    if count > 0 and src_view is not None:
        _pwrite_all(dst_fd, src_view[src_off:src_off + count], dst_off)


class _Timer:
    """Accumulates wall seconds into stats[key]; no-op when stats is None."""

    def __init__(self, stats, key):
        self.stats, self.key = stats, key

    def __enter__(self):
        if self.stats is not None:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.stats is not None:
            self.stats[self.key] = self.stats.get(self.key, 0.0) + \
                (time.perf_counter() - self.t0)
        return False


def _finalize_shards(out_fds, highwater, shard_size: int) -> None:
    """Cut every shard file to exactly shard_size: truncate to the written
    high-water mark first (drops stale bytes of a recycled tmp file), then
    extend — the zero suffix becomes a filesystem hole, so fully-padded
    regions (e.g. a 40MB volume in a 16MB-block layout) cost no write I/O
    at all."""
    for fd, hw in zip(out_fds, highwater):
        os.ftruncate(fd, min(hw, shard_size))
        if hw < shard_size:
            os.ftruncate(fd, shard_size)


def _encode_stream(codec, dat_path: str, dat_size: int, large_block: int,
                   small_block: int, batch_size: int, out_fds,
                   progress=None, cancel=None, stats=None) -> None:
    """Stream the .dat through the codec into the 14 shard fds.

    Two strategies behind one surface, both writing through the
    per-shard writer pool (_ShardWriterPool) so all 14 shard files land
    concurrently:
      - host codecs (native AVX2/GFNI): the GF matmul runs on the calling
        thread straight off an mmap of the .dat via per-row pointers (no
        staging copy), data shards move by in-kernel copy_file_range on
        their writers, and parity rides a small buffer ring — encode of
        unit N overlaps the writes of units N-1.. .
      - device codecs (Pallas/XLA/mesh/numpy): the overlapped reader ->
        dispatch -> drain -> writers pipeline, since JAX dispatch is
        async and the device round-trip genuinely overlaps host I/O.
        Reads stage from the mmap into pooled buffers (no per-batch
        allocation); only parity rides the device.

    WEEDTPU_EC_PIPELINE=serial|pipelined|auto forces the strategy (the
    pipelined machinery accepts host codecs too — bench.py uses that to
    race the two modes on the same codec).

    Rows wholly beyond the .dat are never read, encoded, or written: the
    parity of an all-zero row region is zero, so those regions become
    holes (_finalize_shards).  Partially-covered units encode only the
    rows that carry data, against a column-sliced parity matrix."""
    # stage attribution always accumulates (even when the caller brought
    # no dict): the stats keys feed the pipeline job /debug/pipeline
    # renders, so a production encode is observable, not just a bench one
    stats = stats if stats is not None else {}
    stats["bytes"] = dat_size
    shard_size = layout.shard_file_size(dat_size, large_block, small_block,
                                        data_shards=codec.k)
    highwater = [0] * (codec.k + codec.m)
    if dat_size == 0:
        _finalize_shards(out_fds, highwater, shard_size)
        return

    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    native_host = isinstance(codec, NativeRSCodec)
    pipe = os.environ.get("WEEDTPU_EC_PIPELINE", "auto")
    # the serial-host strategy needs the native ptr-matmul, so it is only
    # reachable for host codecs; `auto` prefers the pipelined machinery
    # even then — interleaved A/B pairs (bench._bench_pipeline_ratio) show
    # the dedicated dispatch/drain threads edge out the serial loop even
    # on a 2-core host, and wider hosts only widen the gap
    use_serial = native_host and pipe == "serial"
    stats["mode"] = "host-serial" if use_serial else "pipelined"

    t_wall = time.perf_counter()
    import mmap as mmap_mod
    with _pipeline.track("ec_encode", stats, dat_size,
                         meta={"mode": stats["mode"]}) as pjob, \
            open(dat_path, "rb") as datf:
        dat_fd = datf.fileno()
        mm = _map_readonly(dat_fd, dat_size)
        dat_view = np.frombuffer(mm, dtype=np.uint8)
        try:
            if use_serial:
                _encode_serial_host(codec, dat_fd, dat_view, dat_size,
                                    large_block, small_block, batch_size,
                                    out_fds, highwater, progress, cancel,
                                    stats)
            else:
                _encode_pipelined(codec, dat_fd, dat_view, dat_size,
                                  large_block, small_block, batch_size,
                                  out_fds, highwater, progress, cancel,
                                  stats, pjob)
        finally:
            del dat_view
            try:
                mm.close()
            except BufferError:
                # an in-flight exception's traceback frames still hold
                # views into the map; GC reaps the mapping with them
                pass
        stats["wall_s"] = time.perf_counter() - t_wall
        frac = overlap_fraction(stats)
        if frac is not None:
            stats["overlap_frac"] = frac
        # stage BYTES are analytic (the layout fixes them), booked once:
        # zero hot-path cost, and the bottleneck verdict gets achieved
        # GB/s per stage for its ceiling-fraction attribution
        _book_stage_bytes(pjob, stats, dat_size,
                          codec.m * shard_size)
    _finalize_shards(out_fds, highwater, shard_size)


def _book_stage_bytes(pjob, stats: dict, data_bytes: int,
                      parity_bytes: int) -> None:
    """Attribute the run's bytes to whichever stages actually ran (a
    host-serial encode has no read/d2h stage; booking bytes against a
    zero-second stage would invent infinite-GB/s rows)."""
    for key, nbytes in (("read_s", data_bytes), ("encode_s", data_bytes),
                        ("d2h_s", parity_bytes),
                        ("write_data_s", data_bytes),
                        ("write_parity_s", parity_bytes),
                        ("reconstruct_s", data_bytes),
                        ("write_s", parity_bytes)):
        if stats.get(key):
            pjob.add_bytes(key[:-2], nbytes)


def _unit_steps(dat_size: int, large_block: int, small_block: int,
                batch_size: int,
                data_shards: int = layout.DATA_SHARDS) -> tuple[int, int]:
    """(min, max) column-batch step _iter_units will actually cut for this
    volume — min picks direct vs batched submission, max sizes the parity
    ring buffers.  Sizing by the actual max matters: a small-block-only
    volume (every production volume under 10x large_block) cuts 1MB units,
    and ring buffers sized by the never-used large step would cycle an 8x
    larger working set through the cache for nothing."""
    k = data_shards
    row = large_block * k
    n_large = (dat_size - 1) // row if dat_size > row else 0
    remaining = dat_size - n_large * row
    steps = []
    if n_large:
        steps.append(min(batch_size, large_block))
    if remaining > 0:
        steps.append(min(batch_size, small_block))
    if not steps:
        steps = [batch_size]
    return min(steps), max(steps)


def _unit_coverage(dat_size: int, row_start: int, block: int, col: int,
                   step: int,
                   data_shards: int = layout.DATA_SHARDS) -> tuple[int, int]:
    """-> (nz, tail): nz = number of leading rows carrying any data in this
    unit, tail = valid bytes in row nz-1 (== step when that row is full)."""
    nz = 0
    tail = step
    for j in range(data_shards):
        off = row_start + j * block + col
        n = min(step, dat_size - off)
        if n <= 0:
            break
        nz = j + 1
        tail = n
    return nz, tail


# the raw write primitives live with the async engine now; re-exported
# here because callers (and tests) reach them through this module
from seaweedfs_tpu.storage.aio import (  # noqa: E402
    _pwrite_all, _pwritev_all, aligned_empty as _aligned_empty)
from seaweedfs_tpu.storage import aio as _aio  # noqa: E402


def _countdown(n: int, cb):
    """Return a thunk that invokes cb after being called n times — the
    release hook for a pooled buffer fanned out to n shard writers."""
    lock = threading.Lock()
    left = [n]

    def hit() -> None:
        with lock:
            left[0] -= 1
            if left[0] > 0:
                return
        cb()
    return hit


class _ShardWriterPool:
    """pwrite/copy_file_range workers servicing the shard fds behind
    bounded queues.

    Shards are striped over _writer_threads(n) workers with a FIXED
    shard -> worker mapping: writes to different shard files proceed
    concurrently — a stall on one file no longer serializes the other
    13 — while writes to the SAME shard stay in submission order on its
    designated thread (they target disjoint offsets, but ordering keeps
    the fd's high-water mark and the page cache walk sequential).  On a
    wide host the default is one worker per shard; on a small host a
    couple of workers carry all 14 fds instead of thrashing the
    scheduler.  Bounded queues make submission apply backpressure
    instead of buffering a whole volume in flight.

    Workers never die: after the first error they drain remaining items
    without touching the fds (still firing release hooks) so producers
    can never deadlock on a full queue; the first error surfaces via
    `.errors` after close().  Busy seconds accumulate per SHARD (not per
    worker) and close() folds them into the stats dict under
    stage_key(shard_index), preserving the write_data_s/write_parity_s
    attribution bench.py reports.

    The actual byte-moving rides the host async-I/O engine
    (storage/aio.py): each worker owns a WriteEngine (io_uring ring with
    O_DIRECT on aligned runs, degrading to pwritev / buffered per
    WEEDTPU_AIO).  Release hooks fire only after the engine drains a
    batch — an async kernel may still be reading a parity buffer long
    after submission returned.  `reg_bufs` (the parity/output rings) are
    registered with every worker's ring so aligned writes go out as
    WRITE_FIXED.  close() folds the engines' submit/complete seconds
    into stats next to the write stages."""

    def __init__(self, fds, highwater=None, stats=None, stage_key=None,
                 depth: int | None = None, workers: int | None = None,
                 reg_bufs=None):
        self._fds = list(fds)
        self._hw = highwater
        self._stats = stats
        self._stage_key = stage_key or (lambda i: "write_s")
        self._mode = _aio.engine_mode()
        self._reg = list(reg_bufs) if reg_bufs else None
        self._engines: list = []
        n = workers if workers else _writer_threads(len(self._fds))
        self._nworkers = max(1, min(len(self._fds), n))
        shards_per = -(-len(self._fds) // self._nworkers)
        self._queues: list[queue.Queue] = [
            queue.Queue(maxsize=(depth or WRITER_DEPTH) * shards_per)
            for _ in range(self._nworkers)]
        self._busy = [0.0] * len(self._fds)
        self._wbytes = [0] * len(self._fds)
        self.errors: list[BaseException] = []
        self._threads = [
            threading.Thread(target=self._run, args=(w,),
                             name=f"ec-writer-{w:02d}", daemon=True)
            for w in range(self._nworkers)]
        for t in self._threads:
            t.start()

    @property
    def failed(self) -> bool:
        return bool(self.errors)

    def _q(self, shard: int) -> queue.Queue:
        return self._queues[shard % self._nworkers]

    def put(self, shard: int, data, off: int, release=None) -> None:
        """Queue a pwrite of a 1-D uint8 buffer at `off`; the caller must
        keep `data` valid until `release` (or the write) completes."""
        self._q(shard).put((shard, [(data, None, off, release)]))

    def copy(self, shard: int, src_fd: int, src_off: int, dst_off: int,
             count: int, src_view=None) -> None:
        """Queue an in-kernel copy_file_range into the shard file."""
        self._q(shard).put(
            (shard, [(None, (src_fd, src_off, count, src_view), dst_off,
                      None)]))

    def put_many(self, shard: int, jobs: list) -> None:
        """Queue a batch of jobs as ONE queue item — one worker wakeup per
        batch, not per job (see _ShardFlusher)."""
        self._q(shard).put((shard, jobs))

    _IOV_RUN = 512  # max buffers merged into one pwritev (< IOV_MAX)

    def _run(self, w: int) -> None:
        q = self._queues[w]
        eng = _aio.WriteEngine(mode=self._mode, reg=self._reg)
        self._engines.append(eng)
        try:
            while True:
                batch = q.get()
                if batch is None:
                    return
                shard, item = batch
                fd = self._fds[shard]
                t0 = time.perf_counter()
                # releases fire only after the batch DRAINS: with an
                # async ring the kernel may still be reading a buffer
                # long after submission returned, and a recycled parity
                # buffer mid-read is silent corruption
                releases: list = []
                ends: list[tuple[int, int]] = []
                idx = 0
                while idx < len(item):
                    data, cfr, off, release = item[idx]
                    if release is not None:
                        releases.append(release)
                    idx += 1
                    try:
                        if self.errors:
                            continue  # drain without touching the fd
                        if cfr is not None:
                            src_fd, src_off, count, src_view = cfr
                            # in-kernel copies want plain buffered fd
                            # semantics: barrier the ring, drop O_DIRECT
                            eng.ensure_buffered(fd)
                            _copy_range(src_fd, fd, src_off, off, count,
                                        src_view=src_view)
                            end = off + count
                            self._wbytes[shard] += count
                            if self._hw is not None and \
                                    end > self._hw[shard]:
                                self._hw[shard] = end
                        else:
                            # merge the run of pwrites targeting
                            # contiguous offsets into one submission
                            bufs = [np.ascontiguousarray(data)]
                            end = off + bufs[0].nbytes
                            while (idx < len(item)
                                   and len(bufs) < self._IOV_RUN
                                   and item[idx][1] is None
                                   and item[idx][2] == end):
                                nxt = np.ascontiguousarray(item[idx][0])
                                bufs.append(nxt)
                                end += nxt.nbytes
                                if item[idx][3] is not None:
                                    releases.append(item[idx][3])
                                idx += 1
                            eng.writev(fd, bufs, off)
                            ends.append((end, end - off))
                    except BaseException as e:  # surfaced after close
                        self.errors.append(e)
                try:
                    eng.drain()
                except BaseException as e:
                    self.errors.append(e)
                else:
                    if not self.errors:
                        for end, n in ends:
                            self._wbytes[shard] += n
                            if self._hw is not None and \
                                    end > self._hw[shard]:
                                self._hw[shard] = end
                self._busy[shard] += time.perf_counter() - t0
                for rel in releases:
                    rel()
        finally:
            try:
                eng.close()
            except BaseException as e:
                self.errors.append(e)

    # a bare pool quacks like a _ShardFlusher so producers can submit
    # DIRECTLY when units are big enough that per-job queue hops are
    # cheap relative to the writes themselves (see _make_sink)
    def account(self, nbytes: int) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        """Drain every queue, join the workers, fold busy seconds into
        stats.  Idempotent, and does not raise — callers inspect
        `.errors`, letting a producer-side exception win over a writer
        one."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join()
        if self._stats is not None:
            # engine sub-stages: where the write stage's wall actually
            # went — SQE stamping + submission syscalls vs CQE waits.
            # These are SUBSETS of the write_* busy seconds (same clock,
            # finer cut), so overlap_fraction excludes them; the
            # pipeline snapshot shows them as disk stages with the full
            # worker capacity behind them
            sub = sum(e.submit_s for e in self._engines)
            comp = sum(e.complete_s for e in self._engines)
            if sub or comp:
                self._stats["submit_s"] = \
                    self._stats.get("submit_s", 0.0) + sub
                self._stats["complete_s"] = \
                    self._stats.get("complete_s", 0.0) + comp
                for wkey in ("submit_workers", "complete_workers"):
                    self._stats[wkey] = self._stats.get(wkey, 0.0) + \
                        self._nworkers
            direct = sum(e.direct_bytes for e in self._engines)
            if direct:
                self._stats["aio_direct_bytes"] = \
                    self._stats.get("aio_direct_bytes", 0) + direct
            # aio_mode is what the engines RESOLVED to, not what the
            # pool asked for: a worker's ring setup can fail where the
            # probe passed (RLIMIT_NOFILE, memlock) and degrade that
            # engine alone — report the most-degraded mode seen so a
            # partly-synchronous round never wears the 'uring' label in
            # the trajectory gate's like-for-like comparison
            rank = {"buffered": 0, "pwritev": 1, "uring": 2}
            modes = [e.mode for e in self._engines]
            resolved = min(modes, key=lambda m: rank.get(m, 0)) \
                if modes else self._mode
            cur = self._stats.get("aio_mode")
            if cur is None or rank.get(resolved, 0) < rank.get(cur, 3):
                self._stats["aio_mode"] = resolved
            degraded = sum(1 for m in modes if m != self._mode)
            if degraded:
                self._stats["aio_degraded_engines"] = \
                    self._stats.get("aio_degraded_engines", 0) + degraded
        if self._stats is not None:
            key_busy: dict[str, float] = {}
            for i, busy in enumerate(self._busy):
                key = self._stage_key(i)
                self._stats[key] = self._stats.get(key, 0.0) + busy
                key_busy[key] = key_busy.get(key, 0.0) + busy
            # stage seconds above are summed across N parallel shard
            # slots: publish the capacity backing them so occupancy math
            # (stats/pipeline busy_frac) divides by it instead of
            # reading a 4-worker 30%-busy pool as a 120%-saturated
            # stage.  The pool's threads split across its stages IN
            # PROPORTION TO BUSY SECONDS — write_data and write_parity
            # share one thread set, and naming each stage the full
            # thread count would let a fully saturated pool read as two
            # half-saturated stages and hand the bottleneck verdict to
            # the wrong stage.  ACCUMULATED, not first-wins —
            # fleet_convert's per-volume pools all fold into one shared
            # stats dict, and their concurrent workers are all capacity
            total_busy = sum(key_busy.values())
            for key, busy_k in key_busy.items():
                if key.endswith("_s") and total_busy > 0:
                    wkey = key[:-2] + "_workers"
                    self._stats[wkey] = self._stats.get(wkey, 0.0) + \
                        self._nworkers * (busy_k / total_busy)
        # the disk-side roofline row: shard writes vs the measured disk
        # ceiling (stats/profile.roofline_snapshot special-cases this
        # kernel onto the wall/bytes columns)
        busy_total, wrote = sum(self._busy), sum(self._wbytes)
        if busy_total > 0 and wrote > 0:
            _profile.KERNELS.record("shard_write", "host", calls=0,
                                    wall_s=busy_total, nbytes=wrote)


FLUSH_BYTES = int(os.environ.get("WEEDTPU_EC_FLUSH_BYTES",
                                 str(8 * 1024 * 1024)))
# units at or above this size skip the submission batcher entirely — a
# queue hop per ~256KB+ write is noise, and direct submission lets the
# writers start (and release pooled buffers) the moment a job exists
# instead of at the next flush-group boundary
DIRECT_MIN = int(os.environ.get("WEEDTPU_EC_DIRECT_MIN",
                                str(256 * 1024)))


def _make_sink(writers: "_ShardWriterPool", nshards: int, min_step: int):
    """Submission front for the writer pool: the pool itself (direct,
    per-job) when every unit is at least DIRECT_MIN bytes, else a
    _ShardFlusher that batches the tiny-unit churn."""
    if min_step >= DIRECT_MIN:
        return writers
    return _ShardFlusher(writers, nshards)


def _parity_ring_size(min_step: int, max_step: int) -> int:
    """Buffers in the countdown-released parity ring.  Direct submission
    needs only the pipeline headroom (writers release per job); the
    batched path must cover a whole unflushed flush group of min_step
    units or the encode stalls on its own batching.  Direct headroom is
    kept at +1 (not more): each buffer is (m, max_step) — 64MB at the
    production 16MB batch — so extra depth is a real RSS cost on a
    storage host running concurrent encodes."""
    if min_step >= DIRECT_MIN:
        return PIPELINE_DEPTH + 1
    return PIPELINE_DEPTH + max(1, FLUSH_BYTES // max_step)


class _ShardFlusher:
    """Producer-side submission batcher for a _ShardWriterPool.

    With the production 16MB column batches each unit is worth a worker
    wakeup, but a small-block-only layout cuts 1MB units — paying a queue
    round-trip per unit per shard costs more scheduler churn than the
    writes themselves on a small host.  The flusher accumulates each
    shard's jobs locally and hands them over as one put_many batch per
    ~FLUSH_BYTES of volume data; the worker then merges the contiguous
    parity runs into single pwritev calls."""

    def __init__(self, writers: _ShardWriterPool, nshards: int,
                 flush_bytes: int = FLUSH_BYTES):
        self._writers = writers
        self._jobs: list[list] = [[] for _ in range(nshards)]
        self._acc = 0
        self._flush_bytes = flush_bytes

    def put(self, shard: int, data, off: int, release=None) -> None:
        self._jobs[shard].append((data, None, off, release))

    def copy(self, shard: int, src_fd: int, src_off: int, dst_off: int,
             count: int, src_view=None) -> None:
        self._jobs[shard].append(
            (None, (src_fd, src_off, count, src_view), dst_off, None))

    def account(self, nbytes: int) -> None:
        """Producers call this once per unit; crossing the flush target
        ships every shard's pending batch."""
        self._acc += nbytes
        if self._acc >= self._flush_bytes:
            self.flush()

    def flush(self) -> None:
        self._acc = 0
        for shard, jobs in enumerate(self._jobs):
            if jobs:
                self._writers.put_many(shard, jobs)
                self._jobs[shard] = []


def overlap_fraction(stats: dict) -> float | None:
    """Achieved stage overlap of an encode/rebuild run: 1 - wall / (sum of
    per-stage seconds).  0.0 means fully serial (the wall clock IS the sum
    of its stages); the upper bound for a given stage mix is
    1 - max_stage/sum.  stall_s is producer IDLE time (waiting on a ring
    buffer), not a productive stage, so it is excluded — a fully
    backpressured run reads as ~0, not as overlapped.  None when the
    stats carry no wall clock or no stage time (e.g. an empty volume)."""
    wall = stats.get("wall_s")
    # submit_s/complete_s are the engine's finer cut of the same seconds
    # the write stages already carry — counting them again would inflate
    # the stage sum and fake overlap
    total = sum(v for key, v in stats.items()
                if key.endswith("_s")
                and key not in ("wall_s", "stall_s", "submit_s",
                                "complete_s")
                and isinstance(v, float))
    if not wall or total <= 0:
        return None
    return round(max(0.0, 1.0 - wall / total), 3)


def _host_parity_unit(codec, dat_view: np.ndarray, tailbuf: np.ndarray,
                      pbuf: np.ndarray, row_start: int, block: int,
                      col: int, step: int, nz: int, tail: int) -> None:
    """Parity for one column unit of a stripe row: gf_matmul_ptrs straight
    off the .dat mmap into pbuf's m rows.  A partial tail row is staged
    into the zeroed tailbuf first; a stripe with nz < k populated rows
    uses a column-truncated generator.  This is the ONE copy of the
    zero-copy host encode — both the serial and pipelined strategies call
    it, so they stay byte-identical by construction."""
    from seaweedfs_tpu import native
    rows = [dat_view[row_start + j * block + col:
                     row_start + j * block + col + step]
            for j in range(nz)]
    if tail < step:
        tailbuf[:tail] = rows[nz - 1][:tail]
        tailbuf[tail:step] = 0
        rows[nz - 1] = tailbuf
    code = codec.code
    mat = code.parity_matrix if nz == code.k else \
        np.ascontiguousarray(code.parity_matrix[:, :nz])
    # the zero-copy path bypasses ops/dispatch, so it feeds the kernel
    # profile itself — otherwise host-encode time vanishes from
    # /debug/pprof?format=table
    with _profile.KERNELS.timed("encode_parity", nbytes=nz * step):
        native.gf_matmul_ptrs(mat, rows, list(pbuf), step)


def _encode_serial_host(codec, dat_fd: int, dat_view: np.ndarray,
                        dat_size: int, large_block: int, small_block: int,
                        batch_size: int, out_fds, highwater,
                        progress=None, cancel=None, stats=None) -> None:
    """Native-codec encode with overlapped shard I/O: the GF matmul runs
    on the calling thread straight off the .dat mmap (zero staging copy),
    while all 14 shard files are written by the per-shard writer pool —
    the encode of unit N overlaps the data copies and parity writes of
    units N-1.. still in flight.  Parity lands in a small ring of pooled
    buffers so the matmul only waits (stall_s) when every buffer is still
    queued behind the disks."""
    k, m = codec.k, codec.m
    min_step, max_step = _unit_steps(dat_size, large_block, small_block,
                                     batch_size, data_shards=k)
    # ALIGN-aligned parity ring: rows qualify for O_DIRECT + registered-
    # buffer submission whenever the step is an ALIGN multiple
    pbufs = [_aligned_empty((m, max_step))
             for _ in range(_parity_ring_size(min_step, max_step))]
    pbuf_pool: queue.Queue = queue.Queue()
    for b in pbufs:
        pbuf_pool.put(b)
    tailbuf = np.zeros(max_step, dtype=np.uint8)
    writers = _ShardWriterPool(
        out_fds, highwater, stats,
        stage_key=lambda i: "write_data_s" if i < k else "write_parity_s",
        reg_bufs=pbufs)
    sink = _make_sink(writers, codec.k + codec.m, min_step)
    done = 0
    try:
        for row_start, block, col, step, shard_off in _iter_units(
                dat_size, large_block, small_block, batch_size,
                data_shards=k):
            if cancel is not None and cancel():
                raise EncodeCancelled("ec encode cancelled")
            if writers.failed:
                break
            nz, tail = _unit_coverage(dat_size, row_start, block, col, step,
                                      data_shards=k)
            if nz == 0:
                continue
            # data shards: in-kernel copy on the per-shard workers, no
            # user-space transit (the mmap view outlives the pool)
            for j in range(nz):
                off = row_start + j * block + col
                n = step if j < nz - 1 else tail
                sink.copy(j, dat_fd, off, shard_off, n,
                          src_view=dat_view)
            try:
                pbuf = pbuf_pool.get_nowait()
            except queue.Empty:
                # ship the pending batches first: their releases are what
                # refill the ring (blocking before the flush would deadlock)
                sink.flush()
                with _Timer(stats, "stall_s"):
                    pbuf = pbuf_pool.get()
            with _Timer(stats, "encode_s"):
                _host_parity_unit(codec, dat_view, tailbuf, pbuf,
                                  row_start, block, col, step, nz, tail)
            release = _countdown(
                m, lambda b=pbuf: pbuf_pool.put(b))
            for i in range(m):
                sink.put(k + i, pbuf[i, :step], shard_off,
                         release=release)
            done += (nz - 1) * step + tail
            sink.account(step)
            if progress is not None:
                progress(done)
        sink.flush()
    finally:
        writers.close()
    if writers.errors:
        raise writers.errors[0]


def _encode_pipelined(codec, dat_fd: int, dat_view: np.ndarray,
                      dat_size: int, large_block: int, small_block: int,
                      batch_size: int, out_fds, highwater,
                      progress=None, cancel=None, stats=None,
                      pjob=None) -> None:
    """Overlapped reader -> dispatch -> drain -> shard-writer pipeline.

    Stages, each on its own thread(s), all behind bounded queues so a
    slow stage backpressures the ones before it instead of buffering the
    volume:

      reader   walks the unit iterator for stripe N+1; data shards go to
               their shard writers by in-kernel copy_file_range on the
               way (they never round-trip the device).  For DEVICE
               codecs it also stages the stripe from the mmap into a
               pooled buffer (read_s) — the device needs a stable host
               buffer to transfer from.  HOST codecs skip the staging
               copy entirely: the dispatch stage encodes straight off
               the mmap, so forcing a host codec through this machinery
               (WEEDTPU_EC_PIPELINE=pipelined) costs no extra memory
               traffic vs the serial strategy.
      dispatch (caller's thread) launches the parity matmul for stripe N
               — asynchronous on JAX backends, eager (ptr-matmul off the
               mmap into a pooled parity ring) for native host codecs
      drain    materialises stripe N-1's parity (d2h_s: the device sync
               point, which the old writer buried inside write_parity_s)
               and fans its m rows out to the shard writers
      writers  striped pwrite workers over the 14 shard fds
               (_ShardWriterPool), so parity files land concurrently
               instead of serially

    A batch buffer returns to the pool as soon as its parity is
    materialised — until then the device may still be reading the
    (possibly zero-copy-aliased on CPU backends) host memory.  Parity
    rows are views into the materialised array, kept alive by the writer
    queue items (host-codec parity rides a countdown-released ring
    instead)."""
    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    native_host = isinstance(codec, NativeRSCodec)
    k, m = codec.k, codec.m
    min_step, max_step = _unit_steps(dat_size, large_block, small_block,
                                     batch_size, data_shards=k)
    pool: queue.Queue = queue.Queue()
    reg_bufs = None
    if native_host:
        tailbuf = np.zeros(max_step, dtype=np.uint8)
        # sized like _parity_ring_size's BATCHED branch: the pipelined
        # drain submits small units through a _ShardFlusher (its pwritev
        # merging measures ~4% faster than direct submission even for
        # DIRECT_MIN-sized units), so the ring must cover a full
        # unflushed flush group.  ALIGN-aligned so O_DIRECT/WRITE_FIXED
        # engage on production block sizes.
        reg_bufs = [_aligned_empty((m, max_step))
                    for _ in range(PIPELINE_DEPTH +
                                   max(1, FLUSH_BYTES // max_step))]
        for b in reg_bufs:
            pool.put(b)
    else:
        for _ in range(PIPELINE_DEPTH):
            pool.put(np.empty((k, max_step), dtype=np.uint8))
    q_read: queue.Queue = queue.Queue(maxsize=PIPELINE_DEPTH)
    # q_disp is unbounded: it carries at most one entry per in-flight
    # pooled buffer (the pool is the real backpressure) plus FLUSH nudges
    q_disp: queue.Queue = queue.Queue()
    # dispatch sends this when it runs dry on parity buffers: the drain's
    # flusher may be sitting on the very jobs whose releases would refill
    # the ring (blocking on pool.get() without the nudge deadlocks)
    FLUSH = object()
    errors: list[BaseException] = []
    writers = _ShardWriterPool(
        out_fds, highwater, stats,
        stage_key=lambda i: "write_data_s" if i < k else "write_parity_s",
        reg_bufs=reg_bufs)
    done = 0

    def reader() -> None:
        nonlocal done
        flusher = _ShardFlusher(writers, k)  # data shards only
        try:
            for row_start, block, col, step, shard_off in _iter_units(
                    dat_size, large_block, small_block, batch_size,
                    data_shards=k):
                if errors or writers.failed:  # downstream died: stop
                    break
                if cancel is not None and cancel():
                    raise EncodeCancelled("ec encode cancelled")
                nz, tail = _unit_coverage(dat_size, row_start, block, col,
                                          step, data_shards=k)
                if nz == 0:
                    continue
                for j in range(nz):
                    off = row_start + j * block + col
                    n = step if j < nz - 1 else tail
                    flusher.copy(j, dat_fd, off, shard_off, n,
                                 src_view=dat_view)
                if native_host:
                    # zero-copy: dispatch encodes off the mmap directly
                    q_read.put((None, step, shard_off,
                                (row_start, block, col, nz, tail)))
                else:
                    with _Timer(stats, "stall_s"):
                        buf = pool.get()
                    with _Timer(stats, "read_s"):
                        batch = buf[:, :step]
                        for j in range(k):
                            off = row_start + j * block + col
                            n = max(0, min(step, dat_size - off))
                            if n > 0:
                                np.copyto(batch[j, :n],
                                          dat_view[off:off + n])
                            if n < step:
                                batch[j, max(n, 0):] = 0
                    q_read.put((buf, step, shard_off, None))
                done += (nz - 1) * step + tail
                flusher.account(step)
                if progress is not None:
                    progress(done)
            flusher.flush()
        except BaseException as e:  # surfaced by the caller's thread
            errors.append(e)
        finally:
            q_read.put(None)

    def drain() -> None:
        failed = False
        # production-size units submit DIRECTLY: each unit's parity is
        # on its writer the moment its d2h lands, so write_parity busy
        # time overlaps the next unit's d2h instead of queueing behind a
        # flush-group boundary.  Tiny units keep the batcher — per-unit
        # queue hops would cost more than the writes.
        flusher = writers if min_step >= DIRECT_MIN else \
            _ShardFlusher(writers, codec.k + codec.m)
        while True:
            item = q_disp.get()
            if item is None:
                flusher.flush()
                return
            if item is FLUSH:
                flusher.flush()
                continue
            buf, step, shard_off, parity, release = item
            if failed or errors or writers.failed:
                if release is not None:
                    for _ in range(m):
                        release()
                elif buf is not None:
                    pool.put(buf)
                continue
            if release is not None:  # host parity: already materialised
                for i in range(m):
                    flusher.put(k + i, parity[i, :step], shard_off,
                                release=release)
                flusher.account(step)
                continue
            try:
                with _Timer(stats, "d2h_s"):
                    pnp = _materialize(parity)
            except BaseException as e:
                errors.append(e)
                failed = True  # keep draining so nothing deadlocks
                pool.put(buf)
                continue
            pool.put(buf)  # device is done with the host memory now
            for i in range(pnp.shape[0]):
                flusher.put(k + i, pnp[i, :step], shard_off)
            flusher.account(step)

    t_r = threading.Thread(target=reader, name="ec-reader", daemon=True)
    t_d = threading.Thread(target=drain, name="ec-drain", daemon=True)
    t_r.start()
    t_d.start()
    try:
        while True:
            item = q_read.get()
            if item is None:
                break
            if pjob is not None:  # stage-queue depth at the consume site
                pjob.queue("q_read", q_read.qsize(), PIPELINE_DEPTH)
            buf, step, shard_off, coverage = item
            if errors or writers.failed:  # stop dispatching, surface below
                if buf is not None:
                    pool.put(buf)
                continue
            if native_host:
                row_start, block, col, nz, tail = coverage
                try:
                    pbuf = pool.get_nowait()
                except queue.Empty:
                    q_disp.put(FLUSH)  # see FLUSH above: avoid deadlock
                    with _Timer(stats, "stall_s"):
                        pbuf = pool.get()
                with _Timer(stats, "encode_s"):
                    _host_parity_unit(codec, dat_view, tailbuf, pbuf,
                                      row_start, block, col, step, nz,
                                      tail)
                release = _countdown(m, lambda b=pbuf: pool.put(b))
                q_disp.put((None, step, shard_off, pbuf, release))
            else:
                with _Timer(stats, "encode_s"):
                    parity = _dispatch_parity(codec, buf[:, :step])
                q_disp.put((buf, step, shard_off, parity, None))
    finally:
        q_disp.put(None)
        t_d.join()
        while t_r.is_alive():  # unblock a reader stuck on a full q_read
            try:
                item = q_read.get(timeout=0.05)
            except queue.Empty:
                continue
            if item is not None and item[0] is not None:
                pool.put(item[0])  # keep the pool whole or the reader starves
        t_r.join()
        writers.close()  # after the producers: no submission can block now
    if errors:
        raise errors[0]
    if writers.errors:
        raise writers.errors[0]


def _survivor_basis(codec, present: list[int],
                    wanted: list[int]) -> list[int]:
    """Which surviving shard files a rebuild must actually read.  RS/MDS:
    any k.  LRC: the code's decode_select picks a minimal span (one local
    group for a single loss).  MSR whole-file rebuild: the file codec's
    node-MDS selection (any k whole files)."""
    sel = getattr(codec, "decode_select", None)
    if sel is not None:  # file-surface hook (MSRFileCodec)
        return list(sel(sorted(present), list(wanted)))
    from seaweedfs_tpu.ops import codec_base as _cb
    code = getattr(codec, "code", codec)
    return list(_cb.select_survivors(code, tuple(sorted(present)),
                                     list(wanted)))


def rebuild_ec_files(base: str, batch_size: int = DEFAULT_BATCH,
                     progress=None, cancel=None, stats=None,
                     codec_tag: str | None = None) -> list[int]:
    """Regenerate whichever `.ecXX` files are missing from the >=10 present
    ones. Returns the rebuilt shard ids.

    Same zero-copy and overlap discipline as the encode path (and the same
    observability: `progress(bytes_done)` per batch over survivor bytes,
    `cancel()` aborts, `stats` gets per-stage seconds + overlap_frac):
    survivor shards are mmap'd and fed to the native decode matmul by row
    pointer, rebuilt shards land in a countdown-released buffer ring and
    stream to per-shard writer workers (the decode of batch N overlaps the
    writes of batch N-1) into recycled `.tmp` inodes, committed by rename
    only on success (reference: RebuildEcFiles, ec_encoder.go:237-291)."""
    from seaweedfs_tpu.ops import codecs as _codecs
    spec = _codecs.parse_tag(codec_tag or
                             (read_vif(base) or {}).get("codec"))
    present = [i for i in range(spec.n)
               if os.path.exists(base + layout.to_ext(i))]
    missing = [i for i in range(spec.n) if i not in present]
    if not missing:
        return []
    if len(present) < spec.k:
        raise ValueError(
            f"need >= {spec.k} shards to rebuild, have {len(present)}")
    # chaos hook: fail like a dying disk BEFORE tmp shard files exist
    from seaweedfs_tpu.maintenance import faults as _faults
    _faults.check_shard_write(base)
    codec = _get_codec(tag=spec.tag)
    use = _survivor_basis(codec, present, missing)
    shard_size = os.path.getsize(base + layout.to_ext(use[0]))
    stats = stats if stats is not None else {}
    stats["bytes"] = shard_size * len(use)
    stats["codec"] = spec.tag
    # MSR sub-packetization: every chunk a codec's interleave must see is
    # an alpha multiple (shard files themselves are block-multiples)
    if spec.alpha > 1:
        batch_size = max(spec.alpha,
                         batch_size - batch_size % spec.alpha)
        if shard_size % spec.alpha:
            raise ValueError(
                f"shard size {shard_size} not {spec.alpha}-aligned")

    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    native_host = isinstance(codec, NativeRSCodec)
    stats["mode"] = "host-serial" if native_host else "staged"
    if native_host:
        from seaweedfs_tpu import native
        dec_mat = codec.code.decode_matrix(list(present), list(missing))

    # a rebuild IS repair work: unless a caller already declared a class
    # (the planner's header re-entered through the middleware), any
    # network hop made on this thread while we run — a remote
    # shard_reader for survivors not on local disk — books as repair
    _flow_token = _netflow.set_class(_netflow.current_class() or "repair")
    pjob = _pipeline.track("ec_rebuild", stats,
                           shard_size * len(use),
                           meta={"missing": len(missing),
                                 "codec": spec.tag})
    t_wall = time.perf_counter()
    import mmap as mmap_mod
    ins: dict[int, object] = {}
    maps = {}
    views = {}
    tmp_paths = {i: base + layout.to_ext(i) + ".tmp" for i in missing}
    out_fds: dict[int, int] = {}
    writers = None
    stage = None
    ok = False
    # setup runs under the same finally that seals the job: a survivor
    # deleted between the present-list and open (a racing repair), or
    # ENOSPC on the tmp outputs, must not leak a forever-"running"
    # ec_rebuild entry on /debug/pipeline
    try:
        for i in use:
            ins[i] = open(base + layout.to_ext(i), "rb")
        for i, p_ in tmp_paths.items():
            out_fds[i] = os.open(p_, os.O_RDWR | os.O_CREAT, 0o644)
        # reconstruction writes ride the same per-shard writer pool as the
        # encode path: rebuilding 4 lost shards streams them to 4 concurrent
        # workers while the next batch's decode matmul runs.  Pooled output
        # buffers (countdown-released once every shard writer is done with
        # its row) keep the decode from racing its own in-flight writes.
        wpos = {i: r for r, i in enumerate(missing)}
        # aligned output ring, registered with the writer engines: the
        # reconstruction writes ride the same aio path as encode parity
        # (heal-side ceiling_frac must match the encode side's)
        obufs = [_aligned_empty(
            (len(missing), min(batch_size, max(shard_size, 1))))
            for _ in range(PIPELINE_DEPTH)]
        writers = _ShardWriterPool([out_fds[i] for i in missing], None,
                                   stats, stage_key=lambda i: "write_s",
                                   reg_bufs=obufs)
        opool: queue.Queue = queue.Queue()
        for b in obufs:
            opool.put(b)
        for i, f in ins.items():
            if shard_size:
                mm = _map_readonly(f.fileno(), shard_size)
                maps[i] = mm
                views[i] = np.frombuffer(mm, dtype=np.uint8)
        done = 0
        for off in range(0, shard_size, batch_size):
            if cancel is not None and cancel():
                raise EncodeCancelled("ec rebuild cancelled")
            if writers.failed:
                break
            n = min(batch_size, shard_size - off)
            with _Timer(stats, "stall_s"):
                obuf = opool.get()
            with _Timer(stats, "reconstruct_s"):
                if native_host:
                    rows = [views[i][off:off + n] for i in use]
                    outs = [obuf[r, :n] for r in range(len(missing))]
                    with _profile.KERNELS.timed("reconstruct",
                                                nbytes=len(use) * n):
                        native.gf_matmul_ptrs(dec_mat, rows, outs, n)
                else:
                    if stage is None:
                        stage = np.empty((len(use),
                                          min(batch_size, shard_size)),
                                         dtype=np.uint8)
                    for row, i in enumerate(use):
                        np.copyto(stage[row, :n], views[i][off:off + n])
                    rebuilt = _reconstruct_batch(
                        codec,
                        {i: stage[row, :n] for row, i in enumerate(use)},
                        missing)
                    for r, i in enumerate(missing):
                        np.copyto(obuf[r, :n], rebuilt[i])
            release = _countdown(len(missing),
                                 lambda b=obuf: opool.put(b))
            for i in missing:
                writers.put(wpos[i], obuf[wpos[i], :n], off,
                            release=release)
            done += n * len(use)
            if progress is not None:
                progress(done)
        writers.close()
        if writers.errors:
            raise writers.errors[0]
        for fd in out_fds.values():
            os.ftruncate(fd, shard_size)
        stats["wall_s"] = time.perf_counter() - t_wall
        frac = overlap_fraction(stats)
        if frac is not None:
            stats["overlap_frac"] = frac
        _book_stage_bytes(pjob, stats,
                          shard_size * len(use),
                          shard_size * len(missing))
        ok = True
    finally:
        _netflow.reset(_flow_token)
        if writers is not None:
            writers.close()  # idempotent; the fds must outlive the workers
        # seal the job only after close() folded the writer-pool busy
        # seconds into stats — finish() exports the stage counters, and
        # a failed rebuild must not export zero write-stage occupancy.
        # The in-flight exception (ENOSPC, vanished survivor) is the
        # error operators triage from /debug/pipeline, not a generic tag
        pjob.finish(None if ok else
                    (sys.exc_info()[1] or "rebuild failed"))
        for f in ins.values():
            f.close()
        for i in list(views):
            del views[i]
        for mm in maps.values():
            try:
                mm.close()
            except BufferError:
                pass
        for fd in out_fds.values():
            os.close(fd)
        if ok:
            for i, p_ in tmp_paths.items():
                os.replace(p_, base + layout.to_ext(i))
        else:
            for p_ in tmp_paths.values():
                try:
                    os.remove(p_)
                except OSError:
                    pass
    return missing


def rebuild_ec_reduced(base: str, lost: list[int], groups: list[dict],
                       fetch_partial, d: int | None = None,
                       batch_size: int = DEFAULT_BATCH,
                       align: int | None = None,
                       progress=None, cancel=None,
                       stats: dict | None = None,
                       codec_tag: str | None = None) -> dict:
    """Reduced-read rebuild of `lost` shards: instead of copying k full
    survivor shards here, each remote helper node ships XOR-combinable
    partial products (ops/regen.py) — repair bandwidth per remote node
    drops to one shard-range per lost shard, byte-identical output.

    `groups` lists the REMOTE helper nodes: {"node": url,
    "shards": [ids], "locality": class}; the local survivor group is
    discovered from the files next to `base` (each rebuilt shard joins
    it for the next pass).  `fetch_partial(node, shards, coeff_rows,
    offset, size) -> bytes` is the server layer's HTTP hop; transport
    failures raise regen.HelperDied and trigger re-planning with a
    substitute survivor.  Lost shards build under `.tmp` names and
    commit by rename per shard, so a helper death / crash never leaves
    a partial shard visible.  Returns accounting: measured helper
    bytes per node + locality class, the plans' predictions, and the
    naive-baseline cost the savings are judged against."""
    from seaweedfs_tpu.ops import regen

    # chaos hook: fail like a dying disk BEFORE tmp shard files exist
    from seaweedfs_tpu.maintenance import faults as _faults
    _faults.check_shard_write(base)

    from seaweedfs_tpu.ops import codecs as _codecs
    spec = _codecs.parse_tag(codec_tag or
                             (read_vif(base) or {}).get("codec"))
    codec = _get_codec(tag=spec.tag)
    if spec.family == "msr":
        # plan coordinates are sub-rows: a batch of S sub-row bytes costs
        # each helper an S*alpha-byte file read — shrink so the helper-
        # side pread stays bounded by the plain path's batch
        batch_size = max(spec.alpha, batch_size // spec.alpha)
    code = getattr(codec, "code", codec)  # RSCode is its own metadata

    lost = sorted(set(lost))
    local_fds: dict[int, int] = {}
    stats = stats if stats is not None else {}
    stats.setdefault("mode", "reduced")
    _flow_token = _netflow.set_class(_netflow.current_class() or "repair")
    t_wall = time.perf_counter()
    try:
        shard_size = 0
        for i in range(spec.n):
            p_ = base + layout.to_ext(i)
            if i not in lost and os.path.exists(p_):
                local_fds[i] = os.open(p_, os.O_RDONLY)
                shard_size = max(shard_size, os.path.getsize(p_))
        if shard_size == 0:
            for g in groups:
                if g.get("shard_size"):
                    shard_size = int(g["shard_size"])
                    break
        if shard_size <= 0:
            raise ValueError(f"cannot size shards of {base}")
        stats["bytes"] = shard_size * len(lost)
        stats["codec"] = spec.tag
        alpha = spec.alpha
        if alpha > 1 and shard_size % alpha:
            raise ValueError(
                f"shard size {shard_size} not {alpha}-aligned for {spec.tag}")

        def read_local(sid: int, off: int, n: int) -> bytes | None:
            fd = local_fds.get(sid)
            if fd is None:
                return None
            try:
                return os.pread(fd, n, off)
            except OSError:
                return None

        # MSR plans address SUB-ROWS: virtual id = file_shard*alpha + row,
        # offsets/lengths in sub-row bytes.  A sub-row is the byte-
        # interleaved slice {t*alpha + row} of its shard file, so reading
        # one means one contiguous pread of [off*alpha, (off+n)*alpha)
        # de-interleaved on the fly; a one-slot cache serves the alpha
        # consecutive sub-row reads execute_plan makes per local file
        # from a single pread.
        _vblk: dict = {}

        def read_local_sub(vid: int, off: int, n: int) -> bytes | None:
            fsid = vid // alpha
            fd = local_fds.get(fsid)
            if fd is None:
                return None
            key = (fsid, off, n)
            blk = _vblk.get(key)
            if blk is None:
                try:
                    raw = os.pread(fd, n * alpha, off * alpha)
                except OSError:
                    return None
                if len(raw) != n * alpha:
                    return None
                blk = np.frombuffer(raw, np.uint8).reshape(n, alpha)
                _vblk.clear()
                _vblk[key] = blk
            return blk[:, vid % alpha].tobytes()

        remote_groups = [
            regen.HelperGroup(node=g["node"],
                              shards=tuple(int(s) for s in g["shards"]
                                           if int(s) not in lost),
                              locality=int(g.get("locality", 3)))
            for g in groups if g.get("shards")]
        done = 0
        predicted: dict = {"per_node": {}, "by_locality": {},
                           "remote": 0, "local": 0}
        for sid in lost:
            tmp = base + layout.to_ext(sid) + ".tmp"
            out_fd = os.open(tmp, os.O_RDWR | os.O_CREAT, 0o644)
            committed = False
            try:
                if spec.family == "msr":
                    # regenerating repair: [alpha, d] posts land as an
                    # [alpha, n] block per segment — re-interleave back
                    # into shard-file byte order on the way to disk
                    def sink(off: int, rows: np.ndarray,
                             fd: int = out_fd) -> None:
                        rows = np.asarray(rows)
                        if rows.ndim == 1:
                            rows = rows.reshape(1, -1)
                        _pwrite_all(
                            fd,
                            np.ascontiguousarray(rows.T.reshape(-1)),
                            off * alpha)

                    planner = regen.plan_msr_repair
                    plan_code = codec  # file codec carries the inner code
                    reader = read_local_sub
                else:
                    def sink(off: int, row: np.ndarray,
                             fd: int = out_fd) -> None:
                        _pwrite_all(fd, np.ascontiguousarray(row), off)

                    planner = None
                    plan_code = code
                    reader = read_local

                local_group = regen.HelperGroup(
                    node="", shards=tuple(sorted(local_fds)), locality=0)
                with _Timer(stats, "reconstruct_s"):
                    plan = regen.repair_shard(
                        plan_code, codec, sid,
                        [local_group] + remote_groups, shard_size,
                        reader, fetch_partial, sink,
                        d=d, batch_size=batch_size,
                        align=align or regen.DEFAULT_SEG_ALIGN,
                        cancel=cancel, stats=stats,
                        planner=planner)
                os.ftruncate(out_fd, shard_size)
                os.close(out_fd)
                out_fd = -1
                os.replace(tmp, base + layout.to_ext(sid))
                committed = True
                pred = plan.predicted_bytes()
                for key in ("remote", "local"):
                    predicted[key] += pred[key]
                for dim in ("per_node", "by_locality"):
                    for k_, v in pred[dim].items():
                        predicted[dim][k_] = predicted[dim].get(k_, 0) + v
                predicted["naive_remote"] = \
                    predicted.get("naive_remote", 0) + \
                    plan.naive_remote_bytes(len(local_group.shards))
                # the rebuilt shard is a local survivor for the next pass
                local_fds[sid] = os.open(base + layout.to_ext(sid),
                                         os.O_RDONLY)
                done += shard_size
                if progress is not None:
                    progress(done)
            finally:
                if out_fd >= 0:
                    os.close(out_fd)
                if not committed:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
        stats["wall_s"] = time.perf_counter() - t_wall
        return {"rebuilt": lost, "shard_size": shard_size,
                "helper_bytes": stats.get("helper_bytes", {}),
                "by_locality": stats.get("by_locality", {}),
                "predicted": predicted,
                "replans": stats.get("replans", 0),
                "dead_helpers": stats.get("dead_helpers", [])}
    finally:
        _netflow.reset(_flow_token)
        for fd in local_fds.values():
            try:
                os.close(fd)
            except OSError:
                pass


def write_dat_file(base: str, dat_size: int,
                   large_block: int = layout.LARGE_BLOCK_SIZE,
                   small_block: int = layout.SMALL_BLOCK_SIZE,
                   out_path: str | None = None,
                   data_shards: int | None = None) -> None:
    """Data shard files -> `<base>.dat` (row-major interleave copy).
    ``out_path`` redirects the output (the un-convert path decodes into
    a temp name and renames, so a crash mid-decode can never leave a
    half-written .dat a restart would mount as live data).  The stripe
    width k comes from the volume's .vif codec tag unless overridden."""
    if data_shards is None:
        from seaweedfs_tpu.ops import codecs as _codecs
        data_shards = _codecs.parse_tag((read_vif(base) or {}).get("codec")).k
    rows = layout.n_large_rows(dat_size, large_block, small_block,
                               data_shards=data_shards)
    ins = [open(base + layout.to_ext(i), "rb")
           for i in range(data_shards)]
    written = 0
    try:
        with open(out_path or (base + ".dat"), "wb") as dat:
            for r in range(rows):
                for j in range(data_shards):
                    ins[j].seek(r * large_block)
                    n = min(large_block, dat_size - written)
                    if n <= 0:
                        return
                    dat.write(ins[j].read(n))
                    written += n
            small_base = rows * large_block
            r = 0
            while written < dat_size:
                for j in range(data_shards):
                    ins[j].seek(small_base + r * small_block)
                    n = min(small_block, dat_size - written)
                    if n <= 0:
                        return
                    dat.write(ins[j].read(n))
                    written += n
                r += 1
    finally:
        for f in ins:
            f.close()


def write_sorted_ecx(idx_path: str, ecx_path: str | None = None) -> None:
    """.idx -> .ecx: 16-byte entries sorted by needle id ascending, ONE entry
    per id. The .idx is a log, so the last occurrence of an id (re-write or
    tombstone) is its truth — keeping duplicates would make the binary
    search land on the oldest entry and resurrect stale data."""
    ecx_path = ecx_path or idx_path[: -len(".idx")] + ".ecx"
    with open(idx_path, "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    latest: dict[int, tuple[int, int]] = {}
    for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
        latest[nid] = (off, size)
    with open(ecx_path, "wb") as out:
        for nid in sorted(latest):
            off, size = latest[nid]
            out.write(idxf.pack_entry(nid, off, size))


def write_idx_from_ecx(ecx_path: str, idx_path: str | None = None) -> None:
    """.ecx (+ replayed .ecj tombstones) -> .idx for decode-to-volume."""
    idx_path = idx_path or ecx_path[: -len(".ecx")] + ".idx"
    ecj_path = ecx_path[: -len(".ecx")] + ".ecj"
    deleted = read_ecj(ecj_path)
    with open(ecx_path, "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    with open(idx_path, "wb") as out:
        for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
            out.write(idxf.pack_entry(nid, off, size))
        for nid in deleted:
            out.write(idxf.pack_entry(nid, 0, t.TOMBSTONE_FILE_SIZE))


def write_vif(base: str, dat_size: int,
              version: int = t.CURRENT_VERSION,
              codec: str | None = None) -> None:
    import json
    doc: dict = {"version": version, "dat_file_size": dat_size}
    if codec:
        doc["codec"] = codec
    with open(base + ".vif", "w") as f:
        json.dump(doc, f)


def read_vif(base: str) -> dict | None:
    import json
    try:
        with open(base + ".vif") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def volume_codec_tag(base: str) -> str:
    """Codec tag of an EC volume from its .vif sidecar.  Volumes written
    before codec tags existed (or whose .vif is missing) are RS — the
    no-flag-day default."""
    from seaweedfs_tpu.ops import codecs as _codecs
    return _codecs.parse_tag((read_vif(base) or {}).get("codec")).tag


def find_dat_file_size(base: str, version: int = t.CURRENT_VERSION) -> int:
    """Recover the original .dat size: the encode-time size from the .vif
    sidecar when present, else the max end offset of live .ecx entries
    (reference: ec_decoder.go:48-70 — index-derived only, which misroutes
    when the volume's tail needles were all deleted)."""
    vif = read_vif(base)
    if vif and "dat_file_size" in vif:
        return int(vif["dat_file_size"])
    with open(base + ".ecx", "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    max_end = 0
    for off, size in zip(offs.tolist(), sizes.tolist()):
        if t.size_is_valid(size):
            end = t.from_offset_units(off) + t.actual_size(size, version)
            max_end = max(max_end, end)
    return max_end


def read_ecj(ecj_path: str) -> list[int]:
    """Deletion journal: 8-byte big-endian needle ids, appended per delete."""
    if not os.path.exists(ecj_path):
        return []
    with open(ecj_path, "rb") as f:
        data = f.read()
    n = len(data) // 8
    return [int.from_bytes(data[i * 8:(i + 1) * 8], "big") for i in range(n)]
