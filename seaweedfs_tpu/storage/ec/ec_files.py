"""EC shard file generation / rebuild / decode — the TPU data plane.

The reference streams 10x256KB buffers through a CPU SIMD encoder
(weed/storage/erasure_coding/ec_encoder.go:120-235). Here each batch is a
[10, B] uint8 matrix shipped to the device once and erasure-coded by the
bit-sliced MXU codec; B defaults to 16MB per shard (160MB per batch) so the
kernel runs deep in its throughput regime and host<->device transfers
amortise. Data shards are written straight from the host buffer — only
parity ([4, B]) comes back from the device.

Functions mirror the reference's capability surface:
  write_ec_files      <- WriteEcFiles (ec_encoder.go:56)
  rebuild_ec_files    <- RebuildEcFiles (ec_encoder.go:91)
  write_sorted_ecx    <- WriteSortedFileFromIdx (ec_encoder.go:27)
  write_dat_file      <- WriteDatFile (ec_decoder.go:153)
  write_idx_from_ecx  <- WriteIdxFileFromEcIndex (ec_decoder.go:18)
  find_dat_file_size  <- FindDatFileSize (ec_decoder.go:48)
"""

from __future__ import annotations

import functools
import os
import queue
import threading

import numpy as np

from seaweedfs_tpu.storage import idx as idxf
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import layout

DEFAULT_BATCH = 16 * 1024 * 1024  # bytes per shard per device round-trip


@functools.lru_cache(maxsize=8)
def _mesh_codec(k: int, m: int):
    from seaweedfs_tpu.models import rs
    from seaweedfs_tpu.parallel import mesh as pmesh
    return pmesh.ShardedRSEncoder(rs.get_code(k, m), pmesh.make_mesh())


def _get_codec(kind: str | None = None):
    """Select the EC codec backend: the `ec.codec` knob of this framework.

    auto (default): Pallas on TPU, native C++ AVX2 on CPU hosts, XLA
    bit-sliced otherwise.  Override with WEEDTPU_EC_CODEC=tpu|jax|cpp|numpy.
    """
    kind = kind or os.environ.get("WEEDTPU_EC_CODEC", "auto")
    k, m = layout.DATA_SHARDS, layout.PARITY_SHARDS
    if kind in ("cpp", "native"):
        from seaweedfs_tpu.ops import native_codec
        return native_codec.get_codec(k, m)
    if kind == "numpy":
        from seaweedfs_tpu.models import rs
        return rs.get_code(k, m)
    if kind == "mesh":
        # multi-chip column-parallel codec (parallel/mesh.py): stripes
        # shard over every attached device; memoized so the jitted
        # shard_maps compile once per (k, m)
        return _mesh_codec(k, m)
    if kind == "auto":
        import jax
        if jax.default_backend() == "tpu":
            from seaweedfs_tpu.ops import pallas_gf
            return pallas_gf.get_codec(k, m)
        from seaweedfs_tpu import native
        if native.available():
            from seaweedfs_tpu.ops import native_codec
            return native_codec.get_codec(k, m)
        from seaweedfs_tpu.ops import gfmat_jax
        return gfmat_jax.get_codec(k, m)
    if kind == "tpu":
        from seaweedfs_tpu.ops import pallas_gf
        return pallas_gf.get_codec(k, m)
    from seaweedfs_tpu.ops import gfmat_jax
    return gfmat_jax.get_codec(k, m)


def _reconstruct_batch(codec, shards: dict[int, np.ndarray],
                       wanted: list[int]) -> dict[int, np.ndarray]:
    """Rebuild `wanted` shard rows from >=k survivor rows (host bytes in/out)."""
    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    from seaweedfs_tpu.models.rs import RSCode
    if isinstance(codec, NativeRSCodec):
        return codec.reconstruct(shards, wanted=wanted)
    if isinstance(codec, RSCode):
        return codec.reconstruct_numpy(shards, wanted=wanted)
    import jax.numpy as jnp
    out = codec.reconstruct({i: jnp.asarray(v) for i, v in shards.items()},
                            wanted=wanted)
    return {i: np.asarray(v) for i, v in out.items()}


PIPELINE_DEPTH = 3  # host batch buffers in flight: read N+1 / encode N / drain N-1


def write_ec_files(base: str, dat_path: str | None = None,
                   large_block: int = layout.LARGE_BLOCK_SIZE,
                   small_block: int = layout.SMALL_BLOCK_SIZE,
                   batch_size: int = DEFAULT_BATCH,
                   progress=None, cancel=None) -> None:
    """Encode `<base>.dat` (or dat_path) into `<base>.ec00` .. `.ec13`,
    plus a `<base>.vif` volume-info sidecar recording the encode-time dat
    size and version (the reference's .vif, volume_info.go:16-40, as JSON):
    the layout was cut from the FILE size, which later lookups cannot
    reliably re-derive from the index once tail needles get deleted.

    `progress(bytes_done)` is called per batch and `cancel()` (returning
    True) aborts mid-stream — a 30GB encode must be observable and
    stoppable (the reference streams progress over its gRPC seam).

    The encode is a three-stage pipeline mirroring (and overlapping) the
    reference's streaming loop (ec_encoder.go:120-235): a reader thread
    fills host batch N+1 from the .dat while the main thread dispatches the
    device encode of batch N (JAX dispatch is async — the parity array is
    not materialised here) and a writer thread blocks on batch N-1's parity
    and drains all 14 shard files. Batch buffers come from a fixed pool of
    PIPELINE_DEPTH, so steady-state allocation is zero."""
    dat_path = dat_path or base + ".dat"
    dat_size = os.path.getsize(dat_path)
    codec = _get_codec()

    # shards build under temp names and commit by rename only when the
    # whole encode succeeds: a cancelled/crashed encode leaves any
    # previous valid shard set (and its .ecx/.vif) untouched
    tmp_paths = [base + layout.to_ext(i) + ".tmp"
                 for i in range(layout.TOTAL_SHARDS)]
    outputs = [open(p_, "wb") for p_ in tmp_paths]
    ok = False
    try:
        _encode_stream(codec, dat_path, dat_size, large_block, small_block,
                       batch_size, outputs, progress, cancel)
        ok = True
    finally:
        for f in outputs:
            f.close()
        if ok:
            write_vif(base, dat_size)
            for i, p_ in enumerate(tmp_paths):
                os.replace(p_, base + layout.to_ext(i))
        else:
            for p_ in tmp_paths:
                try:
                    os.remove(p_)
                except OSError:
                    pass


def _iter_units(dat_size: int, large_block: int, small_block: int,
                batch_size: int):
    """Yield (row_start, block, col, step) column-batch work units in shard
    file order: N full rows of 10 large blocks, then small-block rows."""
    processed = 0
    remaining = dat_size
    while remaining > large_block * layout.DATA_SHARDS:
        step = min(batch_size, large_block)
        assert large_block % step == 0, (large_block, step)
        for col in range(0, large_block, step):
            yield processed, large_block, col, step
        processed += large_block * layout.DATA_SHARDS
        remaining -= large_block * layout.DATA_SHARDS
    while remaining > 0:
        step = min(batch_size, small_block)
        assert small_block % step == 0, (small_block, step)
        for col in range(0, small_block, step):
            yield processed, small_block, col, step
        processed += small_block * layout.DATA_SHARDS
        remaining -= small_block * layout.DATA_SHARDS


def _dispatch_parity(codec, batch: np.ndarray):
    """Dispatch [k, B] -> [m, B] parity. JAX backends return the device
    array WITHOUT materialising it (dispatch is async; the writer's
    np.asarray is the sync point); host backends compute eagerly."""
    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    from seaweedfs_tpu.models.rs import RSCode
    if isinstance(codec, NativeRSCodec):
        return codec.encode_parity(batch)
    if isinstance(codec, RSCode):
        return codec.encode_numpy(batch)[codec.k:]
    import jax.numpy as jnp
    return codec.encode_parity(jnp.asarray(batch))


class EncodeCancelled(RuntimeError):
    pass


def _encode_stream(codec, dat_path: str, dat_size: int, large_block: int,
                   small_block: int, batch_size: int, outputs,
                   progress=None, cancel=None) -> None:
    """Reader -> dispatch -> writer pipeline over the work units.

    A batch buffer is only returned to the pool after the writer has both
    written its data rows and materialised its parity — until then the
    device may still be reading the (possibly zero-copy-aliased on CPU
    backends) host memory."""
    max_step = min(batch_size, max(large_block, small_block))
    pool: queue.Queue = queue.Queue()
    for _ in range(PIPELINE_DEPTH):
        pool.put(np.empty((layout.DATA_SHARDS, max_step), dtype=np.uint8))
    q_read: queue.Queue = queue.Queue(maxsize=PIPELINE_DEPTH)
    q_write: queue.Queue = queue.Queue(maxsize=PIPELINE_DEPTH)
    errors: list[BaseException] = []

    done = 0

    def reader() -> None:
        nonlocal done
        try:
            with open(dat_path, "rb") as dat:
                for row_start, block, col, step in _iter_units(
                        dat_size, large_block, small_block, batch_size):
                    if errors:  # writer failed: stop reading the volume
                        break
                    if cancel is not None and cancel():
                        raise EncodeCancelled("ec encode cancelled")
                    buf = pool.get()
                    batch = buf[:, :step]
                    for j in range(layout.DATA_SHARDS):
                        off = row_start + j * block + col
                        n = max(0, min(step, dat_size - off))
                        if n > 0:
                            dat.seek(off)
                            raw = dat.read(n)
                            batch[j, : len(raw)] = np.frombuffer(
                                raw, dtype=np.uint8)
                        if n < step:  # only the file's tail needs zero-fill
                            batch[j, max(n, 0):] = 0
                    q_read.put((buf, step))
                    done = min(dat_size,
                               done + step * layout.DATA_SHARDS)
                    if progress is not None:
                        progress(done)
        except BaseException as e:  # surfaced by the main thread
            errors.append(e)
        finally:
            q_read.put(None)

    def writer() -> None:
        failed = False
        while True:
            item = q_write.get()
            if item is None:
                return
            buf, step, parity = item
            if not failed:
                try:
                    pnp = np.asarray(parity)  # sync point for device encode
                    for j in range(layout.DATA_SHARDS):
                        outputs[j].write(buf[j, :step].tobytes())
                    for i in range(pnp.shape[0]):
                        outputs[layout.DATA_SHARDS + i].write(pnp[i].tobytes())
                except BaseException as e:
                    errors.append(e)
                    failed = True  # keep draining so nothing deadlocks
            pool.put(buf)

    t_r = threading.Thread(target=reader, name="ec-reader", daemon=True)
    t_w = threading.Thread(target=writer, name="ec-writer", daemon=True)
    t_r.start()
    t_w.start()
    try:
        while True:
            item = q_read.get()
            if item is None:
                break
            buf, step = item
            if errors:  # writer failed: stop dispatching, surface below
                pool.put(buf)
                continue
            parity = _dispatch_parity(codec, buf[:, :step])
            q_write.put((buf, step, parity))
    finally:
        q_write.put(None)
        t_w.join()
        while t_r.is_alive():  # unblock a reader stuck on a full q_read
            try:
                item = q_read.get(timeout=0.05)
            except queue.Empty:
                continue
            if item is not None:
                pool.put(item[0])  # keep the pool whole or the reader starves
        t_r.join()
    if errors:
        raise errors[0]


def rebuild_ec_files(base: str, batch_size: int = DEFAULT_BATCH) -> list[int]:
    """Regenerate whichever `.ecXX` files are missing from the >=10 present
    ones. Returns the rebuilt shard ids."""
    present = [i for i in range(layout.TOTAL_SHARDS)
               if os.path.exists(base + layout.to_ext(i))]
    missing = [i for i in range(layout.TOTAL_SHARDS) if i not in present]
    if not missing:
        return []
    if len(present) < layout.DATA_SHARDS:
        raise ValueError(
            f"need >= {layout.DATA_SHARDS} shards to rebuild, have {len(present)}")
    codec = _get_codec()
    use = present[: layout.DATA_SHARDS]
    shard_size = os.path.getsize(base + layout.to_ext(use[0]))

    ins = {i: open(base + layout.to_ext(i), "rb") for i in use}
    outs = {i: open(base + layout.to_ext(i), "wb") for i in missing}
    try:
        for off in range(0, shard_size, batch_size):
            n = min(batch_size, shard_size - off)
            stack = np.zeros((layout.DATA_SHARDS, n), dtype=np.uint8)
            for row, i in enumerate(use):
                ins[i].seek(off)
                stack[row] = np.frombuffer(ins[i].read(n), dtype=np.uint8)
            rebuilt = _reconstruct_batch(
                codec, {i: stack[row] for row, i in enumerate(use)}, missing)
            for i in missing:
                outs[i].write(np.asarray(rebuilt[i]).tobytes())
    finally:
        for f in ins.values():
            f.close()
        for f in outs.values():
            f.close()
    return missing


def write_dat_file(base: str, dat_size: int,
                   large_block: int = layout.LARGE_BLOCK_SIZE,
                   small_block: int = layout.SMALL_BLOCK_SIZE) -> None:
    """`.ec00`-`.ec09` -> `<base>.dat` (row-major interleave copy)."""
    rows = layout.n_large_rows(dat_size, large_block, small_block)
    ins = [open(base + layout.to_ext(i), "rb")
           for i in range(layout.DATA_SHARDS)]
    written = 0
    try:
        with open(base + ".dat", "wb") as dat:
            for r in range(rows):
                for j in range(layout.DATA_SHARDS):
                    ins[j].seek(r * large_block)
                    n = min(large_block, dat_size - written)
                    if n <= 0:
                        return
                    dat.write(ins[j].read(n))
                    written += n
            small_base = rows * large_block
            r = 0
            while written < dat_size:
                for j in range(layout.DATA_SHARDS):
                    ins[j].seek(small_base + r * small_block)
                    n = min(small_block, dat_size - written)
                    if n <= 0:
                        return
                    dat.write(ins[j].read(n))
                    written += n
                r += 1
    finally:
        for f in ins:
            f.close()


def write_sorted_ecx(idx_path: str, ecx_path: str | None = None) -> None:
    """.idx -> .ecx: 16-byte entries sorted by needle id ascending, ONE entry
    per id. The .idx is a log, so the last occurrence of an id (re-write or
    tombstone) is its truth — keeping duplicates would make the binary
    search land on the oldest entry and resurrect stale data."""
    ecx_path = ecx_path or idx_path[: -len(".idx")] + ".ecx"
    with open(idx_path, "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    latest: dict[int, tuple[int, int]] = {}
    for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
        latest[nid] = (off, size)
    with open(ecx_path, "wb") as out:
        for nid in sorted(latest):
            off, size = latest[nid]
            out.write(idxf.pack_entry(nid, off, size))


def write_idx_from_ecx(ecx_path: str, idx_path: str | None = None) -> None:
    """.ecx (+ replayed .ecj tombstones) -> .idx for decode-to-volume."""
    idx_path = idx_path or ecx_path[: -len(".ecx")] + ".idx"
    ecj_path = ecx_path[: -len(".ecx")] + ".ecj"
    deleted = read_ecj(ecj_path)
    with open(ecx_path, "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    with open(idx_path, "wb") as out:
        for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
            out.write(idxf.pack_entry(nid, off, size))
        for nid in deleted:
            out.write(idxf.pack_entry(nid, 0, t.TOMBSTONE_FILE_SIZE))


def write_vif(base: str, dat_size: int,
              version: int = t.CURRENT_VERSION) -> None:
    import json
    with open(base + ".vif", "w") as f:
        json.dump({"version": version, "dat_file_size": dat_size}, f)


def read_vif(base: str) -> dict | None:
    import json
    try:
        with open(base + ".vif") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def find_dat_file_size(base: str, version: int = t.CURRENT_VERSION) -> int:
    """Recover the original .dat size: the encode-time size from the .vif
    sidecar when present, else the max end offset of live .ecx entries
    (reference: ec_decoder.go:48-70 — index-derived only, which misroutes
    when the volume's tail needles were all deleted)."""
    vif = read_vif(base)
    if vif and "dat_file_size" in vif:
        return int(vif["dat_file_size"])
    with open(base + ".ecx", "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    max_end = 0
    for off, size in zip(offs.tolist(), sizes.tolist()):
        if t.size_is_valid(size):
            end = t.from_offset_units(off) + t.actual_size(size, version)
            max_end = max(max_end, end)
    return max_end


def read_ecj(ecj_path: str) -> list[int]:
    """Deletion journal: 8-byte big-endian needle ids, appended per delete."""
    if not os.path.exists(ecj_path):
        return []
    with open(ecj_path, "rb") as f:
        data = f.read()
    n = len(data) // 8
    return [int.from_bytes(data[i * 8:(i + 1) * 8], "big") for i in range(n)]
