"""EC shard file generation / rebuild / decode — the TPU data plane.

The reference streams 10x256KB buffers through a CPU SIMD encoder
(weed/storage/erasure_coding/ec_encoder.go:120-235). Here each batch is a
[10, B] uint8 matrix shipped to the device once and erasure-coded by the
bit-sliced MXU codec; B defaults to 16MB per shard (160MB per batch) so the
kernel runs deep in its throughput regime and host<->device transfers
amortise. Data shards are written straight from the host buffer — only
parity ([4, B]) comes back from the device.

Functions mirror the reference's capability surface:
  write_ec_files      <- WriteEcFiles (ec_encoder.go:56)
  rebuild_ec_files    <- RebuildEcFiles (ec_encoder.go:91)
  write_sorted_ecx    <- WriteSortedFileFromIdx (ec_encoder.go:27)
  write_dat_file      <- WriteDatFile (ec_decoder.go:153)
  write_idx_from_ecx  <- WriteIdxFileFromEcIndex (ec_decoder.go:18)
  find_dat_file_size  <- FindDatFileSize (ec_decoder.go:48)
"""

from __future__ import annotations

import functools
import os
import queue
import threading
import time

import numpy as np

from seaweedfs_tpu.storage import idx as idxf
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import layout

DEFAULT_BATCH = 16 * 1024 * 1024  # bytes per shard per device round-trip


@functools.lru_cache(maxsize=8)
def _mesh_codec(k: int, m: int):
    from seaweedfs_tpu.models import rs
    from seaweedfs_tpu.parallel import mesh as pmesh
    return pmesh.ShardedRSEncoder(rs.get_code(k, m), pmesh.make_mesh())


def _get_codec(kind: str | None = None):
    """Select the EC codec backend: the `ec.codec` knob of this framework.

    auto (default): Pallas on TPU, native C++ AVX2 on CPU hosts, XLA
    bit-sliced otherwise.  Override with WEEDTPU_EC_CODEC=tpu|jax|cpp|numpy.
    """
    kind = kind or os.environ.get("WEEDTPU_EC_CODEC", "auto")
    k, m = layout.DATA_SHARDS, layout.PARITY_SHARDS
    if kind in ("cpp", "native"):
        from seaweedfs_tpu.ops import native_codec
        return native_codec.get_codec(k, m)
    if kind == "numpy":
        from seaweedfs_tpu.models import rs
        return rs.get_code(k, m)
    if kind == "mesh":
        # multi-chip column-parallel codec (parallel/mesh.py): stripes
        # shard over every attached device; memoized so the jitted
        # shard_maps compile once per (k, m)
        return _mesh_codec(k, m)
    if kind == "auto":
        import jax
        if jax.default_backend() == "tpu":
            from seaweedfs_tpu.ops import pallas_gf
            return pallas_gf.get_codec(k, m)
        from seaweedfs_tpu import native
        if native.available():
            from seaweedfs_tpu.ops import native_codec
            return native_codec.get_codec(k, m)
        from seaweedfs_tpu.ops import gfmat_jax
        return gfmat_jax.get_codec(k, m)
    if kind == "tpu":
        from seaweedfs_tpu.ops import pallas_gf
        return pallas_gf.get_codec(k, m)
    from seaweedfs_tpu.ops import gfmat_jax
    return gfmat_jax.get_codec(k, m)


def _reconstruct_batch(codec, shards: dict[int, np.ndarray],
                       wanted: list[int]) -> dict[int, np.ndarray]:
    """Rebuild `wanted` shard rows from >=k survivor rows (host bytes in/out)."""
    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    from seaweedfs_tpu.models.rs import RSCode
    if isinstance(codec, NativeRSCodec):
        return codec.reconstruct(shards, wanted=wanted)
    if isinstance(codec, RSCode):
        return codec.reconstruct_numpy(shards, wanted=wanted)
    import jax.numpy as jnp
    out = codec.reconstruct({i: jnp.asarray(v) for i, v in shards.items()},
                            wanted=wanted)
    return {i: np.asarray(v) for i, v in out.items()}


PIPELINE_DEPTH = 3  # host batch buffers in flight: read N+1 / encode N / drain N-1


def write_ec_files(base: str, dat_path: str | None = None,
                   large_block: int = layout.LARGE_BLOCK_SIZE,
                   small_block: int = layout.SMALL_BLOCK_SIZE,
                   batch_size: int = DEFAULT_BATCH,
                   progress=None, cancel=None, stats=None) -> None:
    """Encode `<base>.dat` (or dat_path) into `<base>.ec00` .. `.ec13`,
    plus a `<base>.vif` volume-info sidecar recording the encode-time dat
    size and version (the reference's .vif, volume_info.go:16-40, as JSON):
    the layout was cut from the FILE size, which later lookups cannot
    reliably re-derive from the index once tail needles get deleted.

    `progress(bytes_done)` is called per batch with ACTUAL volume bytes
    consumed and `cancel()` (returning True) aborts mid-stream — a 30GB
    encode must be observable and stoppable (the reference streams progress
    over its gRPC seam).  `stats`, when a dict, receives per-stage wall-time
    attribution (read/encode/write seconds) for bench.py.

    Shards build under `.tmp` names and commit by rename only when the
    whole encode succeeds, so a cancelled/crashed encode leaves any
    previous valid shard set (and its .ecx/.vif) untouched.  Stale `.tmp`
    files from an earlier failed/cancelled attempt are recycled in place
    (opened without O_TRUNC): a retried encode overwrites the already-
    allocated pages instead of faulting in fresh ones, which matters both
    on hosts with lazy page allocation and for filesystems that would
    otherwise re-extend the files block by block."""
    dat_path = dat_path or base + ".dat"
    dat_size = os.path.getsize(dat_path)
    codec = _get_codec()

    tmp_paths = [base + layout.to_ext(i) + ".tmp"
                 for i in range(layout.TOTAL_SHARDS)]
    # O_RDWR without O_TRUNC: recycle pages of stale tmp files (see above);
    # _encode_stream ftruncates each fd to its exact final size.
    out_fds = [os.open(p_, os.O_RDWR | os.O_CREAT, 0o644) for p_ in tmp_paths]
    ok = False
    try:
        _encode_stream(codec, dat_path, dat_size, large_block, small_block,
                       batch_size, out_fds, progress, cancel, stats)
        ok = True
    finally:
        for fd in out_fds:
            os.close(fd)
        if ok:
            write_vif(base, dat_size)
            for i, p_ in enumerate(tmp_paths):
                os.replace(p_, base + layout.to_ext(i))
        else:
            for p_ in tmp_paths:
                try:
                    os.remove(p_)
                except OSError:
                    pass


def _iter_units(dat_size: int, large_block: int, small_block: int,
                batch_size: int):
    """Yield (row_start, block, col, step, shard_off) column-batch work
    units in shard file order: N full rows of 10 large blocks, then
    small-block rows.  shard_off is the unit's byte offset inside every
    shard file (all 14 shard files are parallel arrays of blocks)."""
    processed = 0
    remaining = dat_size
    shard_base = 0
    while remaining > large_block * layout.DATA_SHARDS:
        step = min(batch_size, large_block)
        assert large_block % step == 0, (large_block, step)
        for col in range(0, large_block, step):
            yield processed, large_block, col, step, shard_base + col
        processed += large_block * layout.DATA_SHARDS
        remaining -= large_block * layout.DATA_SHARDS
        shard_base += large_block
    while remaining > 0:
        step = min(batch_size, small_block)
        assert small_block % step == 0, (small_block, step)
        for col in range(0, small_block, step):
            yield processed, small_block, col, step, shard_base + col
        processed += small_block * layout.DATA_SHARDS
        remaining -= small_block * layout.DATA_SHARDS
        shard_base += small_block


def _dispatch_parity(codec, batch: np.ndarray):
    """Dispatch [k, B] -> [m, B] parity. JAX backends return the device
    array WITHOUT materialising it (dispatch is async; the writer's
    np.asarray is the sync point); host backends compute eagerly."""
    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    from seaweedfs_tpu.models.rs import RSCode
    if isinstance(codec, NativeRSCodec):
        return codec.encode_parity(batch)
    if isinstance(codec, RSCode):
        return codec.encode_numpy(batch)[codec.k:]
    import jax.numpy as jnp
    return codec.encode_parity(jnp.asarray(batch))


class EncodeCancelled(RuntimeError):
    pass


_CFR_OK = True  # copy_file_range support, latched off on first failure


def _copy_range(src_fd: int, dst_fd: int, src_off: int, dst_off: int,
                count: int, src_view: np.ndarray | None = None) -> None:
    """In-kernel copy of a .dat slice into a shard file (no user-space
    transit), falling back to pwrite from the mmap view where
    copy_file_range is unsupported (non-regular files, cross-fs, old
    kernels)."""
    global _CFR_OK
    if _CFR_OK and hasattr(os, "copy_file_range"):
        so, do, left = src_off, dst_off, count
        try:
            while left > 0:
                n = os.copy_file_range(src_fd, dst_fd, left, so, do)
                if n <= 0:
                    raise OSError("copy_file_range returned 0")
                so += n
                do += n
                left -= n
            return
        except OSError:
            _CFR_OK = False
            src_off, dst_off, count = so, do, left  # resume where CFR died
    if count > 0 and src_view is not None:
        _pwrite_all(dst_fd, src_view[src_off:src_off + count], dst_off)


class _Timer:
    """Accumulates wall seconds into stats[key]; no-op when stats is None."""

    def __init__(self, stats, key):
        self.stats, self.key = stats, key

    def __enter__(self):
        if self.stats is not None:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.stats is not None:
            self.stats[self.key] = self.stats.get(self.key, 0.0) + \
                (time.perf_counter() - self.t0)
        return False


def _finalize_shards(out_fds, highwater, shard_size: int) -> None:
    """Cut every shard file to exactly shard_size: truncate to the written
    high-water mark first (drops stale bytes of a recycled tmp file), then
    extend — the zero suffix becomes a filesystem hole, so fully-padded
    regions (e.g. a 40MB volume in a 16MB-block layout) cost no write I/O
    at all."""
    for fd, hw in zip(out_fds, highwater):
        os.ftruncate(fd, min(hw, shard_size))
        if hw < shard_size:
            os.ftruncate(fd, shard_size)


def _encode_stream(codec, dat_path: str, dat_size: int, large_block: int,
                   small_block: int, batch_size: int, out_fds,
                   progress=None, cancel=None, stats=None) -> None:
    """Stream the .dat through the codec into the 14 shard fds.

    Two strategies behind one surface:
      - host codecs (native AVX2 / numpy): a serial zero-copy loop — the
        kernel reads straight from an mmap of the .dat via per-row
        pointers, data shards move by in-kernel copy_file_range, parity
        lands in a pooled buffer and is pwritten.  On a storage host the
        encode is bandwidth-bound; removing every staging copy beats any
        amount of thread pipelining (and a 1-core host has nothing to
        overlap anyway).
      - device codecs (Pallas/XLA/mesh): the 3-stage reader -> dispatch ->
        writer pipeline, since JAX dispatch is async and the device round-
        trip genuinely overlaps host I/O.  Reads stage from the mmap into
        pooled buffers (no per-batch allocation); only parity rides the
        device — data shards still copy_file_range straight to disk.

    Rows wholly beyond the .dat are never read, encoded, or written: the
    parity of an all-zero row region is zero, so those regions become
    holes (_finalize_shards).  Partially-covered units encode only the
    rows that carry data, against a column-sliced parity matrix."""
    if stats is not None:
        stats["bytes"] = dat_size
    shard_size = layout.shard_file_size(dat_size, large_block, small_block)
    k = layout.DATA_SHARDS
    highwater = [0] * layout.TOTAL_SHARDS
    if dat_size == 0:
        _finalize_shards(out_fds, highwater, shard_size)
        return

    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    native_host = isinstance(codec, NativeRSCodec)
    if stats is not None:
        stats["mode"] = "host-serial" if native_host else "pipelined"

    import mmap as mmap_mod
    with open(dat_path, "rb") as datf:
        dat_fd = datf.fileno()
        mm = mmap_mod.mmap(dat_fd, 0, prot=mmap_mod.PROT_READ)
        try:
            mm.madvise(mmap_mod.MADV_SEQUENTIAL)
        except (AttributeError, OSError):
            pass
        dat_view = np.frombuffer(mm, dtype=np.uint8)
        try:
            if native_host:
                _encode_serial_host(codec, dat_fd, dat_view, dat_size,
                                    large_block, small_block, batch_size,
                                    out_fds, highwater, progress, cancel,
                                    stats)
            else:
                _encode_pipelined(codec, dat_fd, dat_view, dat_size,
                                  large_block, small_block, batch_size,
                                  out_fds, highwater, progress, cancel,
                                  stats)
        finally:
            del dat_view
            try:
                mm.close()
            except BufferError:
                # an in-flight exception's traceback frames still hold
                # views into the map; GC reaps the mapping with them
                pass
    _finalize_shards(out_fds, highwater, shard_size)


def _unit_coverage(dat_size: int, row_start: int, block: int, col: int,
                   step: int) -> tuple[int, int]:
    """-> (nz, tail): nz = number of leading rows carrying any data in this
    unit, tail = valid bytes in row nz-1 (== step when that row is full)."""
    nz = 0
    tail = step
    for j in range(layout.DATA_SHARDS):
        off = row_start + j * block + col
        n = min(step, dat_size - off)
        if n <= 0:
            break
        nz = j + 1
        tail = n
    return nz, tail


def _pwrite_all(fd: int, view, off: int) -> None:
    """pwrite may write short (RLIMIT_FSIZE edge, fs under pressure); a
    silent short write would commit a shard with a zero gap."""
    mv = memoryview(view)
    while len(mv) > 0:
        n = os.pwrite(fd, mv, off)
        if n <= 0:
            raise OSError("pwrite returned 0")
        mv = mv[n:]
        off += n


def _encode_serial_host(codec, dat_fd: int, dat_view: np.ndarray,
                        dat_size: int, large_block: int, small_block: int,
                        batch_size: int, out_fds, highwater,
                        progress=None, cancel=None, stats=None) -> None:
    from seaweedfs_tpu import native
    k, m = layout.DATA_SHARDS, layout.PARITY_SHARDS
    max_step = min(batch_size, max(large_block, small_block))
    pbuf = np.empty((m, max_step), dtype=np.uint8)
    tailbuf = np.zeros(max_step, dtype=np.uint8)
    done = 0
    for row_start, block, col, step, shard_off in _iter_units(
            dat_size, large_block, small_block, batch_size):
        if cancel is not None and cancel():
            raise EncodeCancelled("ec encode cancelled")
        nz, tail = _unit_coverage(dat_size, row_start, block, col, step)
        if nz == 0:
            continue
        # data shards: in-kernel copy, no user-space transit
        with _Timer(stats, "write_data_s"):
            for j in range(nz):
                off = row_start + j * block + col
                n = step if j < nz - 1 else tail
                _copy_range(dat_fd, out_fds[j], off, shard_off, n,
                            src_view=dat_view)
                highwater[j] = max(highwater[j], shard_off + n)
        # parity: ptr-matmul straight off the mmap (partial tail row is
        # staged into a pooled zeroed buffer first)
        with _Timer(stats, "encode_s"):
            rows = [dat_view[row_start + j * block + col:
                             row_start + j * block + col + step]
                    for j in range(nz)]
            if tail < step:
                tailbuf[:tail] = rows[nz - 1][:tail]
                tailbuf[tail:step] = 0
                rows[nz - 1] = tailbuf
            mat = codec.code.parity_matrix if nz == k else \
                np.ascontiguousarray(codec.code.parity_matrix[:, :nz])
            native.gf_matmul_ptrs(mat, rows, list(pbuf), step)
        with _Timer(stats, "write_parity_s"):
            for i in range(m):
                _pwrite_all(out_fds[k + i], pbuf[i, :step], shard_off)
                highwater[k + i] = max(highwater[k + i], shard_off + step)
        done += (nz - 1) * step + tail
        if progress is not None:
            progress(done)


def _encode_pipelined(codec, dat_fd: int, dat_view: np.ndarray,
                      dat_size: int, large_block: int, small_block: int,
                      batch_size: int, out_fds, highwater,
                      progress=None, cancel=None, stats=None) -> None:
    """Reader -> dispatch -> writer pipeline for async device codecs.

    A batch buffer is only returned to the pool after the writer has
    materialised its parity — until then the device may still be reading
    the (possibly zero-copy-aliased on CPU backends) host memory."""
    k, m = layout.DATA_SHARDS, layout.PARITY_SHARDS
    max_step = min(batch_size, max(large_block, small_block))
    pool: queue.Queue = queue.Queue()
    for _ in range(PIPELINE_DEPTH):
        pool.put(np.empty((k, max_step), dtype=np.uint8))
    q_read: queue.Queue = queue.Queue(maxsize=PIPELINE_DEPTH)
    q_write: queue.Queue = queue.Queue(maxsize=PIPELINE_DEPTH)
    errors: list[BaseException] = []
    done = 0

    def reader() -> None:
        nonlocal done
        try:
            for row_start, block, col, step, shard_off in _iter_units(
                    dat_size, large_block, small_block, batch_size):
                if errors:  # writer failed: stop reading the volume
                    break
                if cancel is not None and cancel():
                    raise EncodeCancelled("ec encode cancelled")
                nz, tail = _unit_coverage(dat_size, row_start, block, col,
                                          step)
                if nz == 0:
                    continue
                # data shards never round-trip the device: in-kernel copy
                with _Timer(stats, "write_data_s"):
                    for j in range(nz):
                        off = row_start + j * block + col
                        n = step if j < nz - 1 else tail
                        _copy_range(dat_fd, out_fds[j], off, shard_off, n,
                                    src_view=dat_view)
                        highwater[j] = max(highwater[j], shard_off + n)
                with _Timer(stats, "read_s"):
                    buf = pool.get()
                    batch = buf[:, :step]
                    for j in range(k):
                        off = row_start + j * block + col
                        n = max(0, min(step, dat_size - off))
                        if n > 0:
                            np.copyto(batch[j, :n],
                                      dat_view[off:off + n])
                        if n < step:
                            batch[j, max(n, 0):] = 0
                q_read.put((buf, step, shard_off))
                done += (nz - 1) * step + tail
                if progress is not None:
                    progress(done)
        except BaseException as e:  # surfaced by the main thread
            errors.append(e)
        finally:
            q_read.put(None)

    def writer() -> None:
        failed = False
        while True:
            item = q_write.get()
            if item is None:
                return
            buf, step, shard_off, parity = item
            if not failed:
                try:
                    with _Timer(stats, "write_parity_s"):
                        pnp = np.asarray(parity)  # sync for device encode
                        for i in range(pnp.shape[0]):
                            _pwrite_all(out_fds[k + i],
                                        np.ascontiguousarray(pnp[i, :step]),
                                        shard_off)
                            highwater[k + i] = max(highwater[k + i],
                                                   shard_off + step)
                except BaseException as e:
                    errors.append(e)
                    failed = True  # keep draining so nothing deadlocks
            pool.put(buf)

    t_r = threading.Thread(target=reader, name="ec-reader", daemon=True)
    t_w = threading.Thread(target=writer, name="ec-writer", daemon=True)
    t_r.start()
    t_w.start()
    try:
        while True:
            item = q_read.get()
            if item is None:
                break
            buf, step, shard_off = item
            if errors:  # writer failed: stop dispatching, surface below
                pool.put(buf)
                continue
            with _Timer(stats, "encode_s"):
                parity = _dispatch_parity(codec, buf[:, :step])
            q_write.put((buf, step, shard_off, parity))
    finally:
        q_write.put(None)
        t_w.join()
        while t_r.is_alive():  # unblock a reader stuck on a full q_read
            try:
                item = q_read.get(timeout=0.05)
            except queue.Empty:
                continue
            if item is not None:
                pool.put(item[0])  # keep the pool whole or the reader starves
        t_r.join()
    if errors:
        raise errors[0]


def rebuild_ec_files(base: str, batch_size: int = DEFAULT_BATCH,
                     progress=None, cancel=None, stats=None) -> list[int]:
    """Regenerate whichever `.ecXX` files are missing from the >=10 present
    ones. Returns the rebuilt shard ids.

    Same zero-copy discipline as the encode path (and the same observability:
    `progress(bytes_done)` per batch over survivor bytes, `cancel()` aborts,
    `stats` gets per-stage seconds): survivor shards are mmap'd and fed to
    the native decode matmul by row pointer, rebuilt shards land in a pooled
    buffer and are pwritten into recycled `.tmp` inodes, committed by rename
    only on success (reference: RebuildEcFiles, ec_encoder.go:237-291)."""
    present = [i for i in range(layout.TOTAL_SHARDS)
               if os.path.exists(base + layout.to_ext(i))]
    missing = [i for i in range(layout.TOTAL_SHARDS) if i not in present]
    if not missing:
        return []
    if len(present) < layout.DATA_SHARDS:
        raise ValueError(
            f"need >= {layout.DATA_SHARDS} shards to rebuild, have {len(present)}")
    codec = _get_codec()
    use = present[: layout.DATA_SHARDS]
    shard_size = os.path.getsize(base + layout.to_ext(use[0]))
    if stats is not None:
        stats["bytes"] = shard_size * layout.DATA_SHARDS

    from seaweedfs_tpu.ops.native_codec import NativeRSCodec
    native_host = isinstance(codec, NativeRSCodec)
    if stats is not None:
        stats["mode"] = "host-serial" if native_host else "staged"
    if native_host:
        from seaweedfs_tpu import native
        dec_mat = codec.code.decode_matrix(list(use), list(missing))

    import mmap as mmap_mod
    ins = {i: open(base + layout.to_ext(i), "rb") for i in use}
    maps = {}
    views = {}
    tmp_paths = {i: base + layout.to_ext(i) + ".tmp" for i in missing}
    out_fds = {i: os.open(p_, os.O_RDWR | os.O_CREAT, 0o644)
               for i, p_ in tmp_paths.items()}
    obuf = None
    stage = None
    ok = False
    try:
        if native_host:
            obuf = np.empty(
                (len(missing), min(batch_size, max(shard_size, 1))),
                dtype=np.uint8)
        for i, f in ins.items():
            if shard_size:
                mm = mmap_mod.mmap(f.fileno(), 0, prot=mmap_mod.PROT_READ)
                try:
                    mm.madvise(mmap_mod.MADV_SEQUENTIAL)
                except (AttributeError, OSError):
                    pass
                maps[i] = mm
                views[i] = np.frombuffer(mm, dtype=np.uint8)
        done = 0
        for off in range(0, shard_size, batch_size):
            if cancel is not None and cancel():
                raise EncodeCancelled("ec rebuild cancelled")
            n = min(batch_size, shard_size - off)
            with _Timer(stats, "reconstruct_s"):
                if native_host:
                    rows = [views[i][off:off + n] for i in use]
                    outs = [obuf[r, :n] for r in range(len(missing))]
                    native.gf_matmul_ptrs(dec_mat, rows, outs, n)
                    rebuilt = {i: obuf[r, :n]
                               for r, i in enumerate(missing)}
                else:
                    if stage is None:
                        stage = np.empty((layout.DATA_SHARDS,
                                          min(batch_size, shard_size)),
                                         dtype=np.uint8)
                    for row, i in enumerate(use):
                        np.copyto(stage[row, :n], views[i][off:off + n])
                    rebuilt = _reconstruct_batch(
                        codec,
                        {i: stage[row, :n] for row, i in enumerate(use)},
                        missing)
            with _Timer(stats, "write_s"):
                for i in missing:
                    _pwrite_all(out_fds[i],
                                np.ascontiguousarray(rebuilt[i]), off)
            done += n * layout.DATA_SHARDS
            if progress is not None:
                progress(done)
        for fd in out_fds.values():
            os.ftruncate(fd, shard_size)
        ok = True
    finally:
        for f in ins.values():
            f.close()
        for i in list(views):
            del views[i]
        for mm in maps.values():
            try:
                mm.close()
            except BufferError:
                pass
        for fd in out_fds.values():
            os.close(fd)
        if ok:
            for i, p_ in tmp_paths.items():
                os.replace(p_, base + layout.to_ext(i))
        else:
            for p_ in tmp_paths.values():
                try:
                    os.remove(p_)
                except OSError:
                    pass
    return missing


def write_dat_file(base: str, dat_size: int,
                   large_block: int = layout.LARGE_BLOCK_SIZE,
                   small_block: int = layout.SMALL_BLOCK_SIZE) -> None:
    """`.ec00`-`.ec09` -> `<base>.dat` (row-major interleave copy)."""
    rows = layout.n_large_rows(dat_size, large_block, small_block)
    ins = [open(base + layout.to_ext(i), "rb")
           for i in range(layout.DATA_SHARDS)]
    written = 0
    try:
        with open(base + ".dat", "wb") as dat:
            for r in range(rows):
                for j in range(layout.DATA_SHARDS):
                    ins[j].seek(r * large_block)
                    n = min(large_block, dat_size - written)
                    if n <= 0:
                        return
                    dat.write(ins[j].read(n))
                    written += n
            small_base = rows * large_block
            r = 0
            while written < dat_size:
                for j in range(layout.DATA_SHARDS):
                    ins[j].seek(small_base + r * small_block)
                    n = min(small_block, dat_size - written)
                    if n <= 0:
                        return
                    dat.write(ins[j].read(n))
                    written += n
                r += 1
    finally:
        for f in ins:
            f.close()


def write_sorted_ecx(idx_path: str, ecx_path: str | None = None) -> None:
    """.idx -> .ecx: 16-byte entries sorted by needle id ascending, ONE entry
    per id. The .idx is a log, so the last occurrence of an id (re-write or
    tombstone) is its truth — keeping duplicates would make the binary
    search land on the oldest entry and resurrect stale data."""
    ecx_path = ecx_path or idx_path[: -len(".idx")] + ".ecx"
    with open(idx_path, "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    latest: dict[int, tuple[int, int]] = {}
    for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
        latest[nid] = (off, size)
    with open(ecx_path, "wb") as out:
        for nid in sorted(latest):
            off, size = latest[nid]
            out.write(idxf.pack_entry(nid, off, size))


def write_idx_from_ecx(ecx_path: str, idx_path: str | None = None) -> None:
    """.ecx (+ replayed .ecj tombstones) -> .idx for decode-to-volume."""
    idx_path = idx_path or ecx_path[: -len(".ecx")] + ".idx"
    ecj_path = ecx_path[: -len(".ecx")] + ".ecj"
    deleted = read_ecj(ecj_path)
    with open(ecx_path, "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    with open(idx_path, "wb") as out:
        for nid, off, size in zip(ids.tolist(), offs.tolist(), sizes.tolist()):
            out.write(idxf.pack_entry(nid, off, size))
        for nid in deleted:
            out.write(idxf.pack_entry(nid, 0, t.TOMBSTONE_FILE_SIZE))


def write_vif(base: str, dat_size: int,
              version: int = t.CURRENT_VERSION) -> None:
    import json
    with open(base + ".vif", "w") as f:
        json.dump({"version": version, "dat_file_size": dat_size}, f)


def read_vif(base: str) -> dict | None:
    import json
    try:
        with open(base + ".vif") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def find_dat_file_size(base: str, version: int = t.CURRENT_VERSION) -> int:
    """Recover the original .dat size: the encode-time size from the .vif
    sidecar when present, else the max end offset of live .ecx entries
    (reference: ec_decoder.go:48-70 — index-derived only, which misroutes
    when the volume's tail needles were all deleted)."""
    vif = read_vif(base)
    if vif and "dat_file_size" in vif:
        return int(vif["dat_file_size"])
    with open(base + ".ecx", "rb") as f:
        data = f.read()
    ids, offs, sizes = idxf.read_columns(data)
    max_end = 0
    for off, size in zip(offs.tolist(), sizes.tolist()):
        if t.size_is_valid(size):
            end = t.from_offset_units(off) + t.actual_size(size, version)
            max_end = max(max_end, end)
    return max_end


def read_ecj(ecj_path: str) -> list[int]:
    """Deletion journal: 8-byte big-endian needle ids, appended per delete."""
    if not os.path.exists(ecj_path):
        return []
    with open(ecj_path, "rb") as f:
        data = f.read()
    n = len(data) // 8
    return [int.from_bytes(data[i * 8:(i + 1) * 8], "big") for i in range(n)]
