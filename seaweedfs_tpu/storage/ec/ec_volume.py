"""EC volume runtime: serve needle reads/deletes from shard files.

Mirrors the reference runtime (weed/storage/erasure_coding/ec_volume.go,
ec_shard.go, store_ec.go) with one structural change: the .ecx index is
loaded as numpy columns and binary-searched in memory (searchsorted) rather
than re-reading the file per lookup — the file stays the source of truth
and deletes are written through.

Reads go through a pluggable `shard_reader(shard_id, offset, size)` so the
volume-server layer can back missing local shards with remote RPCs; when a
shard can't be read at all, the interval is reconstructed on-device from any
k readable shards (reference: store_ec.go:339-393
recoverOneRemoteEcShardInterval -> enc.ReconstructData).
"""

from __future__ import annotations

import os
from typing import Callable

import numpy as np

from seaweedfs_tpu.storage import idx as idxf
from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import ec_files, layout

ShardReader = Callable[[int, int, int], "bytes | None"]


class EcVolume:
    def __init__(self, base: str,
                 large_block: int = layout.LARGE_BLOCK_SIZE,
                 small_block: int = layout.SMALL_BLOCK_SIZE,
                 version: int = t.CURRENT_VERSION):
        self.base = base
        self.large_block = large_block
        self.small_block = small_block
        vif = ec_files.read_vif(base)
        self.version = vif.get("version", version) if vif else version

        # replay any crash-left journal into the .ecx, as the reference
        # does at mount (RebuildEcxFile, ec_volume_delete.go:51-98)
        self._replay_ecj()

        self._ecx = open(base + ".ecx", "r+b")
        data = self._ecx.read()
        self.ids, self.offs, self.sizes = idxf.read_columns(data)

        self.shards: dict[int, object] = {}
        for i in range(layout.TOTAL_SHARDS):
            p = base + layout.to_ext(i)
            if os.path.exists(p):
                self.shards[i] = open(p, "rb")
        if self.shards:
            any_id = next(iter(self.shards))
            self.shard_size = os.path.getsize(base + layout.to_ext(any_id))
        else:
            self.shard_size = 0
        self.dat_size = ec_files.find_dat_file_size(base, self.version)

    # -- index ---------------------------------------------------------

    def _replay_ecj(self) -> None:
        ecj = self.base + ".ecj"
        deleted = ec_files.read_ecj(ecj)
        if not deleted:
            return
        with open(self.base + ".ecx", "r+b") as f:
            data = f.read()
            ids, _, _ = idxf.read_columns(data)
            for nid in deleted:
                pos = int(np.searchsorted(ids, nid))
                if pos < len(ids) and ids[pos] == nid:
                    f.seek(pos * 16 + 12)
                    f.write(t.TOMBSTONE_FILE_SIZE.to_bytes(4, "big", signed=True))
        os.remove(ecj)

    def find_needle(self, needle_id: int) -> tuple[int, int]:
        """-> (dat_offset_bytes, size); raises KeyError if absent/deleted."""
        pos = int(np.searchsorted(self.ids, needle_id))
        if pos >= len(self.ids) or self.ids[pos] != needle_id:
            raise KeyError(f"needle {needle_id:x} not in ec volume")
        size = int(self.sizes[pos])
        if not t.size_is_valid(size):
            raise KeyError(f"needle {needle_id:x} deleted")
        return t.from_offset_units(int(self.offs[pos])), size

    def delete_needle(self, needle_id: int) -> None:
        """Tombstone in .ecx (in place) + append to the .ecj journal."""
        pos = int(np.searchsorted(self.ids, needle_id))
        if pos >= len(self.ids) or self.ids[pos] != needle_id:
            return
        self.sizes[pos] = t.TOMBSTONE_FILE_SIZE
        self._ecx.seek(pos * 16 + 12)
        self._ecx.write(t.TOMBSTONE_FILE_SIZE.to_bytes(4, "big", signed=True))
        self._ecx.flush()
        with open(self.base + ".ecj", "ab") as j:
            j.write(needle_id.to_bytes(8, "big"))

    # -- reads ----------------------------------------------------------

    def _read_local(self, shard_id: int, offset: int, size: int) -> bytes | None:
        f = self.shards.get(shard_id)
        if f is None:
            return None
        f.seek(offset)
        return f.read(size)

    def read_interval(self, shard_id: int, offset: int, size: int,
                      shard_reader: ShardReader | None = None) -> bytes:
        data = self._read_local(shard_id, offset, size)
        if data is not None and len(data) == size:
            return data
        if shard_reader is not None:
            data = shard_reader(shard_id, offset, size)
            if data is not None and len(data) == size:
                return data
        return self._reconstruct_interval(shard_id, offset, size, shard_reader)

    def _reconstruct_interval(self, shard_id: int, offset: int, size: int,
                              shard_reader: ShardReader | None) -> bytes:
        """Online repair: rebuild this shard's byte range from any k
        others.  Local shards are gathered first (cheap); the remaining
        remote reads fan out in PARALLEL like the reference's
        recoverOneRemoteEcShardInterval (store_ec.go:349-382) — a serial
        walk would stack per-peer timeouts onto one degraded GET."""
        codec = ec_files._get_codec()
        got: dict[int, np.ndarray] = {}
        missing_remote: list[int] = []
        for i in range(layout.TOTAL_SHARDS):
            if i == shard_id:
                continue
            if len(got) >= layout.DATA_SHARDS:
                break  # enough local shards: no wasted disk reads
            data = self._read_local(i, offset, size)
            if data is not None and len(data) == size:
                got[i] = np.frombuffer(data, dtype=np.uint8)
            else:
                missing_remote.append(i)
        if len(got) < layout.DATA_SHARDS and shard_reader is not None:
            need = layout.DATA_SHARDS - len(got)
            from concurrent.futures import (ThreadPoolExecutor,
                                            as_completed)
            pool = ThreadPoolExecutor(
                max_workers=min(8, len(missing_remote) or 1))
            try:
                futs = {pool.submit(shard_reader, i, offset, size): i
                        for i in missing_remote}
                for fut in as_completed(futs):
                    data = None if fut.exception() else fut.result()
                    if data is not None and len(data) == size:
                        got[futs[fut]] = np.frombuffer(data, dtype=np.uint8)
                        need -= 1
                        if need <= 0:
                            break
            finally:
                # do NOT wait for stragglers: one blackholed peer must not
                # stall the degraded GET past the k fast responders
                pool.shutdown(wait=False, cancel_futures=True)
        if len(got) < layout.DATA_SHARDS:
            raise IOError(
                f"ec volume {self.base}: only {len(got)} shards readable, "
                f"need {layout.DATA_SHARDS} to reconstruct shard {shard_id}")
        out = ec_files._reconstruct_batch(codec, got, [shard_id])
        return np.asarray(out[shard_id]).tobytes()

    def read_needle(self, needle_id: int,
                    shard_reader: ShardReader | None = None) -> ndl.Needle:
        """Full needle read: locate -> per-interval shard reads -> parse."""
        dat_offset, size = self.find_needle(needle_id)
        length = t.actual_size(size, self.version)
        intervals = layout.locate_data(
            self.large_block, self.small_block, self.dat_size,
            dat_offset, length)
        parts = []
        for iv in intervals:
            sid, off = iv.to_shard_id_and_offset(self.large_block, self.small_block)
            parts.append(self.read_interval(sid, off, iv.size, shard_reader))
        record = b"".join(parts)
        n = ndl.Needle.from_record(record, self.version)
        if n.id != needle_id:
            raise IOError(f"ec read returned needle {n.id:x}, wanted {needle_id:x}")
        return n

    def shard_ids(self) -> list[int]:
        return sorted(self.shards)

    def close(self) -> None:
        self._ecx.close()
        for f in self.shards.values():
            f.close()
