"""EC volume runtime: serve needle reads/deletes from shard files.

Mirrors the reference runtime (weed/storage/erasure_coding/ec_volume.go,
ec_shard.go, store_ec.go) with one structural change: the .ecx index is
loaded as numpy columns and binary-searched in memory (searchsorted) rather
than re-reading the file per lookup — the file stays the source of truth
and deletes are written through.

Reads go through a pluggable `shard_reader(shard_id, offset, size)` so the
volume-server layer can back missing local shards with remote RPCs; when a
shard can't be read at all, the interval is reconstructed on-device from any
k readable shards (reference: store_ec.go:339-393
recoverOneRemoteEcShardInterval -> enc.ReconstructData).

The needle read path is a batched engine rather than the reference's
per-interval loop: all intervals are planned up front, adjacent ranges of
the same shard file coalesce into single reads, every local+remote shard
read fans out through one long-lived executor, and ALL missing intervals
reconstruct in ONE codec dispatch (the survivor slices for every failed
range stack column-wise into a single GF(2^8) matmul — RS decodes
byte-position by byte-position, so concatenated ranges rebuild exactly as
they would one by one).  A small LRU keeps recently reconstructed ranges so
repeated degraded GETs of a hot needle cost no shard I/O and no matmul.
`WEEDTPU_EC_READ=serial` restores the per-interval loop (bench baseline).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import (ThreadPoolExecutor,
                                TimeoutError as _FutTimeout,
                                as_completed, wait as _futures_wait)
from typing import Callable

import numpy as np

from seaweedfs_tpu.stats import heat, trace
from seaweedfs_tpu.stats import pipeline as _pipeline
from seaweedfs_tpu.utils import resilience
from seaweedfs_tpu.storage import idx as idxf
from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.ec import ec_files, layout

ShardReader = Callable[[int, int, int], "bytes | None"]

# bytes of reconstructed ranges kept per EcVolume so hot degraded needles
# don't re-reconstruct (0 disables)
RECONSTRUCT_CACHE_BYTES = int(os.environ.get(
    "WEEDTPU_EC_RECONSTRUCT_CACHE", str(8 * 1024 * 1024)))

# one long-lived pool for LOCAL degraded-read shard preads (the old engine
# built a fresh ThreadPoolExecutor per interval — pool construction cost
# per degraded GET, times one per interval).  Remote shard fetches must
# NOT ride this pool: a blackholed peer parks its reader thread for the
# full RPC timeout, and a handful of those would starve every degraded
# GET's fast local preads behind them — remote fan-outs get a throwaway
# per-call pool instead (abandoned stragglers die with it).
_READ_POOL: ThreadPoolExecutor | None = None
_READ_POOL_LOCK = threading.Lock()


def _read_pool() -> ThreadPoolExecutor:
    global _READ_POOL
    pool = _READ_POOL
    if pool is None:
        with _READ_POOL_LOCK:
            pool = _READ_POOL
            if pool is None:
                workers = int(os.environ.get("WEEDTPU_EC_READ_WORKERS",
                                             "16"))
                pool = _READ_POOL = ThreadPoolExecutor(
                    max_workers=max(1, workers),
                    thread_name_prefix="ec-read")
    return pool


class EcVolume:
    def __init__(self, base: str,
                 large_block: int = layout.LARGE_BLOCK_SIZE,
                 small_block: int = layout.SMALL_BLOCK_SIZE,
                 version: int = t.CURRENT_VERSION):
        self.base = base
        # the volume id this EC volume serves — the workload heat
        # tracker's key for degraded reads.  Base names are "<vid>" or
        # "<collection>_<vid>" (store.Location.base_path); take the
        # trailing id so a collection volume's reconstructions land on
        # the SAME heat key as its blob reads
        self.vid = os.path.basename(base).rsplit("_", 1)[-1]
        self.large_block = large_block
        self.small_block = small_block
        vif = ec_files.read_vif(base)
        self.version = vif.get("version", version) if vif else version
        # the volume's erasure code, from its .vif tag (pre-tag volumes
        # and missing .vif mean RS — no flag-day): geometry (k/n/alpha)
        # and the degraded-read survivor policy both key off this
        from seaweedfs_tpu.ops import codecs as _codecs
        self.spec = _codecs.parse_tag((vif or {}).get("codec"))
        self.codec_tag = self.spec.tag

        # replay any crash-left journal into the .ecx, as the reference
        # does at mount (RebuildEcxFile, ec_volume_delete.go:51-98)
        self._replay_ecj()

        self._ecx = open(base + ".ecx", "r+b")
        data = self._ecx.read()
        self.ids, self.offs, self.sizes = idxf.read_columns(data)

        self.shards: dict[int, object] = {}
        for i in range(self.spec.n):
            p = base + layout.to_ext(i)
            if os.path.exists(p):
                self.shards[i] = open(p, "rb")
        if self.shards:
            any_id = next(iter(self.shards))
            self.shard_size = os.path.getsize(base + layout.to_ext(any_id))
        else:
            self.shard_size = 0
        self.dat_size = ec_files.find_dat_file_size(base, self.version)

        # degraded-read engine state: per-stage counters for /metrics and
        # an LRU of reconstructed (shard, offset, size) ranges
        self.read_stats: dict[str, int] = {
            "local_shard_reads": 0, "remote_shard_reads": 0,
            "intervals_coalesced": 0, "reconstruct_batches": 0,
            "reconstruct_intervals": 0, "reconstruct_cache_hits": 0,
        }
        self._stats_lock = threading.Lock()
        self._recon_cache: OrderedDict[tuple[int, int, int], bytes] = \
            OrderedDict()
        self._recon_cache_bytes = 0
        self._recon_lock = threading.Lock()
        # scrub-verdicted corrupt byte ranges per shard: reads overlapping
        # a quarantined range treat the local shard as unreadable, so the
        # interval is served via reconstruction (never from the bad
        # bytes).  A rebuild + remount replaces the file AND this object,
        # which is what clears the quarantine.
        self._quarantine: dict[int, list[tuple[int, int]]] = {}
        self._quarantine_lock = threading.Lock()

    # -- index ---------------------------------------------------------

    def _replay_ecj(self) -> None:
        ecj = self.base + ".ecj"
        deleted = ec_files.read_ecj(ecj)
        if not deleted:
            return
        with open(self.base + ".ecx", "r+b") as f:
            data = f.read()
            ids, _, _ = idxf.read_columns(data)
            for nid in deleted:
                pos = int(np.searchsorted(ids, nid))
                if pos < len(ids) and ids[pos] == nid:
                    f.seek(pos * 16 + 12)
                    f.write(t.TOMBSTONE_FILE_SIZE.to_bytes(4, "big", signed=True))
        os.remove(ecj)

    def find_needle(self, needle_id: int) -> tuple[int, int]:
        """-> (dat_offset_bytes, size); raises KeyError if absent/deleted."""
        pos = int(np.searchsorted(self.ids, needle_id))
        if pos >= len(self.ids) or self.ids[pos] != needle_id:
            raise KeyError(f"needle {needle_id:x} not in ec volume")
        size = int(self.sizes[pos])
        if not t.size_is_valid(size):
            raise KeyError(f"needle {needle_id:x} deleted")
        return t.from_offset_units(int(self.offs[pos])), size

    def delete_needle(self, needle_id: int) -> None:
        """Tombstone in .ecx (in place) + append to the .ecj journal."""
        pos = int(np.searchsorted(self.ids, needle_id))
        if pos >= len(self.ids) or self.ids[pos] != needle_id:
            return
        self.sizes[pos] = t.TOMBSTONE_FILE_SIZE
        self._ecx.seek(pos * 16 + 12)
        self._ecx.write(t.TOMBSTONE_FILE_SIZE.to_bytes(4, "big", signed=True))
        self._ecx.flush()
        with open(self.base + ".ecj", "ab") as j:
            j.write(needle_id.to_bytes(8, "big"))

    # -- stats / cache --------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.read_stats[key] = self.read_stats.get(key, 0) + n

    def read_stats_snapshot(self) -> dict[str, int]:
        with self._stats_lock:
            return dict(self.read_stats)

    def _cache_get(self, key: tuple[int, int, int]) -> bytes | None:
        with self._recon_lock:
            data = self._recon_cache.get(key)
            if data is not None:
                self._recon_cache.move_to_end(key)
        return data

    def _cache_put(self, key: tuple[int, int, int], data: bytes) -> None:
        if RECONSTRUCT_CACHE_BYTES <= 0 or \
                len(data) > RECONSTRUCT_CACHE_BYTES:
            return
        with self._recon_lock:
            old = self._recon_cache.pop(key, None)
            if old is not None:
                self._recon_cache_bytes -= len(old)
            self._recon_cache[key] = data
            self._recon_cache_bytes += len(data)
            while self._recon_cache_bytes > RECONSTRUCT_CACHE_BYTES and \
                    self._recon_cache:
                _, ev = self._recon_cache.popitem(last=False)
                self._recon_cache_bytes -= len(ev)

    # -- quarantine ------------------------------------------------------

    def quarantine_range(self, shard_id: int, offset: int, size: int) -> None:
        """Mark [offset, offset+size) of one shard as corrupt: local reads
        of any overlapping range fail over to reconstruction.  Adjacent /
        overlapping ranges merge so the list stays small."""
        with self._quarantine_lock:
            ranges = self._quarantine.get(shard_id, [])
            ranges.append((offset, size))
            ranges.sort()
            merged: list[tuple[int, int]] = []
            for off, sz in ranges:
                if merged and off <= merged[-1][0] + merged[-1][1]:
                    lo, lsz = merged[-1]
                    merged[-1] = (lo, max(lsz, off + sz - lo))
                else:
                    merged.append((off, sz))
            self._quarantine[shard_id] = merged

    def _is_quarantined(self, shard_id: int, offset: int, size: int) -> bool:
        with self._quarantine_lock:
            ranges = self._quarantine.get(shard_id)
            if not ranges:
                return False
            return any(off < offset + size and offset < off + sz
                       for off, sz in ranges)

    def clear_quarantine(self, shard_id: int) -> None:
        """Forget a shard's quarantined ranges — called when the shard
        FILE is deleted (purged corrupt, or lost): the verdict named
        bytes in a file that no longer exists, and a freshly rebuilt or
        re-copied replacement must not inherit it."""
        with self._quarantine_lock:
            self._quarantine.pop(shard_id, None)

    def quarantine_snapshot(self) -> dict[str, list[list[int]]]:
        with self._quarantine_lock:
            return {str(sid): [[off, sz] for off, sz in ranges]
                    for sid, ranges in self._quarantine.items() if ranges}

    # -- reads ----------------------------------------------------------

    def _read_local(self, shard_id: int, offset: int, size: int) -> bytes | None:
        """Positional read on the shard fd: os.pread carries its own file
        offset, so concurrent interval reads of one EcVolume never race a
        shared seek position.  Quarantined (scrub-verdicted corrupt)
        ranges read as unreadable so every caller — the batched engine,
        survivor gathering, and peer shard_read — falls over to
        reconstruction instead of the bad bytes."""
        f = self.shards.get(shard_id)
        if f is None:
            return None
        if self._quarantine and self._is_quarantined(shard_id, offset, size):
            return None
        try:
            return os.pread(f.fileno(), size, offset)
        except OSError:
            return None

    def read_interval(self, shard_id: int, offset: int, size: int,
                      shard_reader: ShardReader | None = None) -> bytes:
        data = self._read_local(shard_id, offset, size)
        if data is not None and len(data) == size:
            self._bump("local_shard_reads")
            return data
        if shard_reader is not None:
            data = shard_reader(shard_id, offset, size)
            if data is not None and len(data) == size:
                self._bump("remote_shard_reads")
                return data
        return self._reconstruct_interval(shard_id, offset, size, shard_reader)

    def _reconstruct_interval(self, shard_id: int, offset: int, size: int,
                              shard_reader: ShardReader | None) -> bytes:
        """Per-interval repair (the serial baseline and the read_interval
        fallback): a reconstruction batch of one."""
        return self._reconstruct_ranges([(shard_id, offset, size)],
                                        shard_reader, use_cache=False)[0]

    def _read_segs_local(self, shard_id: int,
                         segs: list[tuple[int, int]]) -> bytes | None:
        """All (offset, size) segments of one shard, concatenated; None if
        the shard is absent or any segment reads short."""
        parts = []
        for off, size in segs:
            data = self._read_local(shard_id, off, size)
            if data is None or len(data) != size:
                return None
            parts.append(data)
        return b"".join(parts)

    def _gather_survivors(self, exclude: set[int],
                          segs: list[tuple[int, int]],
                          shard_reader: ShardReader | None,
                          want: list[int] | None = None,
                          need: int | None = None
                          ) -> dict[int, np.ndarray]:
        """Survivor rows covering every segment, local shards first, the
        remainder fanned out to peers in PARALLEL on the shared pool like
        the reference's recoverOneRemoteEcShardInterval
        (store_ec.go:349-382) — a serial walk would stack per-peer
        timeouts onto one degraded GET.

        `want` restricts reads to a codec-chosen basis (an LRC local
        group: the whole point of the code is touching <= r+1 shards on
        a single loss); `need` is how many rows suffice (defaults to
        len(want), else k).  Raises IOError when the floor is missed so
        the caller can retry unrestricted."""
        k = self.spec.k
        universe = want if want is not None else list(range(self.spec.n))
        need = need if need is not None else             (len(want) if want is not None else k)
        floor = min(need, k) if want is None else need
        pool = _read_pool()
        local = [i for i in universe
                 if i not in exclude and i in self.shards]
        results: dict[int, bytes] = {}
        if len(local) == 1:
            data = self._read_segs_local(local[0], segs)
            if data is not None:
                results[local[0]] = data
        elif local:
            futs = {pool.submit(self._read_segs_local, i, segs): i
                    for i in local}
            for fut in as_completed(futs):
                data = None if fut.exception() else fut.result()
                if data is not None:
                    results[futs[fut]] = data
                    if len(results) >= need:
                        break  # enough survivors: no wasted disk reads
            for fut in futs:
                fut.cancel()  # drop un-started stragglers
        self._bump("local_shard_reads", len(results) * len(segs))
        if len(results) < need and shard_reader is not None:
            short = need - len(results)
            remote = [i for i in universe
                      if i not in exclude and i not in results]
            # same-rack-first: when the reader exposes the planner's
            # locality ranking (volume_server._shard_reader), submission
            # order biases the first-k-responders race toward nearby
            # survivors — hedging and the k-early-exit stay untouched
            rank = getattr(shard_reader, "locality_rank", None)
            if rank is not None and len(remote) > 1:
                try:
                    remote.sort(key=lambda sid: (rank(sid), sid))
                except Exception:
                    pass  # ranking is advisory, never load-bearing

            def read_remote(sid: int) -> bytes | None:
                parts = []
                for off, size in segs:
                    data = shard_reader(sid, off, size)
                    if data is None or len(data) != size:
                        return None
                    parts.append(data)
                return b"".join(parts)

            # throwaway pool, like the reference's per-recover fan-out: a
            # stuck peer must stall THIS request at worst, never the
            # shared local-pread pool other degraded GETs ride
            rpool = ThreadPoolExecutor(
                max_workers=min(8, len(remote) or 1))
            try:
                futs = {rpool.submit(read_remote, i): i for i in remote}
                for fut in as_completed(futs):
                    data = None if fut.exception() else fut.result()
                    if data is not None:
                        results[futs[fut]] = data
                        self._bump("remote_shard_reads", len(segs))
                        short -= 1
                        if short <= 0:
                            break
            finally:
                # do NOT wait for stragglers: one blackholed peer must
                # not stall the degraded GET past the k fast responders
                rpool.shutdown(wait=False, cancel_futures=True)
        if len(results) < floor:
            raise IOError(
                f"ec volume {self.base}: only {len(results)} shards "
                f"readable, need {floor} to reconstruct "
                f"shard(s) {sorted(exclude)}")
        rows = {}
        for sid in sorted(results)[:need]:
            rows[sid] = np.frombuffer(results[sid], dtype=np.uint8)
        return rows

    def _reconstruct_ranges(self, ranges: list[tuple[int, int, int]],
                            shard_reader: ShardReader | None,
                            use_cache: bool = True) -> list[bytes]:
        """Rebuild several (shard_id, offset, size) ranges in ONE batched
        codec dispatch: each survivor's slices concatenate into a single
        row, the decode matmul runs once over the whole concatenation, and
        the rebuilt rows split back per range."""
        out: list[bytes | None] = [None] * len(ranges)
        todo: list[int] = []
        for idx, key in enumerate(ranges):
            data = self._cache_get(key) if use_cache else None
            if data is not None:
                out[idx] = data
                self._bump("reconstruct_cache_hits")
            else:
                todo.append(idx)
        if not todo:
            return out  # type: ignore[return-value]
        wanted = sorted({ranges[i][0] for i in todo})
        segs = [(ranges[i][1], ranges[i][2]) for i in todo]
        codec = ec_files._get_codec(tag=self.codec_tag)
        # MSR sub-packetization works on byte-interleaved alpha-blocks:
        # widen each segment to alpha boundaries, slice the lead back off
        # after the decode (alpha=1 for rs/lrc: no-op)
        a = self.spec.alpha
        leads = [0] * len(segs)
        gsegs = segs
        if a > 1:
            gsegs = []
            for i, (off, size) in enumerate(segs):
                leads[i] = off % a
                start = off - leads[i]
                end = off + size
                end += (-end) % a
                gsegs.append((start, end - start))
        # codec-chosen survivor basis: LRC single-loss repairs read one
        # local group (r+1 shards), MSR whole-file decode reads any k
        # whole files.  If a basis shard turns out unreadable, retry
        # unrestricted — non-MDS decodability is then re-judged by the
        # shell over whatever actually arrived.
        sel = getattr(codec, "decode_select", None) or \
            getattr(getattr(codec, "code", None), "decode_select", None)
        basis: list[int] | None = None
        if sel is not None:
            try:
                basis = list(sel(
                    sorted(set(range(self.spec.n)) - set(wanted)),
                    list(wanted)))
            except (ValueError, TypeError):
                basis = None
        with trace.span("ec.gather_survivors", shards_lost=len(wanted),
                        segs=len(gsegs)), \
                _pipeline.flow("ec_read").stage(
                    "gather_survivors",
                    nbytes=self.spec.k * sum(s for _, s in gsegs)):
            try:
                rows = self._gather_survivors(set(wanted), gsegs,
                                              shard_reader, want=basis)
            except IOError:
                if basis is None:
                    raise
                # one extra survivor beyond k keeps every <= tolerance-1
                # loss pattern decodable for LRC; harmless elsewhere
                extra = 1 if self.spec.family == "lrc" else 0
                rows = self._gather_survivors(
                    set(wanted), gsegs, shard_reader,
                    need=self.spec.k + extra)
        # one dispatch decodes every wanted shard over the WHOLE
        # concatenation even though each segment only consumes its own
        # shard's slice — deliberately: with f lost shards that wastes
        # (f-1)/f of the matmul OUTPUT (microseconds at KB batch sizes),
        # while splitting into per-shard dispatches multiplies the
        # per-call orchestration cost this engine exists to amortize
        with trace.span("ec.reconstruct_batch", intervals=len(todo),
                        shards=len(wanted),
                        bytes=sum(s for _, s in gsegs)), \
                _pipeline.flow("ec_read").stage(
                    "reconstruct", nbytes=sum(s for _, s in gsegs)):
            rebuilt = ec_files._reconstruct_batch(codec, rows, wanted)
        self._bump("reconstruct_batches")
        self._bump("reconstruct_intervals", len(todo))
        if heat.ambient_is_data():
            # a read that actually reconstructed: the expensive event
            # the per-volume degraded-read fraction in /cluster/heat
            # measures (canary/scrub/repair classes stay out).  Weight
            # 0: this is the SAME request the serving path's op=read
            # record counts — annotate it, don't count it twice
            heat.record("volume", self.vid, 0, "degraded", weight=0.0)
        pos = 0
        for i, idx in enumerate(todo):
            sid, off, size = ranges[idx]
            lead = leads[i]
            data = np.asarray(
                rebuilt[sid][pos + lead:pos + lead + size]).tobytes()
            pos += gsegs[i][1]
            out[idx] = data
            if use_cache:
                self._cache_put((sid, off, size), data)
        return out  # type: ignore[return-value]

    def _read_ranges(self, plan: list[tuple[int, int, int]],
                     shard_reader: ShardReader | None) -> list[bytes]:
        """The batched read engine: coalesce adjacent per-shard ranges,
        read all coalesced ranges concurrently (local then remote), and
        repair everything still missing in one reconstruction dispatch."""
        # coalesce: group the plan per shard, merge contiguous shard-file
        # ranges (a needle spanning whole stripe rows lands contiguous
        # blocks in each shard file), remembering how each original
        # interval slices back out of its merged read
        with trace.span("ec.coalesce", intervals=len(plan)) as csp:
            per_shard: dict[int, list[tuple[int, int, int]]] = {}
            for i, (sid, off, size) in enumerate(plan):
                per_shard.setdefault(sid, []).append((off, size, i))
            reads: list[list] = []  # [sid, off, size, [(idx, rel_off, sz)..]]
            for sid, lst in per_shard.items():
                lst.sort()
                cur: list | None = None
                for off, size, idx in lst:
                    if cur is not None and cur[1] + cur[2] == off:
                        cur[3].append((idx, cur[2], size))
                        cur[2] += size
                    else:
                        cur = [sid, off, size, [(idx, 0, size)]]
                        reads.append(cur)
            csp.set(reads=len(reads))
        if len(plan) > len(reads):
            self._bump("intervals_coalesced", len(plan) - len(reads))

        blobs: dict[int, bytes] = {}  # read index -> bytes
        failed: list[int] = []
        # reconstructed-range LRU first: a hot degraded needle skips shard
        # I/O entirely
        probe: list[int] = []
        for ri, (sid, off, size, _) in enumerate(reads):
            data = self._cache_get((sid, off, size))
            if data is not None:
                blobs[ri] = data
                self._bump("reconstruct_cache_hits")
            else:
                probe.append(ri)
        # local reads, concurrent when there is anything to overlap
        with trace.span("ec.local_pread", reads=len(probe)) as lsp, \
                _pipeline.flow("ec_read").stage(
                    "local_pread",
                    nbytes=sum(reads[ri][2] for ri in probe)):
            if len(probe) == 1:
                ri = probe[0]
                sid, off, size, _ = reads[ri]
                data = self._read_local(sid, off, size)
                if data is not None and len(data) == size:
                    blobs[ri] = data
                    self._bump("local_shard_reads")
                else:
                    failed.append(ri)
            elif probe:
                pool = _read_pool()
                futs = {pool.submit(self._read_local, *reads[ri][:3]): ri
                        for ri in probe}
                for fut in as_completed(futs):
                    ri = futs[fut]
                    data = None if fut.exception() else fut.result()
                    if data is not None and len(data) == reads[ri][2]:
                        blobs[ri] = data
                        self._bump("local_shard_reads")
                    else:
                        failed.append(ri)
            lsp.set(missed=len(failed))
        # remote fetch of whatever the local disks couldn't serve — on a
        # throwaway pool so a hung peer can't starve the shared pread
        # pool.  The wait is HEDGED (utils/resilience.py): after a
        # p99-informed delay, ranges still in flight are handed to
        # reconstruction from OTHER survivors — a slow-but-alive peer
        # then costs the hedge delay plus one decode, not its full
        # latency.  Completions that beat the cutoff feed the latency
        # tracker; abandoned fetches do not (they would teach the
        # tracker that slow is normal and quietly disable hedging).
        pending: dict = {}  # abandoned primary future -> read index
        if failed and shard_reader is not None:
            still: list[int] = []
            hedge_s = resilience.hedge_delay_s()

            def timed_fetch(sid: int, off: int, size: int):
                t0 = time.perf_counter()
                return shard_reader(sid, off, size), \
                    time.perf_counter() - t0

            def collect(fut, ri) -> None:
                res = None if fut.exception() else fut.result()
                data = res[0] if res else None
                if data is not None and len(data) == reads[ri][2]:
                    blobs[ri] = data
                    self._bump("remote_shard_reads")
                    if hedge_s is not None:
                        # only completions that BEAT a hedge cutoff may
                        # teach the tracker: with hedging off there is
                        # no cutoff, and feeding unfiltered (possibly
                        # slow-peer) latencies here would raise the
                        # hedge delay toward exactly the latency it
                        # exists to cut
                        resilience.SHARD_FETCH.observe(res[1])
                else:
                    still.append(ri)

            with trace.span("ec.remote_fetch", reads=len(failed),
                            hedge_ms=None if hedge_s is None else
                            round(hedge_s * 1000.0, 1)) as rsp, \
                    _pipeline.flow("ec_read").stage(
                        "remote_fetch",
                        nbytes=sum(reads[ri][2] for ri in failed)):
                rpool = ThreadPoolExecutor(max_workers=min(8, len(failed)))
                futs = {rpool.submit(timed_fetch, *reads[ri][:3]): ri
                        for ri in failed}
                try:
                    if hedge_s is None:
                        for fut in as_completed(futs):
                            collect(fut, futs[fut])
                    else:
                        done, not_done = _futures_wait(set(futs),
                                                       timeout=hedge_s)
                        for fut in done:
                            collect(fut, futs[fut])
                        if not_done:
                            from seaweedfs_tpu.stats import metrics
                            metrics.HEDGE_TOTAL.labels("fired").inc()
                            for fut in not_done:
                                pending[fut] = futs[fut]
                                still.append(futs[fut])
                finally:
                    # when hedging left primaries in flight, do NOT
                    # cancel them: reconstruction may find too few
                    # survivors and need to fall back to whichever
                    # primary eventually answers
                    rpool.shutdown(wait=False,
                                   cancel_futures=not pending)
                rsp.set(missed=len(still))
            failed = still
        # one-shot batched reconstruction of every range still missing
        if failed:
            failed.sort()
            keys = [tuple(reads[ri][:3]) for ri in failed]
            try:
                rebuilt = self._reconstruct_ranges(keys, shard_reader)
            except IOError:
                if not pending:
                    raise
                # the hedge lost its bet — too few survivors to decode —
                # so the abandoned primary fetches are the only source
                # left: wait them out (deadline-bounded) and decode
                # whatever still misses afterwards
                from seaweedfs_tpu.stats import metrics
                metrics.HEDGE_TOTAL.labels("primary_rescued").inc()
                try:
                    for fut in as_completed(
                            list(pending),
                            timeout=resilience.clamp_timeout(30.0)):
                        ri = pending[fut]
                        res = None if fut.exception() else fut.result()
                        data = res[0] if res else None
                        if data is not None and len(data) == reads[ri][2]:
                            blobs[ri] = data
                            self._bump("remote_shard_reads")
                except (_FutTimeout, TimeoutError):
                    pass
                failed = [ri for ri in failed if ri not in blobs]
                rebuilt = self._reconstruct_ranges(
                    [tuple(reads[ri][:3]) for ri in failed],
                    shard_reader) if failed else []
            else:
                if pending:
                    from seaweedfs_tpu.stats import metrics
                    metrics.HEDGE_TOTAL.labels("hedge_won").inc()
            for ri, data in zip(failed, rebuilt):
                blobs[ri] = data
        parts: list[bytes | None] = [None] * len(plan)
        for ri, (_, _, _, members) in enumerate(reads):
            blob = blobs[ri]
            for idx, rel, size in members:
                parts[idx] = blob[rel:rel + size]
        return parts  # type: ignore[return-value]

    def read_needle(self, needle_id: int,
                    shard_reader: ShardReader | None = None,
                    mode: str | None = None,
                    skip_shards: frozenset | None = None) -> ndl.Needle:
        """Full needle read: locate -> plan all intervals -> batched shard
        reads + one-shot reconstruction -> parse.  `mode` (or
        WEEDTPU_EC_READ) = "serial" restores the per-interval loop.

        `skip_shards` withholds those shards from BOTH the local files
        and the remote reader, forcing the read through reconstruction —
        the canary prober's deliberate degraded read.  Implemented as a
        shallow view sharing fds/index/caches/stats with self (the
        reconstruction cache is keyed by range, so results are identical
        whichever survivors produced them), never mutating this volume."""
        if skip_shards:
            import copy as _copy
            skip = frozenset(skip_shards)
            view = _copy.copy(self)
            view.shards = {s: f for s, f in self.shards.items()
                           if s not in skip}
            # the view must NOT share the reconstruction-range LRU: a
            # cache hit would serve the probe without touching the
            # decode path (defeating a canary that exists to exercise
            # it), and probe results must not displace real entries
            view._recon_cache = OrderedDict()
            view._recon_cache_bytes = 0
            view._recon_lock = threading.Lock()
            inner = shard_reader

            def skipping_reader(sid: int, off: int, size: int):
                if sid in skip or inner is None:
                    return None
                return inner(sid, off, size)

            rank = getattr(inner, "locality_rank", None)
            if rank is not None:
                skipping_reader.locality_rank = rank
            return view.read_needle(needle_id, skipping_reader, mode)
        with trace.span("ec.plan", needle=f"{needle_id:x}") as psp:
            dat_offset, size = self.find_needle(needle_id)
            length = t.actual_size(size, self.version)
            intervals = layout.locate_data(
                self.large_block, self.small_block, self.dat_size,
                dat_offset, length, data_shards=self.spec.k)
            plan = []
            for iv in intervals:
                sid, off = iv.to_shard_id_and_offset(self.large_block,
                                                     self.small_block)
                plan.append((sid, off, iv.size))
            psp.set(intervals=len(plan), bytes=length)
        mode = mode or os.environ.get("WEEDTPU_EC_READ", "batched")
        if mode == "serial":
            parts = [self.read_interval(sid, off, size, shard_reader)
                     for sid, off, size in plan]
        else:
            parts = self._read_ranges(plan, shard_reader)
        record = b"".join(parts)
        n = ndl.Needle.from_record(record, self.version)
        if n.id != needle_id:
            raise IOError(f"ec read returned needle {n.id:x}, wanted {needle_id:x}")
        return n

    def shard_ids(self) -> list[int]:
        return sorted(self.shards)

    def close(self) -> None:
        self._ecx.close()
        for f in self.shards.values():
            f.close()
