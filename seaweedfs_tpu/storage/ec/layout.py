"""EC striping layout: how a volume .dat maps onto 14 shard files.

Semantics match the reference exactly (weed/storage/erasure_coding/
ec_locate.go, ec_encoder.go:17-23, encodeDatFile loop at :198-235) so shard
files interoperate:

- The .dat is consumed row-major. While more than one large row
  (10 x 1GB) remains, a large row is cut into 10 large blocks; the rest is
  cut into rows of 10 small (1MB) blocks, the final row zero-padded.
- Shard j's file = its large blocks in row order, then its small blocks.
- Parity shards 10..13 hold the RS parity of each row, same block sizes.

This is the system's "sequence sharding": a needle read touches only the
block(s) its byte range lands in, while encode streams sequentially.
"""

from __future__ import annotations

from dataclasses import dataclass

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS
# existence-scan ceiling for shard files of ANY registered codec
# (msr_9_16 writes .ec17); pure filesystem probes use this instead of
# TOTAL_SHARDS so a node holding only high shards still finds them
MAX_TOTAL_SHARDS = 32
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1GB
SMALL_BLOCK_SIZE = 1024 * 1024  # 1MB


def to_ext(shard_id: int) -> str:
    return f".ec{shard_id:02d}"


@dataclass(frozen=True)
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows: int
    # stripe width: k of the volume's codec (RS default; LRC shares the
    # same 10-wide geometry, MSR volumes stripe 9-wide)
    data_shards: int = DATA_SHARDS

    def to_shard_id_and_offset(self, large_block: int = LARGE_BLOCK_SIZE,
                               small_block: int = SMALL_BLOCK_SIZE) -> tuple[int, int]:
        """(shard_id, offset inside that shard's file)."""
        off = self.inner_block_offset
        row = self.block_index // self.data_shards
        if self.is_large_block:
            off += row * large_block
        else:
            off += self.large_block_rows * large_block + row * small_block
        return self.block_index % self.data_shards, off


def n_large_rows(dat_size: int, large_block: int = LARGE_BLOCK_SIZE,
                 small_block: int = SMALL_BLOCK_SIZE,
                 data_shards: int = DATA_SHARDS) -> int:
    """Number of 10-wide large-block rows for a volume of dat_size bytes.

    Exactly matches the encode loop's strict `remaining > 10*large`
    condition: rows are cut while MORE than one large row remains.

    Deliberate deviation: the reference derives this as
    `(datSize + 10*small) // (10*large)` (ec_locate.go:19-20), which
    disagrees with its own encode loop whenever the trailing small-row
    region is larger than 10*(large-small) bytes — reads in that window
    would misroute. We stay loop-consistent for every size instead; for
    sizes outside that window the two formulas agree."""
    del small_block  # kept in the signature for call-site symmetry
    row = large_block * data_shards
    if dat_size <= row:
        return 0
    return (dat_size - 1) // row


def n_small_rows(dat_size: int, large_block: int = LARGE_BLOCK_SIZE,
                 small_block: int = SMALL_BLOCK_SIZE,
                 data_shards: int = DATA_SHARDS) -> int:
    remaining = dat_size - \
        n_large_rows(dat_size, large_block, small_block, data_shards) \
        * large_block * data_shards
    return max(0, -(-remaining // (small_block * data_shards)))


def shard_file_size(dat_size: int, large_block: int = LARGE_BLOCK_SIZE,
                    small_block: int = SMALL_BLOCK_SIZE,
                    data_shards: int = DATA_SHARDS) -> int:
    """Size of each .ecXX file for a volume of dat_size bytes."""
    return n_large_rows(dat_size, large_block, small_block, data_shards) \
        * large_block + \
        n_small_rows(dat_size, large_block, small_block, data_shards) \
        * small_block


def locate_offset(large_block: int, small_block: int, dat_size: int,
                  offset: int,
                  data_shards: int = DATA_SHARDS) -> tuple[int, bool, int]:
    """-> (block_index, is_large_block, inner_block_offset)."""
    large_row = large_block * data_shards
    rows = n_large_rows(dat_size, large_block, small_block, data_shards)
    if offset < rows * large_row:
        return int(offset // large_block), True, int(offset % large_block)
    offset -= rows * large_row
    return int(offset // small_block), False, int(offset % small_block)


def locate_data(large_block: int, small_block: int, dat_size: int,
                offset: int, size: int,
                data_shards: int = DATA_SHARDS) -> list[Interval]:
    """Map a logical .dat byte range to the shard-block intervals covering it."""
    block_index, is_large, inner = locate_offset(
        large_block, small_block, dat_size, offset, data_shards)
    rows = n_large_rows(dat_size, large_block, small_block, data_shards)
    out: list[Interval] = []
    while size > 0:
        remaining = (large_block if is_large else small_block) - inner
        step = min(size, remaining)
        out.append(Interval(block_index, inner, step, is_large, rows,
                            data_shards))
        size -= step
        if size <= 0:
            break
        block_index += 1
        if is_large and block_index == rows * data_shards:
            is_large = False
            block_index = 0
        inner = 0
    return out
